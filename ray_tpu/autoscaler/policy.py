"""Slice-aware scale policy: the autoscaler's demand + placement brain.

Parity: the reference autoscaler v2's demand calculator
(`python/ray/autoscaler/v2/scheduler.py` — ResourceDemandScheduler's
"which node types, how many" answer) specialized for a TPU cluster where
node types are SLICE-SHAPED (a v5p host contributes its chips as one
atomic inventory unit, launcher.py NodeTypeSpec) and demand has three
extra sources beyond the queued-task view:

  * queued-beyond-quota leases — the head's job ledger refuses a charge
    and the lease parks; whether that parked work should attract new
    nodes is policy (`autoscaler_quota_demand`): quotas here are
    admission ceilings (Borg-style), so by default parked work still
    counts as demand and the ceiling re-checks against the grown
    cluster's shares;
  * explicit scale requests — the elastic trainer's capacity-wait
    (train/trainer.py) and any worker-side `request("scale_up", ...)`
    land in the head's scale-request queue and are drained here;
  * serve shed rate — `ray_tpu_serve_shed_total` climbing faster than
    `autoscaler_shed_rate_threshold`/s over `autoscaler_shed_window_s`
    means admission control is rejecting traffic the cluster could
    absorb with another replica's worth of capacity.

Placement is a best-fit-decreasing pack over slice-shaped node types
(fewest wasted TPU chips first, then CPUs), replacing the reconciler's
one-launch-per-unmet-request first fit — without the pack, 4 queued
1-chip tasks launch 4 hosts where one 4-chip host suffices.
"""

from __future__ import annotations

import time


def _shed_total(rt) -> float:
    """Cluster-wide `ray_tpu_serve_shed_total` right now: the head's own
    registry plus every live worker's shipped snapshot (replica processes
    shed; their counters ride the event-flush metric deltas)."""
    total = 0.0
    try:
        from ray_tpu.util.metrics import _LOCK, _REGISTRY
        with _LOCK:
            m = _REGISTRY.get("ray_tpu_serve_shed_total")
        if m is not None:
            with m._lock:
                total += sum(m._values.values())
        for per in rt.worker_metric_snapshots().values():
            snap = per.get("ray_tpu_serve_shed_total")
            if snap:
                total += sum(snap.get("values", {}).values())
    except Exception:  # noqa: BLE001 — a torn scrape must not stop scaling
        pass
    return total


class ScalePolicy:
    """Stateless-ish demand/placement policy consulted by the reconciler
    each tick. Holds only the shed-rate window samples."""

    def __init__(self, rt, cfg=None):
        self.rt = rt
        self.cfg = cfg or rt.config
        self._shed_samples: list[tuple[float, float]] = []  # (ts, total)

    # ---- demand sources beyond the queued-task view ----

    def extra_demand(self) -> list[dict]:
        demand: list[dict] = []
        take = getattr(self.rt, "take_scale_requests", None)
        if take is not None:
            for req in take():
                demand.extend(dict(b) for b in req.get("bundles", []) if b)
        demand.extend(self._shed_demand())
        return demand

    def _shed_demand(self) -> list[dict]:
        """One replica-shaped bundle per threshold-crossing of the serve
        shed rate. TPU-shaped when the cluster serves on TPU (any node
        advertises chips), CPU-shaped otherwise."""
        window = getattr(self.cfg, "autoscaler_shed_window_s", 30.0)
        threshold = getattr(self.cfg, "autoscaler_shed_rate_threshold", 1.0)
        if threshold <= 0:
            return []
        now = time.monotonic()
        total = _shed_total(self.rt)
        self._shed_samples.append((now, total))
        while (len(self._shed_samples) > 2
               and self._shed_samples[1][0] <= now - window):
            self._shed_samples.pop(0)
        t0, v0 = self._shed_samples[0]
        if now - t0 < 1e-3 or total <= v0:
            return []
        rate = (total - v0) / (now - t0)
        if rate < threshold:
            return []
        has_tpu = any(n["resources"].get("TPU", 0) > 0
                      for n in self.rt.nodes_table() if n["alive"])
        return [{"CPU": 1.0, "TPU": 1.0} if has_tpu else {"CPU": 1.0}]

    # ---- queued-demand quota classification ----

    def include_queued(self, job_id: str, req: dict) -> bool:
        """Should this queued task count toward scale-up demand? A task
        parked by its own job's quota only counts when
        `autoscaler_quota_demand` says ceilings re-check against the
        grown cluster; capacity-starved tasks always count."""
        jobs = getattr(self.rt, "jobs", None)
        if jobs is None or jobs.would_admit(job_id, req):
            return True
        return bool(getattr(self.cfg, "autoscaler_quota_demand", True))

    # ---- slice-aware placement ----

    def plan_launches(self, unmet: list[dict], node_types: dict,
                      counts: dict) -> list[str]:
        """Pack unmet demand into the fewest slice-shaped launches.
        Best-fit decreasing: biggest requests place first, each into an
        already-planned launch when it fits, else onto the node type
        wasting the fewest TPU chips (then CPUs). Returns node-type names
        to launch, one entry per node; `counts` caps against
        max_workers and is NOT mutated."""
        planned: list[tuple[str, dict]] = []  # (tname, remaining avail)
        budget = {t: max(0, c.max_workers - counts.get(t, 0))
                  for t, c in node_types.items()}
        order = sorted(unmet, key=lambda r: (-r.get("TPU", 0.0),
                                             -r.get("CPU", 0.0)))
        for req in order:
            placed = False
            for _, avail in planned:
                if _fits(avail, req):
                    _sub(avail, req)
                    placed = True
                    break
            if placed:
                continue
            best = None
            for tname, tcfg in node_types.items():
                if budget.get(tname, 0) <= 0:
                    continue
                res = dict(tcfg.resources)
                if not _fits(res, req):
                    continue
                waste = (res.get("TPU", 0.0) - req.get("TPU", 0.0),
                         res.get("CPU", 0.0) - req.get("CPU", 0.0))
                if best is None or waste < best[0]:
                    best = (waste, tname, res)
            if best is None:
                continue  # nothing fits (or everything is at max_workers)
            _, tname, res = best
            budget[tname] -= 1
            _sub(res, req)
            planned.append((tname, res))
        return [t for t, _ in planned]


def _fits(avail: dict, req: dict) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in req.items())


def _sub(avail: dict, req: dict) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v
