"""TPU-VM slice provider for the autoscaler: ICI-topology-aware scale-up.

Parity/lineage: generalizes the reference's TPU pod accessories
(`python/ray/_private/accelerators/tpu.py` — `TPU-{type}-head` pod-slice
resource at `tpu.py:422`, chips-per-host facts at `tpu.py:46-60`) into the
scheduler-facing autoscaler itself, per SURVEY §7 item 11: demand for an
``ICI_CONTIGUOUS`` placement group of N chips launches the SMALLEST slice
type that holds N chips, as a gang of per-host nodes that register with
contiguous ids (registration order ~ ICI order, which is what the
ICI_CONTIGUOUS packer walks).

The cloud surface is a mockable API object (``create_slice``/
``delete_slice``); production would implement it against the GCE TPU-VM
API, tests inject ``LocalSliceAPI`` which "launches" a slice by spawning
one local node agent per host (the fake-multinode trick).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid

from ray_tpu.autoscaler import NodeProvider

# chips per host by generation (reference tpu.py:46-60: v2/v3/v4/v5p are
# 4-chip hosts; v5litepod/v6e are 8-chip hosts) and the slice sizes (in
# chips) each generation ships.
GENERATIONS = {
    "v4": {"chips_per_host": 4,
           "sizes": (4, 8, 16, 32, 64, 128, 256, 512)},
    "v5p": {"chips_per_host": 4,
            "sizes": (4, 8, 16, 32, 64, 128, 256, 512)},
    "v5litepod": {"chips_per_host": 8,
                  "sizes": (1, 4, 8, 16, 32, 64, 128, 256)},
    "v6e": {"chips_per_host": 8,
            "sizes": (1, 4, 8, 16, 32, 64, 128, 256)},
}


def pick_slice_type(generation: str, n_chips: int) -> str | None:
    """Smallest slice of `generation` with >= n_chips chips, e.g.
    pick_slice_type("v5litepod", 12) -> "v5litepod-16"."""
    gen = GENERATIONS.get(generation)
    if gen is None:
        return None
    for size in gen["sizes"]:
        if size >= n_chips:
            return f"{generation}-{size}"
    return None


def slice_hosts(accelerator_type: str) -> list[dict]:
    """Host layout of a slice: per-host resources incl. the
    `TPU-{type}-head` marker on worker 0 (reference tpu.py:422)."""
    generation, _, chips_s = accelerator_type.rpartition("-")
    chips = int(chips_s)
    per_host = GENERATIONS[generation]["chips_per_host"]
    n_hosts = max(1, (chips + per_host - 1) // per_host)
    hosts = []
    for i in range(n_hosts):
        res = {"TPU": float(min(per_host, chips - i * per_host))}
        if i == 0:
            res[f"TPU-{accelerator_type}-head"] = 1.0
        hosts.append(res)
    return hosts


class LocalSliceAPI:
    """Mock cloud API: a slice is a set of local node agents (the
    reference's fake-multinode pattern). Production swaps this for a GCE
    TPU-VM client with the same two calls."""

    def __init__(self, runtime):
        self.rt = runtime
        self.address = runtime.enable_cluster()
        self.procs: dict[str, list[subprocess.Popen]] = {}

    def create_slice(self, name: str, accelerator_type: str) -> list[str]:
        """Returns the hex node ids of the slice's hosts (in ICI order)."""
        node_ids = []
        procs = []
        env = dict(os.environ)
        env.update(self.rt.config.to_env())
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = (os.path.dirname(pkg) + os.pathsep
                             + env.get("PYTHONPATH", ""))
        for host_res in slice_hosts(accelerator_type):
            node_id = uuid.uuid4().hex[:16]
            res = dict(host_res)
            tpus = res.pop("TPU", 0)
            cmd = [sys.executable, "-m", "ray_tpu.core.node_agent",
                   "--head", self.address,
                   "--num-cpus", "1", "--num-tpus", str(tpus),
                   "--resources", json.dumps(res),
                   "--node-id", node_id]
            log = os.path.join(self.rt.session_dir, "logs",
                               f"slice-{name}-{node_id[:8]}.out")
            with open(log, "ab") as f:
                procs.append(subprocess.Popen(
                    cmd, env=env, stdout=f, stderr=subprocess.STDOUT))
            node_ids.append(node_id)
        self.procs[name] = procs
        return node_ids

    def delete_slice(self, name: str):
        for proc in self.procs.pop(name, []):
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


class TPUSliceProvider(NodeProvider):
    """Slice-granular provider: create/terminate whole TPU slices.

    Also serves plain per-node launches (NodeProvider surface) by treating
    a node type's "TPU" resource as a single-host slice request.
    """

    def __init__(self, runtime=None, api=None, generation="v5litepod"):
        from ray_tpu.core.runtime import get_runtime
        self.rt = runtime or get_runtime()
        self.api = api or LocalSliceAPI(self.rt)
        self.generation = generation
        self.slices: dict[str, list[str]] = {}   # slice name -> node ids
        self._node_slice: dict[str, str] = {}    # node id -> slice name

    # -- slice surface (used by the autoscaler's PG fast path) --

    def launch_slice(self, n_chips: int, timeout: float = 120.0) -> str:
        """Launch the smallest slice holding n_chips; blocks until every
        host registered. Returns the slice name."""
        accel = pick_slice_type(self.generation, n_chips)
        if accel is None:
            raise ValueError(
                f"no {self.generation} slice holds {n_chips} chips")
        name = f"{accel}-{uuid.uuid4().hex[:8]}"
        node_ids = self.api.create_slice(name, accel)
        deadline = time.monotonic() + timeout
        pending = set(node_ids)
        while pending and time.monotonic() < deadline:
            alive = {n["node_id"] for n in self.rt.nodes_table()
                     if n["alive"]}
            pending -= alive
            if pending:
                time.sleep(0.1)
        if pending:
            self.api.delete_slice(name)
            raise TimeoutError(
                f"slice {name}: {len(pending)} hosts never registered")
        self.slices[name] = node_ids
        for nid in node_ids:
            self._node_slice[nid] = name
        return name

    def terminate_slice(self, name: str):
        for nid in self.slices.pop(name, []):
            self._node_slice.pop(nid, None)
        self.api.delete_slice(name)

    # -- NodeProvider surface --

    def create_node(self, node_type: str, resources: dict) -> str:
        name = self.launch_slice(int(resources.get("TPU", 1) or 1))
        return self.slices[name][0]

    def terminate_node(self, node_id_hex: str):
        # TPU slices are atomic: terminating any host releases the slice.
        name = self._node_slice.get(node_id_hex)
        if name is not None:
            self.terminate_slice(name)
