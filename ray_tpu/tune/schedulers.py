"""Trial schedulers: FIFO, ASHA, HyperBand, median stopping, PBT, PB2.

Parity: reference `tune/schedulers/` — `async_hyperband.py` (ASHA:
asynchronous successive halving with rungs at r*eta^k, stop a trial at a
rung if its metric is below the rung's top-1/eta quantile),
`hyperband.py` (bracketed successive halving), `median_stopping_rule.py`,
`pbt.py` (PopulationBasedTraining: at each perturbation interval,
bottom-quantile trials clone a top-quantile trial's checkpoint with mutated
hyperparams) and PB2 (`pb2.py`: PBT with a GP-bandit picking the exploit
config instead of random perturbation).
"""

from __future__ import annotations

import math
import random
from typing import Any

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial, metrics: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, *, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        # rung milestones: grace * eta^k up to max_t
        self.rungs: list[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung value histories: {milestone: [metric, ...]}
        self._recorded: dict[int, list[float]] = {r: [] for r in self.rungs}

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        val = metrics.get(self.metric)
        if t is None or val is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # ran to completion budget
        decision = CONTINUE
        for rung in reversed(self.rungs):
            if t < rung:
                continue
            recorded = self._recorded[rung]
            if rung in trial.rungs_hit:
                break  # already judged at this rung
            trial.rungs_hit.add(rung)
            recorded.append(val if self.mode == "max" else -val)
            recorded.sort(reverse=True)
            k = max(1, len(recorded) // self.eta)
            cutoff = recorded[k - 1]
            mine = val if self.mode == "max" else -val
            if len(recorded) >= self.eta and mine < cutoff:
                decision = STOP
            break
        return decision


class PopulationBasedTraining:
    def __init__(self, *, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 seed: int | None = None):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._latest: dict[Any, tuple[float, Any]] = {}  # trial id -> (score, trial)

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        val = metrics.get(self.metric)
        if t is None or val is None:
            return CONTINUE
        score = val if self.mode == "max" else -val
        self._latest[trial.id] = (score, trial)
        if t - trial.last_perturb < self.interval:
            return CONTINUE
        trial.last_perturb = t
        ranked = sorted(self._latest.values(), key=lambda x: x[0])
        n = len(ranked)
        if n < 2:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = [tr for _s, tr in ranked[:k]]
        top = [tr for _s, tr in ranked[-k:]]
        if trial in bottom:
            donor = self._rng.choice(top)
            if donor is not trial and donor.latest_checkpoint:
                trial.exploit_from = donor
                return "EXPLOIT"
        return CONTINUE

    def mutate(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            else:  # Domain
                out[key] = spec.sample(self._rng)
            # Standard PBT: either resample (above) or perturb 0.8x/1.2x.
            if isinstance(out.get(key), (int, float)) and \
                    self._rng.random() < 0.5 and key in config \
                    and isinstance(config[key], (int, float)):
                out[key] = config[key] * self._rng.choice([0.8, 1.2])
        return out


class MedianStoppingRule:
    """Stop a trial whose running-average metric falls below the median of
    the running averages every other trial had reached by the same step
    (parity: tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, *, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        # trial id -> list of (t, score)
        self._hist: dict[Any, list[tuple[float, float]]] = {}

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        val = metrics.get(self.metric)
        if t is None or val is None:
            return CONTINUE
        score = val if self.mode == "max" else -val
        self._hist.setdefault(trial.id, []).append((t, score))
        if t < self.grace:
            return CONTINUE
        # running average of this trial up to t
        mine = [s for tt, s in self._hist[trial.id] if tt <= t]
        my_avg = sum(mine) / len(mine)
        others = []
        for tid, hist in self._hist.items():
            if tid == trial.id:
                continue
            upto = [s for tt, s in hist if tt <= t]
            if upto:
                others.append(sum(upto) / len(upto))
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        return STOP if my_avg < median else CONTINUE


class HyperBandScheduler:
    """Bracketed successive halving (parity: tune/schedulers/hyperband.py,
    asynchronous flavor): each new trial joins the bracket with the fewest
    members; bracket s uses grace period r*eta^s, so different brackets
    trade exploration breadth against per-trial budget. Within a bracket,
    rung decisions are ASHA cutoffs."""

    def __init__(self, *, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # one bracket per grace period eta^s < max_t (integer loop — a
        # float log would drop the top bracket at exact powers of eta)
        self._brackets = []
        grace = 1
        while grace < max_t:
            self._brackets.append(ASHAScheduler(
                metric=metric, mode=mode, time_attr=time_attr, max_t=max_t,
                grace_period=grace, reduction_factor=reduction_factor))
            grace *= reduction_factor
        if not self._brackets:
            self._brackets.append(ASHAScheduler(
                metric=metric, mode=mode, time_attr=time_attr, max_t=max_t,
                grace_period=1, reduction_factor=reduction_factor))
        self._members: dict[Any, int] = {}
        self._counts = [0] * len(self._brackets)

    def on_result(self, trial, metrics: dict) -> str:
        b = self._members.get(trial.id)
        if b is None:
            b = self._counts.index(min(self._counts))
            self._members[trial.id] = b
            self._counts[b] += 1
        bracket = self._brackets[b]
        if bracket.metric is None:
            bracket.metric = self.metric
        return bracket.on_result(trial, metrics)


class PB2(PopulationBasedTraining):
    """PBT with GP-guided exploration (parity: tune/schedulers/pb2.py):
    instead of random 0.8x/1.2x perturbation, `mutate` fits an RBF GP to
    (hyperparam-vector -> latest score) over the population's history and
    picks the candidate maximizing a UCB acquisition inside the
    hyperparam_bounds box."""

    def __init__(self, *, hyperparam_bounds: dict | None = None,
                 ucb_kappa: float = 1.5, n_candidates: int = 128, **kw):
        # PB2 takes bounds (continuous box), not mutation distributions.
        super().__init__(hyperparam_mutations=None, **kw)
        self.bounds = hyperparam_bounds or {}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self._gp_obs: list[tuple[list[float], float]] = []

    def _vec(self, config) -> list[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def on_result(self, trial, metrics: dict) -> str:
        val = metrics.get(self.metric)
        if val is not None and self.bounds:
            score = val if self.mode == "max" else -val
            self._gp_obs.append((self._vec(trial.config), score))
            if len(self._gp_obs) > 512:
                self._gp_obs = self._gp_obs[-512:]
        return super().on_result(trial, metrics)

    def mutate(self, config: dict) -> dict:
        out = dict(config)
        if not self.bounds:
            return out
        cands = []
        for _ in range(self.n_candidates):
            c = {}
            for k, (lo, hi) in self.bounds.items():
                base = float(config.get(k, (lo + hi) / 2))
                if self._rng.random() < 0.5:  # local jitter around donor
                    span = (hi - lo) * 0.1
                    c[k] = min(hi, max(lo, base + self._rng.gauss(0, span)))
                else:
                    c[k] = lo + self._rng.random() * (hi - lo)
            cands.append(c)
        if len(self._gp_obs) < 4:
            pick = self._rng.choice(cands)
            out.update(pick)
            return out
        import numpy as np
        X = np.array([x for x, _ in self._gp_obs])
        y = np.array([s for _, s in self._gp_obs], dtype=float)
        y = (y - y.mean()) / (y.std() or 1.0)
        ls = 0.25
        K = np.exp(-0.5 * ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
                   / ls ** 2) + 1e-5 * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            out.update(self._rng.choice(cands))
            return out
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
        Xc = np.array([self._vec(c) for c in cands])
        Kc = np.exp(-0.5 * ((Xc[:, None, :] - X[None, :, :]) ** 2).sum(-1)
                    / ls ** 2)
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        ucb = mu + self.kappa * np.sqrt(var)
        out.update(cands[int(np.argmax(ucb))])
        return out
