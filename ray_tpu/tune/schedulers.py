"""Trial schedulers: FIFO, ASHA, PBT.

Parity: reference `tune/schedulers/` — `async_hyperband.py` (ASHA:
asynchronous successive halving with rungs at r*eta^k, stop a trial at a
rung if its metric is below the rung's top-1/eta quantile) and `pbt.py`
(PopulationBasedTraining: at each perturbation interval, bottom-quantile
trials clone a top-quantile trial's checkpoint with mutated hyperparams).
"""

from __future__ import annotations

import random
from typing import Any

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial, metrics: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, *, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        # rung milestones: grace * eta^k up to max_t
        self.rungs: list[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung value histories: {milestone: [metric, ...]}
        self._recorded: dict[int, list[float]] = {r: [] for r in self.rungs}

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        val = metrics.get(self.metric)
        if t is None or val is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # ran to completion budget
        decision = CONTINUE
        for rung in reversed(self.rungs):
            if t < rung:
                continue
            recorded = self._recorded[rung]
            if rung in trial.rungs_hit:
                break  # already judged at this rung
            trial.rungs_hit.add(rung)
            recorded.append(val if self.mode == "max" else -val)
            recorded.sort(reverse=True)
            k = max(1, len(recorded) // self.eta)
            cutoff = recorded[k - 1]
            mine = val if self.mode == "max" else -val
            if len(recorded) >= self.eta and mine < cutoff:
                decision = STOP
            break
        return decision


class PopulationBasedTraining:
    def __init__(self, *, metric: str | None = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 seed: int | None = None):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._latest: dict[Any, tuple[float, Any]] = {}  # trial id -> (score, trial)

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        val = metrics.get(self.metric)
        if t is None or val is None:
            return CONTINUE
        score = val if self.mode == "max" else -val
        self._latest[trial.id] = (score, trial)
        if t - trial.last_perturb < self.interval:
            return CONTINUE
        trial.last_perturb = t
        ranked = sorted(self._latest.values(), key=lambda x: x[0])
        n = len(ranked)
        if n < 2:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = [tr for _s, tr in ranked[:k]]
        top = [tr for _s, tr in ranked[-k:]]
        if trial in bottom:
            donor = self._rng.choice(top)
            if donor is not trial and donor.latest_checkpoint:
                trial.exploit_from = donor
                return "EXPLOIT"
        return CONTINUE

    def mutate(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            else:  # Domain
                out[key] = spec.sample(self._rng)
            # Standard PBT: either resample (above) or perturb 0.8x/1.2x.
            if isinstance(out.get(key), (int, float)) and \
                    self._rng.random() < 0.5 and key in config \
                    and isinstance(config[key], (int, float)):
                out[key] = config[key] * self._rng.choice([0.8, 1.2])
        return out
