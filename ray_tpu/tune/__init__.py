"""ray_tpu.tune: hyperparameter search over trial actors.

Parity: reference `python/ray/tune/` — Tuner.fit (`tuner.py:43,312`),
TuneController (`execution/tune_controller.py:68`), search spaces
(`search/sample.py`, basic variant generation), schedulers ASHA/PBT/FIFO
(`schedulers/`), tune.report via the shared train session, experiment
checkpoint/resume (`execution/experiment_state.py`).
"""

from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    get_checkpoint,
    report,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import (  # noqa: F401
    Result,
    ResultGrid,
    TuneConfig,
    Tuner,
    with_resources,
)

__all__ = [
    "Tuner", "TuneConfig", "Result", "ResultGrid", "with_resources",
    "report", "get_checkpoint", "Checkpoint",
    "grid_search", "uniform", "loguniform", "randint", "choice",
    "ASHAScheduler", "FIFOScheduler", "PopulationBasedTraining",
]
