"""ray_tpu.tune: hyperparameter search over trial actors.

Parity: reference `python/ray/tune/` — Tuner.fit (`tuner.py:43,312`),
TuneController (`execution/tune_controller.py:68`), search spaces
(`search/sample.py`, basic variant generation), schedulers ASHA/HyperBand/
median-stop/PBT/PB2 (`schedulers/`), sequential searchers TPE/BayesOpt/BOHB
(`search/hyperopt`, `search/bayesopt`, `search/bohb` — implemented natively
here), tune.report via the shared train session, experiment
checkpoint/resume (`execution/experiment_state.py`).
"""

from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    get_checkpoint,
    report,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (  # noqa: F401
    BayesOptSearcher,
    BOHBSearcher,
    choice,
    ConcurrencyLimiter,
    grid_search,
    loguniform,
    randint,
    TPESearcher,
    uniform,
)
from ray_tpu.tune.tuner import (  # noqa: F401
    Result,
    ResultGrid,
    TuneConfig,
    Tuner,
    with_resources,
)

__all__ = [
    "Tuner", "TuneConfig", "Result", "ResultGrid", "with_resources",
    "report", "get_checkpoint", "Checkpoint",
    "grid_search", "uniform", "loguniform", "randint", "choice",
    "ASHAScheduler", "FIFOScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining", "PB2",
    "TPESearcher", "BayesOptSearcher", "BOHBSearcher", "ConcurrencyLimiter",
]
