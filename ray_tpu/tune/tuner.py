"""Tuner + trial controller: trials as actors, schedulers, experiment state.

Parity: reference `tune/tuner.py:43,312` (Tuner.fit), the TuneController
event loop (`tune/execution/tune_controller.py:68,666` — trials run as
actors, results polled, scheduler decisions applied), trial-level fault
handling, and experiment checkpointing/resume
(`tune/execution/experiment_state.py`, `Tuner.restore`).

Trials run the user function in a trial-runner actor with the train-session
mailbox (the same mechanism JaxTrainer workers use), so `tune.report` and
`tune.get_checkpoint` behave identically inside both libraries.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
import time
from typing import Any, Callable

import cloudpickle

import ray_tpu
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import generate_variants

PENDING, RUNNING, TERMINATED, ERRORED = \
    "PENDING", "RUNNING", "TERMINATED", "ERRORED"


@dataclasses.dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    scheduler: Any = None
    search_alg: Any = None  # Searcher/ConcurrencyLimiter (tune.search)
    seed: int | None = None


@dataclasses.dataclass
class Result:
    metrics: dict
    config: dict
    path: str
    checkpoint: Any = None
    error: str | None = None
    metrics_history: list = dataclasses.field(default_factory=list)


class ResultGrid:
    def __init__(self, results: list[Result]):
        self._results = results

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> Result:
        metric = metric or self._default_metric
        mode = mode or self._default_mode
        ok = [r for r in self._results
              if r.error is None and metric in (r.metrics or {})]
        if not ok:
            raise RuntimeError("no successful trial reported "
                               f"metric {metric!r}")
        return (max if mode == "max" else min)(
            ok, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)

    _default_metric: str | None = None
    _default_mode: str = "max"


def with_resources(trainable: Callable, resources: dict) -> Callable:
    """Attach per-trial resources (parity: tune.with_resources)."""
    trainable._tune_resources = dict(resources)
    return trainable


class Trial:
    def __init__(self, trial_id: str, config: dict, storage_dir: str):
        self.id = trial_id
        self.config = config
        self.storage_dir = storage_dir
        self.state = PENDING
        self.runner = None
        self.iteration = 0
        self.last_metrics: dict = {}
        self.history: list[dict] = []
        self.latest_checkpoint: str | None = None
        self.error: str | None = None
        self.last_poll_seq = 0
        self.rungs_hit: set = set()
        self.last_perturb = 0
        self.exploit_from: "Trial | None" = None
        self.restore_from: str | None = None

    def snapshot(self) -> dict:
        return {
            "id": self.id, "config": _jsonable(self.config),
            "state": self.state, "iteration": self.iteration,
            "last_metrics": _jsonable(self.last_metrics),
            "checkpoint": self.latest_checkpoint, "error": self.error,
        }


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return {k: repr(v) for k, v in obj.items()} \
            if isinstance(obj, dict) else repr(obj)


@ray_tpu.remote
class _TrialRunner:
    """Hosts one trial's user function + session mailbox."""

    def __init__(self, storage_dir: str):
        self.storage_dir = storage_dir
        self._session = None
        self._thread = None

    def start(self, fn_bytes: bytes, config: dict,
              checkpoint_path: str | None):
        import threading
        import traceback
        from ray_tpu.train import session as session_mod
        from ray_tpu.train.checkpoint import Checkpoint
        fn = cloudpickle.loads(fn_bytes)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        self._session = session_mod.TrainSession(
            0, 1, self.storage_dir, checkpoint=ckpt)
        session_mod._set_session(self._session)
        s = self._session

        def target():
            try:
                out = fn(config)
                if isinstance(out, dict):  # final-dict trainable style
                    s.report(out)
            except BaseException:  # noqa: BLE001 — ship to controller
                s.error = traceback.format_exc()
            finally:
                s.finished = True

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        """Returns {"reports", "finished", "error", "seq"}. The error is
        TERMINAL SESSION STATE, not a drained report: a lost/duplicated
        poll reply then cannot lose it — the next poll re-reads it. `seq`
        counts executed polls so the controller can spot replies that were
        executed but never consumed (message-loss diagnostics)."""
        s = self._session
        if s is None:
            return {"reports": [], "finished": False, "error": None,
                    "seq": 0}
        # Read finished BEFORE draining: the loop thread appends its final
        # report before setting finished, so this order can't lose it
        # (drain-then-read could: drain empty -> report lands -> read True).
        finished = s.finished
        self._poll_seq = getattr(self, "_poll_seq", 0) + 1
        return {"reports": s.drain_reports(), "finished": finished,
                "error": s.error, "seq": self._poll_seq}


class TuneController:
    """Parity: tune_controller.py step loop, single-threaded driver."""

    def __init__(self, trainable, trials: list[Trial], *,
                 tune_config: TuneConfig, run_config,
                 experiment_dir: str):
        self.trainable = trainable
        self.fn_bytes = cloudpickle.dumps(trainable)
        self.trials = trials
        self.cfg = tune_config
        self.run_config = run_config
        self.experiment_dir = experiment_dir
        self.scheduler = tune_config.scheduler or sched_mod.FIFOScheduler()
        if getattr(self.scheduler, "metric", None) is None and \
                hasattr(self.scheduler, "metric"):
            self.scheduler.metric = tune_config.metric
        self.searcher = tune_config.search_alg
        if self.searcher is not None:
            if getattr(self.searcher, "metric", None) is None:
                self.searcher.metric = tune_config.metric
            # Searchers default mode=None ("inherit"); an explicit searcher
            # mode that contradicts TuneConfig is a config error, not a
            # silent override.
            if getattr(self.searcher, "mode", None) is None:
                self.searcher.mode = tune_config.mode
            elif self.searcher.mode != tune_config.mode:
                raise ValueError(
                    f"search_alg mode={self.searcher.mode!r} contradicts "
                    f"TuneConfig mode={tune_config.mode!r}")
        self.resources = getattr(trainable, "_tune_resources", {"cpu": 1})

    # ---- lifecycle ----

    def _launch(self, trial: Trial):
        opts = {"num_cpus": float(self.resources.get("cpu", 1)),
                "num_tpus": float(self.resources.get("tpu", 0))}
        trial.runner = _TrialRunner.options(**opts).remote(trial.storage_dir)
        trial.last_poll_seq = 0  # fresh runner, fresh poll stream
        trial.error = None  # a relaunch (PBT exploit) supersedes old errors
        ckpt = trial.restore_from or trial.latest_checkpoint
        trial.runner.start.remote(
            self.fn_bytes, trial.config, ckpt)
        trial.state = RUNNING

    def _stop_runner(self, trial: Trial):
        if trial.runner is not None:
            try:
                ray_tpu.kill(trial.runner)
            except Exception:  # noqa: BLE001
                pass
            trial.runner = None

    def _should_stop(self, metrics: dict) -> bool:
        stop = getattr(self.run_config, "stop", None)
        if not stop:
            return False
        if callable(stop):
            return stop(metrics)
        return any(metrics.get(k, float("-inf")) >= v
                   for k, v in stop.items())

    # ---- main loop ----

    def run(self) -> list[Trial]:
        max_conc = self.cfg.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 2)) - 1)
        notified: set[str] = set()
        while True:
            running = [t for t in self.trials if t.state == RUNNING]
            pending = [t for t in self.trials if t.state == PENDING]
            if self.searcher is not None:
                # Sequential search: mint new trials from the searcher as
                # slots free up, so later suggestions see earlier results.
                while (len(self.trials) < self.cfg.num_samples
                       and len(running) + len(pending) < max_conc):
                    tid = f"trial_{len(self.trials):04d}"
                    cfg = self.searcher.suggest(tid)
                    if cfg is None:  # ConcurrencyLimiter holding back
                        break
                    tdir = os.path.join(self.experiment_dir, tid)
                    os.makedirs(tdir, exist_ok=True)
                    t = Trial(tid, cfg, tdir)
                    self.trials.append(t)
                    pending.append(t)
                exhausted = len(self.trials) >= self.cfg.num_samples
            else:
                exhausted = True
            if not running and not pending:
                if not exhausted:
                    print("tune: WARNING search_alg returned no suggestion "
                          "with no trials in flight; ending the experiment "
                          f"at {len(self.trials)}/{self.cfg.num_samples} "
                          "trials", file=sys.stderr)
                break
            while pending and len(running) < max_conc:
                t = pending.pop(0)
                self._launch(t)
                running.append(t)
            polls = [(t, t.runner.poll.remote()) for t in running
                     if t.runner is not None]
            for trial, ref in polls:
                try:
                    poll = ray_tpu.get(ref, timeout=60)
                except Exception as e:  # noqa: BLE001 — runner died
                    trial.state = ERRORED
                    trial.error = f"trial runner died: {e}"
                    self._stop_runner(trial)
                    continue
                seq = poll.get("seq", 0)
                if trial.last_poll_seq and seq > trial.last_poll_seq + 1:
                    print(f"tune: WARNING trial {trial.id} poll seq jumped "
                          f"{trial.last_poll_seq}->{seq}: a poll reply was "
                          f"executed but never consumed", file=sys.stderr)
                trial.last_poll_seq = seq
                if poll.get("error") and not trial.error:
                    trial.error = poll["error"]
                self._process_reports(trial, poll["reports"])
                if poll["finished"] and trial.state == RUNNING:
                    trial.state = (ERRORED if trial.error else TERMINATED)
                    self._stop_runner(trial)
            if self.searcher is not None:
                for t in self.trials:
                    if t.state in (TERMINATED, ERRORED) \
                            and t.id not in notified:
                        notified.add(t.id)
                        self.searcher.on_trial_complete(
                            t.id, t.last_metrics or None)
            self._save_experiment_state()
            time.sleep(0.02)
        self._save_experiment_state()
        return self.trials

    def _process_reports(self, trial: Trial, reports: list[dict]):
        for rep in reports:
            metrics = dict(rep.get("metrics", {}))
            trial.iteration += 1
            metrics.setdefault("training_iteration", trial.iteration)
            ack = rep.get("ckpt_shard")
            if ack:
                # Two-phase checkpoint ack (train/session.py): tune trials
                # are world-1 gangs, so the single rank's durable-shard
                # ack IS "all ranks acked" — commit the manifest here and
                # only then adopt the path (torn dirs stay invisible).
                from ray_tpu.train import checkpoint as ckpt_mod
                if ack.get("shard") and not ckpt_mod.is_committed(
                        ack["dir"]):
                    ckpt_mod.commit_manifest(
                        ack["dir"], step=ack["step"],
                        world_size=ack["world"], shards=[ack["shard"]])
                trial.latest_checkpoint = ack["dir"]
            elif rep.get("checkpoint"):
                trial.latest_checkpoint = rep["checkpoint"]
            trial.last_metrics = metrics
            trial.history.append(metrics)
            if trial.state != RUNNING:
                continue
            if self._should_stop(metrics):
                trial.state = TERMINATED
                self._stop_runner(trial)
                continue
            decision = self.scheduler.on_result(trial, metrics)
            if decision == sched_mod.STOP:
                trial.state = TERMINATED
                self._stop_runner(trial)
            elif decision == "EXPLOIT":
                donor = trial.exploit_from
                trial.exploit_from = None
                if donor is not None and donor.latest_checkpoint:
                    # PBT: restart from the donor's checkpoint with
                    # mutated hyperparams (tune/schedulers/pbt.py).
                    self._stop_runner(trial)
                    trial.config = self.scheduler.mutate(donor.config)
                    trial.restore_from = donor.latest_checkpoint
                    trial.state = PENDING

    def _save_experiment_state(self):
        state = {
            "timestamp": time.time(),
            "trials": [t.snapshot() for t in self.trials],
        }
        path = os.path.join(self.experiment_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, path)


class Tuner:
    def __init__(self, trainable=None, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None, run_config=None,
                 _trials: list[Trial] | None = None):
        from ray_tpu.train.trainer import RunConfig
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name="tune_run")
        self._preloaded_trials = _trials

    def _experiment_dir(self) -> str:
        base = getattr(self.run_config, "storage_path", None) or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        d = os.path.join(base, getattr(self.run_config, "name", "tune_run"))
        os.makedirs(d, exist_ok=True)
        return d

    def fit(self) -> ResultGrid:
        if self.trainable is None:
            raise ValueError("Tuner needs a trainable")
        exp_dir = self._experiment_dir()
        if self._preloaded_trials is not None:
            trials = self._preloaded_trials
        elif self.tune_config.search_alg is not None:
            if self.param_space:
                raise ValueError(
                    "pass the search space to the searcher "
                    "(e.g. TPESearcher(space)), not Tuner(param_space=...) "
                    "— providing both is ambiguous")
            trials = []  # minted lazily by the controller from the searcher
        else:
            variants = generate_variants(
                self.param_space, self.tune_config.num_samples,
                seed=self.tune_config.seed)
            trials = []
            for i, cfg in enumerate(variants):
                tdir = os.path.join(exp_dir, f"trial_{i:04d}")
                os.makedirs(tdir, exist_ok=True)
                trials.append(Trial(f"trial_{i:04d}", cfg, tdir))
        controller = TuneController(
            self.trainable, trials, tune_config=self.tune_config,
            run_config=self.run_config, experiment_dir=exp_dir)
        done = controller.run()
        results = []
        for t in done:
            from ray_tpu.train.checkpoint import Checkpoint
            results.append(Result(
                metrics=t.last_metrics, config=t.config,
                path=t.storage_dir,
                checkpoint=(Checkpoint(t.latest_checkpoint)
                            if t.latest_checkpoint else None),
                error=t.error, metrics_history=t.history))
        grid = ResultGrid(results)
        grid._default_metric = self.tune_config.metric
        grid._default_mode = self.tune_config.mode
        return grid

    @classmethod
    def restore(cls, path: str, trainable, *,
                restart_errored: bool = False,
                tune_config: TuneConfig | None = None,
                run_config=None) -> "Tuner":
        """Resume an interrupted experiment from experiment_state.json."""
        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        trials = []
        for snap in state["trials"]:
            t = Trial(snap["id"], snap["config"],
                      os.path.join(path, snap["id"]))
            t.iteration = snap.get("iteration", 0)
            t.last_metrics = snap.get("last_metrics") or {}
            t.latest_checkpoint = snap.get("checkpoint")
            t.error = snap.get("error")
            st = snap["state"]
            if st == TERMINATED:
                t.state = TERMINATED
            elif st == ERRORED and not restart_errored:
                t.state = ERRORED
            else:
                # PENDING/RUNNING (interrupted) or restarted ERRORED:
                # rerun from the latest checkpoint.
                t.state = PENDING
                t.restore_from = t.latest_checkpoint
                t.error = None
            trials.append(t)
        from ray_tpu.train.trainer import RunConfig
        rc = run_config or RunConfig(
            name=os.path.basename(path.rstrip("/")),
            storage_path=os.path.dirname(path.rstrip("/")))
        return cls(trainable, tune_config=tune_config, run_config=rc,
                   _trials=trials)
