"""Search spaces + variant generation.

Parity: reference `tune/search/` — `grid_search` markers, sampling
distributions (`tune/search/sample.py`: uniform/loguniform/randint/choice),
and the BasicVariantGenerator (grid cross-product x num_samples random
draws, `tune/search/basic_variant.py`).
"""

from __future__ import annotations

import itertools
import random
from typing import Any


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class LogUniform(Domain):
    def __init__(self, lo: float, hi: float):
        import math
        self.llo, self.lhi = math.log(lo), math.log(hi)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.llo, self.lhi))


class RandInt(Domain):
    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randrange(self.lo, self.hi)


class Choice(Domain):
    def __init__(self, options):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


def uniform(lo: float, hi: float) -> Uniform:
    return Uniform(lo, hi)


def loguniform(lo: float, hi: float) -> LogUniform:
    return LogUniform(lo, hi)


def randint(lo: int, hi: int) -> RandInt:
    return RandInt(lo, hi)


def choice(options) -> Choice:
    return Choice(options)


class _GridSearch:
    def __init__(self, values):
        self.values = list(values)


def grid_search(values) -> _GridSearch:
    return _GridSearch(values)


def generate_variants(param_space: dict, num_samples: int,
                      seed: int | None = None) -> list[dict]:
    """Cross-product of grid_search axes x num_samples draws of Domains.

    Parity: BasicVariantGenerator semantics — each grid combination is run
    num_samples times, with Domain params re-sampled per variant.
    """
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, _GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, _GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
