"""Search spaces, variant generation, and sequential search algorithms.

Parity: reference `tune/search/` — `grid_search` markers, sampling
distributions (`tune/search/sample.py`: uniform/loguniform/randint/choice),
the BasicVariantGenerator (grid cross-product x num_samples random draws,
`tune/search/basic_variant.py`), and native equivalents of the wrapped
searchers: TPE (`tune/search/hyperopt/`), GP Bayesian optimization
(`tune/search/bayesopt/`), budget-aware TPE (`tune/search/bohb/`), and
ConcurrencyLimiter (`tune/search/searcher.py`). The reference shells out to
external libraries for these; here they are implemented directly (numpy
only) so the framework is self-contained.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class LogUniform(Domain):
    def __init__(self, lo: float, hi: float):
        import math
        self.llo, self.lhi = math.log(lo), math.log(hi)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.llo, self.lhi))


class RandInt(Domain):
    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randrange(self.lo, self.hi)


class Choice(Domain):
    def __init__(self, options):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


def uniform(lo: float, hi: float) -> Uniform:
    return Uniform(lo, hi)


def loguniform(lo: float, hi: float) -> LogUniform:
    return LogUniform(lo, hi)


def randint(lo: int, hi: int) -> RandInt:
    return RandInt(lo, hi)


def choice(options) -> Choice:
    return Choice(options)


class _GridSearch:
    def __init__(self, values):
        self.values = list(values)


def grid_search(values) -> _GridSearch:
    return _GridSearch(values)


def generate_variants(param_space: dict, num_samples: int,
                      seed: int | None = None) -> list[dict]:
    """Cross-product of grid_search axes x num_samples draws of Domains.

    Parity: BasicVariantGenerator semantics — each grid combination is run
    num_samples times, with Domain params re-sampled per variant.
    """
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, _GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, _GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants


# ---------------------------------------------------------------------------
# Sequential searchers (suggest configs one at a time, learn from results)
# ---------------------------------------------------------------------------


class Searcher:
    """Base sequential searcher (parity: tune/search/searcher.py Searcher).

    The TuneController asks `suggest()` for each new trial and feeds every
    finished trial back through `on_trial_complete`."""

    def __init__(self, space: dict, *, metric: str | None = None,
                 mode: str | None = None, seed: int | None = None):
        self.space = dict(space)
        self.metric = metric
        # None = "inherit from TuneConfig"; standalone use defaults to max.
        self.mode = mode
        self._rng = random.Random(seed)
        # observations: list of (config, score) with score maximized
        self._obs: list[tuple[dict, float]] = []
        self._live: dict[str, dict] = {}

    # -- controller protocol --

    def suggest(self, trial_id: str) -> dict:
        cfg = self._suggest()
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, metrics: dict | None):
        cfg = self._live.pop(trial_id, None)
        if cfg is None or not metrics or self.metric not in metrics:
            return
        val = metrics[self.metric]
        self._obs.append((cfg, -val if self.mode == "min" else val))

    # -- implementation hook --

    def _random_config(self) -> dict:
        cfg = {}
        for k, v in self.space.items():
            if isinstance(v, _GridSearch):
                cfg[k] = self._rng.choice(v.values)
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self._rng)
            else:
                cfg[k] = v
        return cfg

    def _suggest(self) -> dict:
        return self._random_config()


def _to_unit(domain, value) -> float | None:
    """Map a sampled value into [0,1] under the domain's natural metric."""
    if isinstance(domain, Uniform):
        return (value - domain.lo) / max(domain.hi - domain.lo, 1e-12)
    if isinstance(domain, LogUniform):
        return (math.log(value) - domain.llo) / max(
            domain.lhi - domain.llo, 1e-12)
    if isinstance(domain, RandInt):
        return (value - domain.lo) / max(domain.hi - 1 - domain.lo, 1e-12)
    return None  # categorical


def _from_unit(domain, u: float):
    u = min(max(u, 0.0), 1.0)
    if isinstance(domain, Uniform):
        return domain.lo + u * (domain.hi - domain.lo)
    if isinstance(domain, LogUniform):
        return math.exp(domain.llo + u * (domain.lhi - domain.llo))
    if isinstance(domain, RandInt):
        return min(domain.hi - 1, domain.lo + int(u * (domain.hi - domain.lo)))
    raise TypeError(f"not a numeric domain: {domain}")


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (native HyperOpt equivalent,
    parity: tune/search/hyperopt/hyperopt_search.py).

    Observations are split into a good set (top `gamma` quantile) and a bad
    set. Each numeric dimension is modelled as a kernel density (mixture of
    Gaussians centred on observed points in unit space); candidates are
    drawn from the good-set density and ranked by the likelihood ratio
    l(x)/g(x). Categorical dimensions use smoothed count weights. Dimensions
    factorize independently, as in HyperOpt's default configuration."""

    def __init__(self, space: dict, *, metric: str | None = None,
                 mode: str | None = None, n_initial_points: int = 10,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int | None = None):
        super().__init__(space, metric=metric, mode=mode, seed=seed)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates

    def _split(self):
        ranked = sorted(self._obs, key=lambda o: -o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    @staticmethod
    def _kde_logpdf(x: float, pts: list[float], bw: float) -> float:
        if not pts:
            return 0.0
        acc = 0.0
        for p in pts:
            z = (x - p) / bw
            acc += math.exp(-0.5 * z * z)
        return math.log(max(acc / (len(pts) * bw), 1e-300))

    def _suggest(self) -> dict:
        if len(self._obs) < self.n_initial:
            return self._random_config()
        good, bad = self._split()
        cfg = {}
        for key, dom in self.space.items():
            if not isinstance(dom, Domain) and not isinstance(dom, _GridSearch):
                cfg[key] = dom
                continue
            if isinstance(dom, (Choice, _GridSearch)):
                options = dom.options if isinstance(dom, Choice) else dom.values
                # smoothed counts from the good set
                weights = []
                for o in options:
                    c = sum(1 for g, _ in good if g.get(key) == o)
                    weights.append(c + 1.0)
                total = sum(weights)
                r = self._rng.random() * total
                acc = 0.0
                pick = options[-1]
                for o, w in zip(options, weights):
                    acc += w
                    if r <= acc:
                        pick = o
                        break
                cfg[key] = pick
                continue
            good_u = [u for g, _ in good
                      if (u := _to_unit(dom, g.get(key))) is not None]
            bad_u = [u for b, _ in bad
                     if (u := _to_unit(dom, b.get(key))) is not None]
            # Scott-ish bandwidth on the unit interval, floored so early
            # iterations keep exploring.
            bw = max(0.1, 1.0 / max(len(good_u), 1) ** 0.5 * 0.5)
            best_u, best_score = None, -float("inf")
            for _ in range(self.n_candidates):
                if good_u and self._rng.random() < 0.9:
                    centre = self._rng.choice(good_u)
                    u = min(max(self._rng.gauss(centre, bw), 0.0), 1.0)
                else:
                    u = self._rng.random()
                score = (self._kde_logpdf(u, good_u, bw)
                         - self._kde_logpdf(u, bad_u, bw))
                if score > best_score:
                    best_u, best_score = u, score
            cfg[key] = _from_unit(dom, best_u)
        return cfg


class BayesOptSearcher(Searcher):
    """GP-based Bayesian optimization (parity: tune/search/bayesopt/).

    RBF-kernel Gaussian process over the numeric dimensions mapped to unit
    space (categoricals are sampled randomly), with expected improvement
    maximized over a random candidate pool. Pure numpy."""

    def __init__(self, space: dict, *, metric: str | None = None,
                 mode: str | None = None, n_initial_points: int = 8,
                 n_candidates: int = 256, kappa_noise: float = 1e-6,
                 length_scale: float = 0.2, seed: int | None = None):
        super().__init__(space, metric=metric, mode=mode, seed=seed)
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.noise = kappa_noise
        self.ls = length_scale
        self._num_keys = [k for k, v in space.items()
                          if isinstance(v, (Uniform, LogUniform, RandInt))]

    def _vec(self, cfg) -> list[float]:
        return [_to_unit(self.space[k], cfg[k]) for k in self._num_keys]

    def _suggest(self) -> dict:
        if len(self._obs) < self.n_initial or not self._num_keys:
            return self._random_config()
        import numpy as np
        X = np.array([self._vec(c) for c, _ in self._obs])
        y = np.array([s for _, s in self._obs], dtype=float)
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (self.ls ** 2))

        K = k(X, X) + self.noise * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return self._random_config()
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        # candidate pool: random + jittered copies of the best points
        cands = [self._random_config() for _ in range(self.n_candidates)]
        best_cfgs = [c for c, _ in sorted(self._obs, key=lambda o: -o[1])[:4]]
        for c in best_cfgs:
            for _ in range(8):
                j = dict(c)
                for kk in self._num_keys:
                    u = _to_unit(self.space[kk], j[kk])
                    j[kk] = _from_unit(self.space[kk],
                                       u + self._rng.gauss(0, 0.05))
                cands.append(j)
        Xc = np.array([self._vec(c) for c in cands])
        Kc = k(Xc, X)
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        sigma = np.sqrt(var)
        best = yn.max()
        z = (mu - best) / sigma
        # expected improvement with Phi/phi in closed form
        from math import erf
        Phi = 0.5 * (1.0 + np.vectorize(erf)(z / math.sqrt(2)))
        phi = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
        ei = (mu - best) * Phi + sigma * phi
        return cands[int(np.argmax(ei))]


class BOHBSearcher(TPESearcher):
    """Budget-aware TPE (parity: tune/search/bohb/ TuneBOHB): observations
    are bucketed by the training budget they were measured at (the
    `training_iteration` each trial reached); the model conditions on the
    largest budget with enough points, so early low-fidelity results stop
    polluting the model once high-fidelity ones exist. Pair with
    ASHAScheduler/HyperBandScheduler for the HpBandSter behavior."""

    def __init__(self, space: dict, *, metric: str | None = None,
                 mode: str | None = None, min_points_per_budget: int = 6,
                 **kw):
        super().__init__(space, metric=metric, mode=mode, **kw)
        self.min_points = min_points_per_budget
        self._budget_obs: dict[int, list[tuple[dict, float]]] = {}

    def on_trial_complete(self, trial_id: str, metrics: dict | None):
        cfg = self._live.get(trial_id)
        budget = int((metrics or {}).get("training_iteration", 0))
        super().on_trial_complete(trial_id, metrics)
        if cfg is not None and metrics and self.metric in metrics:
            val = metrics[self.metric]
            score = -val if self.mode == "min" else val
            self._budget_obs.setdefault(budget, []).append((cfg, score))

    def _split(self):
        # largest budget with >= min_points observations wins
        for budget in sorted(self._budget_obs, reverse=True):
            obs = self._budget_obs[budget]
            if len(obs) >= self.min_points:
                ranked = sorted(obs, key=lambda o: -o[1])
                n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
                return ranked[:n_good], ranked[n_good:]
        return super()._split()


class ConcurrencyLimiter:
    """Caps in-flight suggestions (parity: tune/search/searcher.py
    ConcurrencyLimiter): suggest() returns None while `max_concurrent`
    trials are outstanding, which the controller treats as "no trial
    available yet"."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._inflight: set[str] = set()

    @property
    def metric(self):
        return self.searcher.metric

    @metric.setter
    def metric(self, v):
        self.searcher.metric = v

    @property
    def mode(self):
        return self.searcher.mode

    @mode.setter
    def mode(self, v):
        self.searcher.mode = v

    def suggest(self, trial_id: str):
        if len(self._inflight) >= self.max_concurrent:
            return None
        self._inflight.add(trial_id)
        return self.searcher.suggest(trial_id)

    def on_trial_complete(self, trial_id: str, metrics: dict | None):
        self._inflight.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, metrics)
