"""Usage stats: opt-out, local-only recording.

Parity: reference `python/ray/_private/usage/usage_lib.py` — the reference
collects cluster metadata and (unless RAY_USAGE_STATS_ENABLED=0) reports
it to a telemetry endpoint. This environment is zero-egress by design, so
the equivalent records the same shape of report to the session directory
only; `usage_stats_enabled()` honors the same opt-out env var.
"""

from __future__ import annotations

import json
import os
import time

_ENV = "RAY_TPU_USAGE_STATS_ENABLED"


def usage_stats_enabled() -> bool:
    return os.environ.get(_ENV, "1") not in ("0", "false", "False")


def build_report(rt) -> dict:
    """The reference's report shape: versions, cluster size, library use."""
    import sys
    report = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "session_start": time.time(),
        "python_version": sys.version.split()[0],
        "os": os.uname().sysname.lower(),
        "total_num_cpus": rt.cluster_resources().get("CPU", 0),
        "total_num_tpus": rt.cluster_resources().get("TPU", 0),
        "num_nodes": sum(1 for n in rt.nodes_table() if n["alive"]),
    }
    try:
        import jax
        report["jax_version"] = jax.__version__
    except ImportError:
        pass
    return report


def record_usage(rt):
    """Write the report under the session dir (no egress); no-op when the
    user opted out."""
    if not usage_stats_enabled():
        return None
    path = os.path.join(rt.session_dir, "usage_stats.json")
    try:
        with open(path, "w") as f:
            json.dump(build_report(rt), f, indent=1)
        return path
    except OSError:
        return None
