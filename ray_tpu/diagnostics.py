"""Process-global jit compile-cache diagnostics.

The dynamic half of graphcheck's recompile gate (finding class 3): the
static pass can prove a *hazard* (weak types, per-call jit wrappers,
unstable static args), but whether a hot loop actually recompiles in
steady state is a runtime fact. `jit_misses()` is a monotonic counter of
backend compiles in this process — tests snapshot it, run N steady-state
steps, and assert the delta is zero:

    base = diagnostics.jit_misses()
    for _ in range(8):
        engine.step()
    assert diagnostics.jit_misses() == base

Implementation: jax.monitoring duration events. Every executable build
records '/jax/core/compile/backend_compile_duration' exactly once (the
jaxpr trace and jaxpr->MLIR stages record their own keys, counted
separately as `jit_traces()` — a tracing-cache miss that HITS the
persistent compilation cache still costs the trace). The listener is
registered at import, appends nothing per event but two int increments,
and is process-global — counters cover every engine/trainer/actor in
the process, which is exactly what a steady-state assertion wants.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counts = {"compiles": 0, "traces": 0}
_installed = False

_COMPILE_KEY = "/jax/core/compile/backend_compile_duration"
_TRACE_KEY = "/jax/core/compile/jaxpr_trace_duration"


def _listener(name: str, duration_secs: float = 0.0, **_kw) -> None:
    if name == _COMPILE_KEY:
        with _lock:
            _counts["compiles"] += 1
    elif name == _TRACE_KEY:
        with _lock:
            _counts["traces"] += 1


def _install() -> None:
    global _installed
    if _installed:
        return
    import jax
    jax.monitoring.register_event_duration_secs_listener(_listener)
    _installed = True


_install()


def jit_misses() -> int:
    """Monotonic count of backend compiles in this process. A steady-state
    hot loop must hold this flat; every increment is a fresh executable
    (new shape bucket, weak-type fork, unstable static, dropped cache)."""
    with _lock:
        return _counts["compiles"]


def jit_traces() -> int:
    """Monotonic count of jaxpr traces (>= jit_misses: retraces that hit
    the executable cache still pay python tracing)."""
    with _lock:
        return _counts["traces"]
