"""Logical-axis sharding rules → PartitionSpecs (GSPMD lowering).

The scaling-book recipe: annotate arrays with *logical* axis names
("batch", "seq", "embed", "mlp", "heads", "vocab", "expert", ...), map those
to mesh axes with a rules table, and let GSPMD insert collectives. FSDP is
just "embed→fsdp on params + gather before use"; TP is "mlp/heads→tp";
sequence parallelism is "seq→sp".
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class ShardingRules:
    """logical name -> mesh axis (or None = replicated)."""

    rules: dict[str, str | tuple[str, ...] | None]

    @classmethod
    def default(cls) -> "ShardingRules":
        return cls({
            # activations
            "batch": ("dp", "fsdp"),
            "seq": "sp",
            "embed_act": None,
            # params
            "embed": "fsdp",       # ZeRO-3: shard the "long" param axis
            "mlp": "tp",
            "heads": "tp",
            "kv_heads": "tp",
            "head_dim": None,
            "vocab": "tp",
            "expert": "ep",
            "stage": "pp",
        })

    def spec(self, logical_axes: tuple[str | None, ...]) -> P:
        out = []
        used: set[str] = set()
        for name in logical_axes:
            axis = None if name is None else self.rules.get(name)
            if isinstance(axis, tuple):
                axis = tuple(a for a in axis if a not in used)
                used.update(axis)
                out.append(axis if axis else None)
            else:
                if axis in used:
                    axis = None
                if axis is not None:
                    used.add(axis)
                out.append(axis)
        return P(*out)


def logical_to_physical(rules: ShardingRules, logical_tree):
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: rules.spec(axes),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def declared_param_specs(param_axes, rules: ShardingRules | None = None):
    """THE declared param shardings: the single table both the jit sites
    (train/step.py in_shardings) and the graphcheck cross-check read.
    graphcheck compares the shardings a hot graph actually LOWERED with
    against this declaration, so an edit that drops in_shardings from a
    jit site — or a rules edit that silently de-shards a param — fails
    the static gate instead of surfacing as an MFU cliff on hardware."""
    return logical_to_physical(rules or ShardingRules.default(),
                               param_axes)


def shard_params(params, logical_tree, rules: ShardingRules, mesh: Mesh):
    """Device-put a param pytree with its sharding (for init / restore)."""
    specs = logical_to_physical(rules, logical_tree)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def reshard(tree, shardings):
    """Device-put every leaf onto its (new-mesh) sharding — the elastic
    restore step: state saved on an N-device mesh lands on an M-device
    mesh (jax moves shards through host memory where layouts differ).
    `shardings` is a matching pytree of NamedShardings, e.g. the
    state_shardings make_train_step derives for the NEW mesh."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)


def with_sharding(x, mesh: Mesh, spec: P):
    """Sharding constraint inside jit (GSPMD hint)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions: `jax.shard_map(..., check_vma=)` on
    current jax, `jax.experimental.shard_map.shard_map(..., check_rep=)`
    on 0.4.x — same semantics (replication checking off; the wrappers
    here all psum/permute explicitly). Every sp/pp entry point routes
    through this so one jax upgrade path touches one function."""
    try:
        from jax import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes that shard the batch dimension of activations (the
    activation-layout half of the "batch" rule): the axes present on this
    mesh, in rule order, so constraints built from it agree with
    batch_spec = P(("dp", "fsdp")) on any mesh shape."""
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def __graphcheck__(gc):
    """graphcheck hook (tools/graphcheck): the canonical activation
    batch-constraint graph. Pins that `activation_batch_sharded` lowers
    to a pure layout constraint on a dp x fsdp mesh — zero collectives,
    zero callbacks — i.e. the embedding-seam constraint stays a hint,
    never a resharding round trip."""

    def build(mesh):
        from jax.sharding import NamedSharding

        x = jax.ShapeDtypeStruct((64, 128), jnp.float32)
        batch_spec = P(("dp", "fsdp"))

        def fn(a):
            return activation_batch_sharded(a, mesh) * 2.0

        return gc.GraphSpec(
            name="parallel.batch_constraint", fn=fn, args=(x,),
            in_shardings=(NamedSharding(mesh, batch_spec),),
            declared_in_specs=(("acts", batch_spec),),
            expect_sharded=("acts",), arg_names=("acts",))

    gc.register("parallel.batch_constraint", build,
                meshes=({"dp": 2, "fsdp": 2},))


def activation_batch_sharded(x, mesh: Mesh):
    """Constrain a [batch, ...] activation to the canonical layout: batch
    over the data axes, everything else replicated. Used at layout seams
    where the partitioner would otherwise propagate a PARAM sharding into
    the activation (the embedding lookup: its natural output inherits the
    table's embed sharding on a transposed device order, which XLA can
    only leave via involuntary full rematerialization)."""
    axes = data_axes(mesh)
    spec = P(axes if axes else None, *([None] * (x.ndim - 1)))
    return with_sharding(x, mesh, spec)
