"""Logical-axis sharding rules → PartitionSpecs (GSPMD lowering).

The scaling-book recipe: annotate arrays with *logical* axis names
("batch", "seq", "embed", "mlp", "heads", "vocab", "expert", ...), map those
to mesh axes with a rules table, and let GSPMD insert collectives. FSDP is
just "embed→fsdp on params + gather before use"; TP is "mlp/heads→tp";
sequence parallelism is "seq→sp".
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class ShardingRules:
    """logical name -> mesh axis (or None = replicated)."""

    rules: dict[str, str | tuple[str, ...] | None]

    @classmethod
    def default(cls) -> "ShardingRules":
        return cls({
            # activations
            "batch": ("dp", "fsdp"),
            "seq": "sp",
            "embed_act": None,
            # params
            "embed": "fsdp",       # ZeRO-3: shard the "long" param axis
            "mlp": "tp",
            "heads": "tp",
            "kv_heads": "tp",
            "head_dim": None,
            "vocab": "tp",
            "expert": "ep",
            "stage": "pp",
        })

    def spec(self, logical_axes: tuple[str | None, ...]) -> P:
        out = []
        used: set[str] = set()
        for name in logical_axes:
            axis = None if name is None else self.rules.get(name)
            if isinstance(axis, tuple):
                axis = tuple(a for a in axis if a not in used)
                used.update(axis)
                out.append(axis if axis else None)
            else:
                if axis in used:
                    axis = None
                if axis is not None:
                    used.add(axis)
                out.append(axis)
        return P(*out)


def logical_to_physical(rules: ShardingRules, logical_tree):
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: rules.spec(axes),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def shard_params(params, logical_tree, rules: ShardingRules, mesh: Mesh):
    """Device-put a param pytree with its sharding (for init / restore)."""
    specs = logical_to_physical(rules, logical_tree)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def with_sharding(x, mesh: Mesh, spec: P):
    """Sharding constraint inside jit (GSPMD hint)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
