"""Pipeline parallelism: GPipe microbatch schedule over the "pp" mesh axis.

Parity note: the reference only consumes a vLLM pipeline_parallel_size for
placement (`vllm_models.py:127`) and offers compiled-graph NCCL channels as a
substrate (`dag/compiled_dag_node.py:805`). Here PP is a compiler-visible
program: stage parameters are sharded over "pp", activations flow between
stages with `jax.lax.ppermute` inside a `lax.scan`, and reverse-mode autodiff
through the scan + ppermute yields the backward schedule for free (XLA
overlaps the permutes with stage compute).

Schedule: plain GPipe — M microbatches drain through S stages in M+S-1 ticks;
bubble fraction (S-1)/(M+S-1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def gpipe_inner(stage_fn, stage_params, microbatches, axis_name: str,
                num_stages: int):
    """Run inside shard_map over `axis_name` ("pp").

    stage_fn: (params, x) -> y, the per-stage computation.
    stage_params: this stage's parameter shard (leading stage axis removed).
    microbatches: [M, ...] all microbatch inputs (same on every stage; only
      stage 0 reads them).
    Returns [M, ...] stage outputs, valid on the LAST stage (zeros elsewhere).
    """
    stage = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + num_stages - 1
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    y0 = jax.eval_shape(lambda x: stage_fn(stage_params, x), microbatches[0])
    out_buf = jnp.zeros((m,) + y0.shape, y0.dtype)

    def tick(carry, t):
        incoming, out_buf = carry
        mb_idx = t - stage  # which microbatch this stage works on at tick t
        active = (mb_idx >= 0) & (mb_idx < m)
        # Stage 0 reads from the input queue, others from the wire.
        feed = jax.lax.cond(
            stage == 0,
            lambda: jax.lax.dynamic_index_in_dim(
                microbatches, jnp.clip(mb_idx, 0, m - 1), keepdims=False),
            lambda: incoming)
        y = stage_fn(stage_params, feed)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # Last stage banks its result; everyone forwards along the ring.
        out_buf = jax.lax.cond(
            active & (stage == num_stages - 1),
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, y.astype(b.dtype), jnp.clip(mb_idx, 0, m - 1), axis=0),
            lambda b: b,
            out_buf)
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return (nxt, out_buf), None

    incoming0 = jnp.zeros(y0.shape, y0.dtype)
    (_, out_buf), _ = jax.lax.scan(
        tick, (incoming0, out_buf), jnp.arange(ticks))
    return out_buf


def gpipe(stage_fn, stacked_params, microbatches, mesh, axis_name: str = "pp"):
    """stacked_params: pytree with leading stage axis sharded over pp.

    Returns per-microbatch outputs replicated... outputs live on the last
    stage; callers typically compute the loss inside stage_fn of the last
    stage and psum. For generic use we broadcast the last stage's buffer.
    """
    from ray_tpu.parallel.sharding import shard_map_compat
    s = mesh.shape[axis_name]

    def inner(params, mbs):
        params = jax.tree.map(lambda x: x[0], params)  # drop stage axis
        out = gpipe_inner(stage_fn, params, mbs, axis_name, s)
        # Broadcast final-stage outputs to all stages (psum of one-hot).
        return jax.lax.psum(out, axis_name)

    return shard_map_compat(
        inner, mesh,
        (jax.tree.map(lambda _: P(axis_name), stacked_params), P()),
        P())(stacked_params, microbatches)
