"""Ring attention: blockwise attention with KV rotation over an ICI ring.

Greenfield per SURVEY.md §5.7 — the reference has no sequence/context
parallelism (grep-verified, SURVEY.md:149). Design follows blockwise ring
attention (Liu et al.): the sequence is sharded over the "sp" mesh axis; each
step every device computes flash attention of its local Q block against the
KV block currently resident, then rotates KV to the next ring neighbor with
`jax.lax.ppermute` (lowered to ICI collective-permute, so the transfer
overlaps the next block's compute under XLA's scheduler).

The per-step inner attention runs the Pallas flash kernels from
`ray_tpu.ops.attention` (fwd + bwd), so per-device live memory is
O(kernel block), never O(chunk^2). Per-step partial results merge through
normalized-output/logsumexp accumulation (identical math to flash
attention's online softmax, fp32 accumulators).

Backward is a ring-level custom VJP, not AD through the forward loop: the
forward saves only (q, k, v, out, lse) — O(local block) residuals — and the
backward re-rotates KV while dK/dV accumulators travel WITH their blocks,
arriving home after the full ring pass. dQ accumulates locally.

Communication cost: (sp-1) ppermutes of the local KV block forward,
(sp-1) ppermutes of (KV, dKV) backward — bandwidth-optimal for full
attention.

A pure-jnp implementation (`impl="jnp"`) remains the CPU/numerics oracle;
`impl="interpret"` runs the Pallas kernels in interpreter mode so CPU tests
exercise the exact TPU code path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import _flash_bwd, _flash_fwd, _on_tpu

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# jnp path (oracle; also the fallback for block-unfriendly local lengths)
# ---------------------------------------------------------------------------


def _block_attn(q, k, v, scale, mask):
    """One Q-block x KV-block flash step. Returns (partial_out, rowmax, rowsum).

    q: [B, Lq, H, D]  k,v: [B, Lk, H, D]  mask: [Lq, Lk] or None (True=keep).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                     # [B,H,Lq]
    # Rows with no visible keys: keep m finite so exp() underflows to 0.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])          # [B,H,Lq,Lk]
    l = jnp.sum(p, axis=-1)                     # [B,H,Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def _merge(acc, o, m, l):
    """Merge a new block into the running (out, max, sum) accumulator."""
    acc_o, acc_m, acc_l = acc
    new_m = jnp.maximum(acc_m, m)
    alpha = jnp.exp(acc_m - new_m)              # rescale old
    beta = jnp.exp(m - new_m)                   # rescale new
    new_l = acc_l * alpha + l * beta
    new_o = (acc_o * alpha[..., None].transpose(0, 2, 1, 3)
             + o * beta[..., None].transpose(0, 2, 1, 3))
    return new_o, new_m, new_l


def _ring_jnp_inner(q, k, v, axis_name: str, axis_size: int,
                    causal: bool = True, scale: float | None = None):
    """Pure-jnp ring pass (reverse-differentiable through the scan)."""
    n = axis_size
    idx = jax.lax.axis_index(axis_name)
    lq = q.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)

    rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lq), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lq), 1)
    diag_mask = rows >= cols  # causal mask within the diagonal block

    acc_o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    acc_m = jnp.full(q.shape[:1] + (q.shape[2], lq), NEG_INF, jnp.float32)
    acc_l = jnp.zeros_like(acc_m)

    def step(t, carry):
        acc, cur_k, cur_v = carry
        src_block = (idx - t) % n  # global block id of the resident KV
        if causal:
            # Full mask when src < idx, diagonal mask when ==, all-hidden when >.
            keep_all = src_block < idx
            keep_diag = src_block == idx
            mask = jnp.where(
                keep_all, jnp.ones_like(diag_mask),
                jnp.where(keep_diag, diag_mask, jnp.zeros_like(diag_mask)))
        else:
            mask = None
        o, m, l = _block_attn(qf, cur_k, cur_v, scale, mask)
        acc = _merge(acc, o, m, l)
        # Rotate KV around the ring (ICI collective-permute).
        perm = [(i, (i + 1) % n) for i in range(n)]
        nxt_k = jax.lax.ppermute(cur_k, axis_name, perm)
        nxt_v = jax.lax.ppermute(cur_v, axis_name, perm)
        return acc, nxt_k, nxt_v

    carry = ((acc_o, acc_m, acc_l), k.astype(jnp.float32), v.astype(jnp.float32))
    (acc_o, acc_m, acc_l), _, _ = jax.lax.fori_loop(0, n, step, carry)
    out = acc_o / jnp.maximum(acc_l, 1e-30)[..., None].transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas path: flash kernels per ring step, ring-level custom VJP
# ---------------------------------------------------------------------------


def _ring_block(l_local: int) -> int:
    """Kernel tile that divides the local chunk (<= the default 512)."""
    return math.gcd(l_local, 512)


def _step_fwd(q, k, v, scale, causal, blk, interpret):
    """One ring step through the Pallas forward kernel.

    q/k/v: [BH, L, D] -> (o normalized [BH, L, D] f32, lse [BH, L] f32).
    """
    out, lse = _flash_fwd(q, k, v, scale, causal, bq=blk, bk=blk,
                          interpret=interpret, with_lse=True)
    return out.astype(jnp.float32), lse[:, :, 0]


def _merge_normalized(o_acc, lse_acc, o_t, lse_t):
    """Merge two (normalized out, logsumexp) partials — flash math."""
    m = jnp.maximum(lse_acc, lse_t)
    a = jnp.exp(lse_acc - m)
    b = jnp.exp(lse_t - m)
    denom = jnp.maximum(a + b, 1e-30)
    o = (o_acc * a[..., None] + o_t * b[..., None]) / denom[..., None]
    return o, m + jnp.log(denom)


def _ring_fwd_loop(q, k, v, axis_name, n, causal, scale, blk, interpret):
    """q/k/v in kernel layout [BH, L, D]. Returns (out [BH,L,D], lse [BH,L])."""
    idx = jax.lax.axis_index(axis_name)
    qk = q  # kernels take the query's dtype straight to the MXU
    o_acc = jnp.zeros(q.shape, jnp.float32)
    lse_acc = jnp.full(q.shape[:2], NEG_INF, jnp.float32)

    def skip_fn(_q, _k, _v):
        return (jnp.zeros(_q.shape, jnp.float32),
                jnp.full(_q.shape[:2], NEG_INF, jnp.float32))

    full_fn = functools.partial(_step_fwd, scale=scale, causal=False,
                                blk=blk, interpret=interpret)
    diag_fn = functools.partial(_step_fwd, scale=scale, causal=True,
                                blk=blk, interpret=interpret)

    def step(t, carry):
        o_acc, lse_acc, cur_k, cur_v = carry
        src = (idx - t) % n
        if causal:
            mode = jnp.where(src < idx, 1, jnp.where(src == idx, 2, 0))
        else:
            mode = jnp.ones((), jnp.int32)
        o_t, lse_t = jax.lax.switch(mode, [skip_fn, full_fn, diag_fn],
                                    qk, cur_k, cur_v)
        o_acc, lse_acc = _merge_normalized(o_acc, lse_acc, o_t, lse_t)
        perm = [(i, (i + 1) % n) for i in range(n)]
        cur_k = jax.lax.ppermute(cur_k, axis_name, perm)
        cur_v = jax.lax.ppermute(cur_v, axis_name, perm)
        return o_acc, lse_acc, cur_k, cur_v

    o_acc, lse_acc, _, _ = jax.lax.fori_loop(
        0, n, step, (o_acc, lse_acc, k, v))
    return o_acc, lse_acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_pallas(q, k, v, axis_name, n, causal, scale, blk, interpret):
    out, _ = _ring_fwd_loop(q, k, v, axis_name, n, causal, scale, blk,
                            interpret)
    return out.astype(q.dtype)


def _ring_pallas_fwd(q, k, v, axis_name, n, causal, scale, blk, interpret):
    out, lse = _ring_fwd_loop(q, k, v, axis_name, n, causal, scale, blk,
                              interpret)
    out = out.astype(q.dtype)
    # O(local block) residuals only — the rotated KV copies are recomputed
    # by re-rotating in the backward pass, never stored.
    return out, (q, k, v, out, lse)


def _ring_pallas_bwd(axis_name, n, causal, scale, blk, interpret, res, g):
    q, k, v, out, lse = res
    idx = jax.lax.axis_index(axis_name)
    g = g.astype(q.dtype)

    def zeros_fn(_q, _k, _v, _o, _lse, _g):
        return (jnp.zeros(_q.shape, jnp.float32),
                jnp.zeros(_k.shape, jnp.float32),
                jnp.zeros(_v.shape, jnp.float32))

    def _step_bwd(causal_mode):
        def run(qb, kb, vb, ob, lseb, gb):
            dq, dk, dv = _flash_bwd(qb, kb, vb, ob, lseb, gb, scale,
                                    causal_mode, bq=blk, bk=blk,
                                    interpret=interpret)
            return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                    dv.astype(jnp.float32))
        return run

    full_fn, diag_fn = _step_bwd(False), _step_bwd(True)

    def step(t, carry):
        dq_acc, cur_k, cur_v, dk_acc, dv_acc = carry
        src = (idx - t) % n
        if causal:
            mode = jnp.where(src < idx, 1, jnp.where(src == idx, 2, 0))
        else:
            mode = jnp.ones((), jnp.int32)
        dq_t, dk_t, dv_t = jax.lax.switch(
            mode, [zeros_fn, full_fn, diag_fn], q, cur_k, cur_v, out, lse, g)
        dq_acc = dq_acc + dq_t
        dk_acc = dk_acc + dk_t
        dv_acc = dv_acc + dv_t
        # dK/dV travel WITH their KV blocks: after the full ring pass each
        # block arrives home carrying every device's contribution.
        perm = [(i, (i + 1) % n) for i in range(n)]
        cur_k = jax.lax.ppermute(cur_k, axis_name, perm)
        cur_v = jax.lax.ppermute(cur_v, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        return dq_acc, cur_k, cur_v, dk_acc, dv_acc

    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dq, _, _, dk, dv = jax.lax.fori_loop(0, n, step, (dq, k, v, dk, dv))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_pallas.defvjp(_ring_pallas_fwd, _ring_pallas_bwd)


def _ring_kernel_inner(q, k, v, axis_name: str, axis_size: int,
                       causal: bool = True, scale: float | None = None,
                       impl: str = "pallas"):
    """Pallas-kernel ring pass. q/k/v: [B, L, H, D] local chunks."""
    b, l, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    blk = _ring_block(l)
    # Kernel layout [B*H, L, D] once; rotation happens in this layout too.
    def to_k(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)
    out = _ring_pallas(to_k(q), to_k(k), to_k(v), axis_name, axis_size,
                       causal, scale, blk, impl == "interpret")
    return out.reshape(b, h, l, d).transpose(0, 2, 1, 3)


def ring_attention_inner(q, k, v, axis_name: str, axis_size: int,
                         causal: bool = True, scale: float | None = None,
                         impl: str = "jnp"):
    """Call inside shard_map with seq sharded over `axis_name`.

    q, k, v: [batch, seq_local, heads, head_dim] (kv heads must equal q heads
    here; GQA repeat happens before the call). `axis_size` must be the static
    ring size — the ppermute permutation table is built at trace time.
    """
    if impl in ("pallas", "interpret"):
        return _ring_kernel_inner(q, k, v, axis_name, axis_size,
                                  causal=causal, scale=scale, impl=impl)
    return _ring_jnp_inner(q, k, v, axis_name, axis_size,
                           causal=causal, scale=scale)


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = True,
                   q_spec: P | None = None, impl: str = "auto"):
    """shard_map wrapper: q/k/v sharded [batch, seq/sp, heads, head_dim].

    impl: "auto" (pallas kernels on TPU, jnp elsewhere), "pallas",
    "interpret" (pallas interpreter — CPU tests take the kernel code path),
    "jnp" (pure-jnp oracle).
    """
    from ray_tpu.parallel.sharding import shard_map_compat
    n = mesh.shape[axis_name]
    explicit = impl in ("pallas", "interpret")
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "jnp"
    if impl in ("pallas", "interpret") and _ring_block(q.shape[1] // n) < 8:
        if explicit:
            # Silent downgrade would reintroduce the O(chunk^2) score
            # materialization at exactly the scale the kernel was asked
            # for — fail loudly instead.
            raise ValueError(
                f"ring_attention(impl={impl!r}): local chunk "
                f"{q.shape[1] // n} has no MXU-friendly tile divisor "
                f"(gcd with 512 < 8); pad the sequence so seq/{n} is a "
                f"multiple of 128")
        impl = "jnp"  # auto on CPU-sized toys: jnp oracle is fine
    spec = q_spec if q_spec is not None else P(None, axis_name, None, None)
    fn = functools.partial(ring_attention_inner, axis_name=axis_name,
                           axis_size=n, causal=causal, impl=impl)
    return shard_map_compat(fn, mesh, (spec, spec, spec), spec)(q, k, v)


def reference_attention(q, k, v, causal: bool = True,
                        scale: float | None = None):
    """Unsharded reference for tests: same math, single device."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
