"""Ring attention: blockwise attention with KV rotation over an ICI ring.

Greenfield per SURVEY.md §5.7 — the reference has no sequence/context
parallelism (grep-verified, SURVEY.md:149). Design follows blockwise ring
attention (Liu et al.): the sequence is sharded over the "sp" mesh axis; each
step every device computes flash-style online-softmax attention of its local Q
block against the KV block currently resident, then rotates KV to the next
ring neighbor with `jax.lax.ppermute` (lowered to ICI collective-permute, so
the transfer overlaps the next block's compute under XLA's scheduler).

Communication cost: (sp-1) ppermutes of the local KV block — bandwidth-optimal
for full attention; numerics identical to unsharded attention (same
log-sum-exp accumulation as flash attention, fp32 accumulators).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One Q-block x KV-block flash step. Returns (partial_out, rowmax, rowsum).

    q: [B, Lq, H, D]  k,v: [B, Lk, H, D]  mask: [Lq, Lk] or None (True=keep).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                     # [B,H,Lq]
    # Rows with no visible keys: keep m finite so exp() underflows to 0.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])          # [B,H,Lq,Lk]
    l = jnp.sum(p, axis=-1)                     # [B,H,Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def _merge(acc, o, m, l):
    """Merge a new block into the running (out, max, sum) accumulator."""
    acc_o, acc_m, acc_l = acc
    new_m = jnp.maximum(acc_m, m)
    alpha = jnp.exp(acc_m - new_m)              # rescale old
    beta = jnp.exp(m - new_m)                   # rescale new
    new_l = acc_l * alpha + l * beta
    new_o = (acc_o * alpha[..., None].transpose(0, 2, 1, 3)
             + o * beta[..., None].transpose(0, 2, 1, 3))
    return new_o, new_m, new_l


def ring_attention_inner(q, k, v, axis_name: str, axis_size: int,
                         causal: bool = True, scale: float | None = None):
    """Call inside shard_map with seq sharded over `axis_name`.

    q, k, v: [batch, seq_local, heads, head_dim] (kv heads must equal q heads
    here; GQA repeat happens before the call). `axis_size` must be the static
    ring size — the ppermute permutation table is built at trace time.
    """
    n = axis_size
    idx = jax.lax.axis_index(axis_name)
    lq = q.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)

    rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lq), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lq), 1)
    diag_mask = rows >= cols  # causal mask within the diagonal block

    acc_o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    acc_m = jnp.full(q.shape[:1] + (q.shape[2], lq), NEG_INF, jnp.float32)
    acc_l = jnp.zeros_like(acc_m)

    def step(t, carry):
        acc, cur_k, cur_v = carry
        src_block = (idx - t) % n  # global block id of the resident KV
        if causal:
            # Full mask when src < idx, diagonal mask when ==, all-hidden when >.
            keep_all = src_block < idx
            keep_diag = src_block == idx
            mask = jnp.where(
                keep_all, jnp.ones_like(diag_mask),
                jnp.where(keep_diag, diag_mask, jnp.zeros_like(diag_mask)))
        else:
            mask = None
        o, m, l = _block_attn(qf, cur_k, cur_v, scale, mask)
        acc = _merge(acc, o, m, l)
        # Rotate KV around the ring (ICI collective-permute).
        perm = [(i, (i + 1) % n) for i in range(n)]
        nxt_k = jax.lax.ppermute(cur_k, axis_name, perm)
        nxt_v = jax.lax.ppermute(cur_v, axis_name, perm)
        return acc, nxt_k, nxt_v

    carry = ((acc_o, acc_m, acc_l), k.astype(jnp.float32), v.astype(jnp.float32))
    (acc_o, acc_m, acc_l), _, _ = jax.lax.fori_loop(0, n, step, carry)
    out = acc_o / jnp.maximum(acc_l, 1e-30)[..., None].transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = True,
                   q_spec: P | None = None):
    """shard_map wrapper: q/k/v sharded [batch, seq/sp, heads, head_dim]."""
    from jax import shard_map
    spec = q_spec if q_spec is not None else P(None, axis_name, None, None)
    fn = functools.partial(ring_attention_inner, axis_name=axis_name,
                           axis_size=mesh.shape[axis_name], causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def reference_attention(q, k, v, causal: bool = True,
                        scale: float | None = None):
    """Unsharded reference for tests: same math, single device."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
