"""Collective layer: XLA collectives inside jit + host-side rendezvous.

Replaces the reference's `ray.util.collective` NCCL/GLOO groups
(`util/collective/collective.py:123`, `nccl_collective_group.py:128`): dense
math communication happens INSIDE compiled programs via jax.lax collectives
(ICI); only control-plane rendezvous (actors joining a mesh, barriers) goes
through the object/KV plane, mirroring how the reference uses GCS KV for
NCCL unique-id exchange.
"""

from __future__ import annotations

import time

import jax

# ---- in-program collectives (use inside jit/shard_map) ----

psum = jax.lax.psum
pmean = jax.lax.pmean
pmax = jax.lax.pmax
ppermute = jax.lax.ppermute
all_gather = jax.lax.all_gather
all_to_all = jax.lax.all_to_all


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    return jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


# ---- host-side rendezvous over the runtime KV (control plane) ----

class Barrier:
    """N-party named barrier over the head KV store.

    Used by actor groups gang-entering a jitted SPMD program (the
    "SPMD-vs-actor impedance" in SURVEY.md §7): every member must arrive
    before any proceeds.
    """

    def __init__(self, name: str, world_size: int):
        self.name = name
        self.world_size = world_size
        self._round = 0

    def wait(self, timeout: float = 300.0):
        from ray_tpu.core.runtime import get_runtime, Runtime
        rt = get_runtime()
        self._round += 1
        key = ("barrier", self.name, self._round)

        def kv_incr():
            # Atomic on the head (runtime.kv_incr): a get-then-put here would
            # lose counts when members arrive concurrently.
            if isinstance(rt, Runtime):
                return rt.kv_incr(key)
            return rt.request("kv_incr", key)

        def kv_read():
            if isinstance(rt, Runtime):
                return int(rt.kv.get(key, b"0"))
            return int(rt.request("kv_get", key) or b"0")

        kv_incr()
        deadline = time.monotonic() + timeout
        while kv_read() < self.world_size:
            if time.monotonic() > deadline:
                raise TimeoutError(f"barrier {self.name} timed out")
            time.sleep(0.005)
