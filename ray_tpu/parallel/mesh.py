"""Device mesh construction for dp/fsdp/tp/sp/ep/pp axes.

Parity note: the reference has no mesh concept — its TP/PP degrees are vLLM
engine config consumed for placement only
(`llm/_internal/serve/deployments/llm/vllm/vllm_models.py:123-137`). Here the
mesh IS the parallelism substrate: axes are named, shardings are
PartitionSpecs over them, and XLA/GSPMD inserts the collectives.

Axis conventions (scaling-book style):
- "dp"   pure data parallelism (gradient psum)
- "fsdp" data parallelism + parameter/optimizer sharding (ZeRO-3 via GSPMD)
- "tp"   tensor parallelism (activation all-gather / reduce-scatter on ICI)
- "sp"   sequence/context parallelism (ring attention over an ICI ring)
- "ep"   expert parallelism (MoE all-to-all dispatch)
- "pp"   pipeline stages (ppermute microbatch schedule)
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh


AXES = ("dp", "fsdp", "pp", "sp", "tp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Degrees per axis; -1 on at most one axis = absorb remaining devices."""

    dp: int = 1
    fsdp: int = -1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXES}
        wild = [a for a, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def make_mesh(config: MeshConfig | None = None, devices=None,
              axis_names=AXES) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    On real TPU slices jax's device order already follows the physical torus,
    so adjacent mesh coordinates are ICI neighbors; the "sp" and "tp" axes
    land on rings, which is what ring attention and tensor collectives want.
    For multi-host meshes prefer jax.experimental.mesh_utils via
    make_hybrid_mesh().
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig(fsdp=len(devices))
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_names)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def make_hybrid_mesh(config: MeshConfig, dcn_axes=("dp", "pp")) -> Mesh:
    """Multi-slice mesh: DCN-crossing axes outermost, ICI axes within a slice.

    Uses mesh_utils.create_hybrid_device_mesh so slow DCN hops only carry the
    dp/pp traffic (gradient psum, stage boundaries), never tp/sp collectives.
    """
    from jax.experimental import mesh_utils
    devices = jax.devices()
    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices <= 1:
        return make_mesh(config, devices=devices)
    sizes = config.resolve(len(devices))
    # Split each dcn axis between slices (outer) and within-slice (inner):
    # the slice count must factor entirely into the dcn axes, otherwise an
    # ICI axis would be forced across DCN — refuse rather than mis-lay.
    dcn = {a: 1 for a in AXES}
    rem = num_slices
    for a in dcn_axes:
        g = math.gcd(sizes[a], rem)
        dcn[a] = g
        rem //= g
    if rem != 1:
        raise ValueError(
            f"{num_slices} slices do not factor into dcn axes "
            f"{({a: sizes[a] for a in dcn_axes})}; an ICI axis "
            f"({[a for a in AXES if a not in dcn_axes]}) would cross DCN")
    ici_shape = tuple(sizes[a] // dcn[a] for a in AXES)
    arr = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_mesh_shape=tuple(dcn[a] for a in AXES),
        devices=devices)
    return Mesh(arr, AXES)


def elastic_config(config: MeshConfig, n_devices: int) -> MeshConfig:
    """Refit a mesh config to a new device count (the gang re-mesh after a
    worker/host death). Model-parallel axes (tp/sp/ep/pp) are baked into
    the program's shardings and kept fixed; the DATA axes (dp, fsdp)
    absorb the change — dp keeps the largest divisor of its old degree
    that fits, fsdp takes the rest. Raises if the model axes alone no
    longer fit (a tp=4 program cannot re-mesh onto 2 chips)."""
    model = 1
    for a in ("pp", "sp", "tp", "ep"):
        model *= max(getattr(config, a), 1)
    if n_devices % model:
        raise ValueError(
            f"cannot re-mesh onto {n_devices} devices: model axes need "
            f"multiples of {model} "
            f"(pp={config.pp} sp={config.sp} tp={config.tp} ep={config.ep})")
    data = n_devices // model
    old_dp = max(config.dp, 1)
    dp = math.gcd(old_dp, data)
    return dataclasses.replace(config, dp=dp, fsdp=data // dp)


_current_mesh: Mesh | None = None


def set_global_mesh(mesh: Mesh):
    global _current_mesh
    _current_mesh = mesh


def get_abstract_mesh() -> Mesh | None:
    return _current_mesh
