"""Parallelism layer: device meshes, GSPMD shardings, ICI collectives,
sequence/context parallelism (ring attention, Ulysses), pipeline stages.

This layer replaces the reference's NCCL/GLOO collective plane
(`python/ray/util/collective/`) with XLA collectives over ICI: everything runs
inside jit over a `jax.sharding.Mesh`, so XLA lowers communication to ICI
transfers and overlaps it with compute.
"""

from ray_tpu.parallel.mesh import (MeshConfig, elastic_config,
                                   get_abstract_mesh, make_mesh)
from ray_tpu.parallel.sharding import (
    ShardingRules,
    logical_to_physical,
    reshard,
    shard_params,
    with_sharding,
)
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "MeshConfig", "make_mesh", "elastic_config", "get_abstract_mesh",
    "ShardingRules", "logical_to_physical", "shard_params", "reshard",
    "with_sharding", "ring_attention", "ulysses_attention",
]
