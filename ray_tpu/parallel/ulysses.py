"""Ulysses-style sequence parallelism: all-to-all head<->sequence resharding.

Greenfield per SURVEY.md §5.7/§2.4. Instead of rotating KV (ring), each device
trades its sequence shard for a head shard with one `jax.lax.all_to_all`
(ICI), runs full-sequence attention on heads/sp local heads, and trades back.
Cheaper than ring when heads >= sp and sequence fits per-device after the
swap; ring wins for extreme context lengths. Both are exposed as
`context_parallel_attention` strategies in the trainer.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.ring_attention import reference_attention


def ulysses_attention_inner(q, k, v, axis_name: str, causal: bool = True):
    """Inside shard_map: q/k/v [batch, seq_local, heads, head_dim]."""
    # seq-sharded -> head-sharded: split heads axis (2), gather seq axis (1).
    def swap_in(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def swap_out(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = swap_in(q), swap_in(k), swap_in(v)   # [B, S_full, H/sp, D]
    out = reference_attention(qh, kh, vh, causal=causal)
    return swap_out(out)                               # [B, S/sp, H, D]


def ulysses_attention(q, k, v, mesh, axis_name: str = "sp",
                      causal: bool = True):
    from ray_tpu.parallel.sharding import shard_map_compat
    spec = P(None, axis_name, None, None)
    fn = functools.partial(ulysses_attention_inner, axis_name=axis_name,
                           causal=causal)
    return shard_map_compat(fn, mesh, (spec, spec, spec), spec)(q, k, v)
