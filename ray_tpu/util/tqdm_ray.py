"""Cluster-safe progress bars.

Parity: reference `python/ray/experimental/tqdm_ray.py` — worker-side bars
forward state to the driver instead of fighting over the terminal. Here:
the driver renders a real tqdm; workers report through the head KV, and
the driver-side bar (if any is open for the same desc) folds remote
updates in on refresh. Standalone worker bars degrade to throttled log
lines in the worker's log file.
"""

from __future__ import annotations

import sys
import time

_KV_PREFIX = "__tqdm__:"


def _is_driver() -> bool:
    from ray_tpu.core.runtime import Runtime, current_runtime
    return isinstance(current_runtime(), Runtime)


class tqdm:
    """Drop-in subset of tqdm.tqdm: iterable wrapping, update, close."""

    def __init__(self, iterable=None, desc: str = "", total: int | None = None,
                 unit: str = "it", flush_interval_s: float = 0.5):
        self._iterable = iterable
        self.desc = desc or "progress"
        self.total = total if total is not None else (
            len(iterable) if hasattr(iterable, "__len__") else None)
        self.unit = unit
        self.n = 0
        self._flush_every = flush_interval_s
        self._last_flush = 0.0
        self._driver = _is_driver()
        self._bar = None
        if self._driver:
            import tqdm as _tqdm_mod
            self._bar = _tqdm_mod.tqdm(desc=self.desc, total=self.total,
                                       unit=unit, file=sys.stderr)

    def __iter__(self):
        for x in self._iterable:
            yield x
            self.update(1)
        self.close()

    def update(self, n: int = 1):
        self.n += n
        now = time.monotonic()
        if self._bar is not None:
            self._bar.update(n)
        elif now - self._last_flush >= self._flush_every:
            self._last_flush = now
            self._report()

    def _report(self):
        total = f"/{self.total}" if self.total else ""
        print(f"[{self.desc}] {self.n}{total} {self.unit}", flush=True)
        try:
            from ray_tpu.experimental.internal_kv import _internal_kv_put
            _internal_kv_put(f"{_KV_PREFIX}{self.desc}",
                             str(self.n).encode())
        except Exception:  # noqa: BLE001 — progress is best effort
            pass

    def close(self):
        if self._bar is not None:
            self._bar.close()
        elif self.n:
            self._last_flush = 0.0
            self._report()


def safe_print(*args, **kwargs):
    """Print without tearing an open driver bar (parity: tqdm_ray.safe_print)."""
    try:
        import tqdm as _tqdm_mod
        _tqdm_mod.tqdm.write(" ".join(str(a) for a in args))
    except Exception:  # noqa: BLE001
        print(*args, **kwargs)
