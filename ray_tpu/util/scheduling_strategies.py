"""Scheduling strategies attached to task/actor options.

Parity: reference `python/ray/util/scheduling_strategies.py:15,41,135`
(PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy and the
"DEFAULT"/"SPREAD" string strategies). TPU-native addition: strategies are
plain picklable records interpreted by the head scheduler; the
ICI_CONTIGUOUS placement-group strategy maps bundles onto topologically
contiguous TPU sub-slices.
"""

from __future__ import annotations


class PlacementGroupSchedulingStrategy:
    """Run the task/actor inside a placement-group bundle's reservation."""

    __slots__ = ("placement_group", "placement_group_bundle_index",
                 "placement_group_capture_child_tasks")

    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks)

    def __reduce__(self):
        return (PlacementGroupSchedulingStrategy,
                (self.placement_group, self.placement_group_bundle_index,
                 self.placement_group_capture_child_tasks))


class NodeAffinitySchedulingStrategy:
    """Pin to a node (parity: scheduling_strategies.py:135). On the
    single-node runtime every node id resolves to the head; the multi-node
    plane honors it for real."""

    __slots__ = ("node_id", "soft")

    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def __reduce__(self):
        return (NodeAffinitySchedulingStrategy, (self.node_id, self.soft))


DEFAULT = "DEFAULT"
SPREAD = "SPREAD"
