"""Client mode: remote drivers over TCP (the `ray://` role).

Parity: reference `python/ray/util/client/` — a driver process OUTSIDE the
cluster speaks to the head over one TCP connection and gets the full task/
actor/object API. Redesign: instead of a dedicated gRPC proxy server
(`util/client/server/`), the client speaks the native worker frame protocol
over the head's existing cluster endpoint; the head inlines every object
value over the wire (a client has no node-local shm store).

    import ray_tpu
    ray_tpu.init(address="10.0.0.1:6379")   # from any machine
"""

from __future__ import annotations

import socket
import threading

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID, WorkerID
from ray_tpu.core.status import RayTpuError
from ray_tpu.core.transport import recv_msg, send_msg
from ray_tpu.core.worker import WorkerRuntime


class ClientRuntime(WorkerRuntime):
    """Store-free WorkerRuntime over TCP: all values travel inline."""

    is_client = True

    def __init__(self, address: str):
        host, _, port = address.rpartition(":")
        sock = socket.create_connection((host or "127.0.0.1", int(port)))
        super().__init__(sock, WorkerID.from_random(), store_path="")
        self._connected = True
        send_msg(sock, ("client_hello", self.worker_id.binary()),
                 self.send_lock)
        self._receiver = threading.Thread(target=self._recv_loop,
                                          daemon=True, name="rtpu-client-rx")
        self._receiver.start()

    def _recv_loop(self):
        while True:
            try:
                msg = recv_msg(self.sock)
            except OSError:
                msg = None
            if msg is None:
                self._connected = False
                # Unblock every waiter with a connection error.
                with self._wait_lock:
                    pending = list(self._pending_waits.items())
                    self._pending_waits.clear()
                for oid, evs in pending:
                    self.object_cache[oid] = RayTpuError(
                        "client connection to the head was lost")
                    for ev in evs:
                        ev.set()
                with self._req_lock:
                    futs = list(self._req_futures.values())
                    self._req_futures.clear()
                for fut in futs:
                    fut.set_exception(RayTpuError(
                        "client connection to the head was lost"))
                return
            try:
                self.handle_push(msg)
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()

    # -- store-free object plane --

    @property
    def store(self):
        raise RayTpuError("client mode has no local object store")

    # No node-local arena: args always ride the frame inline, and the
    # direct-call relaxation for locally-sealed deps never applies.
    put_arg_object = None

    def deps_ready_local(self, refs):
        return False

    def request(self, what, arg=None, timeout=30.0):
        if not self._connected:
            raise RayTpuError("client connection to the head was lost")
        return super().request(what, arg, timeout)

    def put(self, value):
        from ray_tpu.core.jobs import current_job_id
        from ray_tpu.core.object_ref import ObjectRef
        payload, bufs, _refs = serialization.serialize_value(value)
        # Third element = owning job (client processes carry it in
        # RAY_TPU_JOB_ID); old heads that read only (payload, bufs)
        # unpack by index and never see it.
        oid = self.request(
            "client_put", (payload, bufs, current_job_id(rt=self)),
            timeout=120.0)
        return ObjectRef(ObjectID(oid), _add_ref=False)

    def _get_one(self, ref, timeout=None):
        oid = ref.id.binary()
        _MISS = object()
        cached = self.object_cache.get(oid, _MISS)
        if cached is not _MISS:
            return self._raise_if_error(cached)
        if not self._connected:
            raise RayTpuError("client connection to the head was lost")
        ev = threading.Event()
        with self._wait_lock:
            self._pending_waits.setdefault(oid, []).append(ev)
        self.send(("wait_obj", oid))
        if not ev.wait(timeout):
            from ray_tpu.core.status import GetTimeoutError
            raise GetTimeoutError(f"get() timed out on {ref}")
        cached = self.object_cache.get(oid, _MISS)
        if cached is not _MISS:
            return self._raise_if_error(cached)
        raise RayTpuError(f"head pushed no value for {ref}")

    def wait(self, refs, num_returns=1, timeout=None):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        ready_ids = self.request(
            "client_wait",
            ([r.id.binary() for r in refs], num_returns, timeout),
            timeout=None if timeout is None else timeout + 10.0)
        ready_set = set(ready_ids)
        ready = [r for r in refs if r.id.binary() in ready_set]
        not_ready = [r for r in refs if r.id.binary() not in ready_set]
        return ready[:num_returns], ready[num_returns:] + not_ready

    def disconnect(self):
        self._connected = False
        try:
            self.sock.close()
        except OSError:
            pass
