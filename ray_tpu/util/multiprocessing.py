"""multiprocessing.Pool shim over ray_tpu tasks.

Parity: reference `python/ray/util/multiprocessing/pool.py` — the stdlib
Pool surface (apply/apply_async/map/map_async/starmap/imap/imap_unordered)
with every call running as a task on the cluster instead of a forked local
process.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable

import ray_tpu


class AsyncResult:
    """stdlib-shaped handle over one or many object refs."""

    def __init__(self, refs, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        self._result = None
        self._error = None
        self._done = threading.Event()

        def waiter():
            try:
                out = ray_tpu.get(self._refs, timeout=None)
                self._result = out[0] if single else out
                if callback is not None:
                    callback(self._result)
            except BaseException as e:  # noqa: BLE001 — stored for .get()
                self._error = e
                if error_callback is not None:
                    error_callback(e)
            finally:
                self._done.set()

        threading.Thread(target=waiter, daemon=True).start()

    def get(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError("AsyncResult.get timed out")
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: float | None = None):
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result not ready")
        return self._error is None


class Pool:
    """Task-backed process pool (parity: ray.util.multiprocessing.Pool).

    `processes` bounds in-flight chunks, not OS processes — the runtime's
    worker pool does the actual process management.
    """

    def __init__(self, processes: int | None = None, initializer=None,
                 initargs=(), ray_address: str | None = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=ray_address) if ray_address \
                else ray_tpu.init()
        self._processes = processes or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        self._closed = False
        self._initializer = initializer
        self._initargs = tuple(initargs)

        init = self._initializer
        iargs = self._initargs

        @ray_tpu.remote
        def _run_chunk(fn, chunk, star):
            if init is not None:
                # Stdlib runs the initializer once per process; worker
                # reuse makes per-chunk idempotent initializers the
                # equivalent here.
                init(*iargs)
            if star:
                return [fn(*args) for args in chunk]
            return [fn(*args) if isinstance(args, tuple) else fn(args)
                    for args in chunk]

        self._run_chunk = _run_chunk

    # -- helpers --

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def _chunks(self, iterable: Iterable, chunksize: int | None):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], len(items)

    def _submit(self, fn, chunks, star: bool):
        return [self._run_chunk.remote(fn, c, star) for c in chunks]

    # -- stdlib surface --

    def apply(self, fn: Callable, args=(), kwds=None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args=(), kwds=None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}

        @ray_tpu.remote
        def _run_one():
            return fn(*args, **kwds)

        return AsyncResult([_run_one.remote()], single=True,
                           callback=callback, error_callback=error_callback)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: int | None = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_open()
        chunks, _n = self._chunks(iterable, chunksize)
        refs = self._submit(fn, chunks, star=False)

        flat_cb = None
        if callback is not None:
            def flat_cb(parts):
                callback(list(itertools.chain.from_iterable(parts)))
        res = AsyncResult(refs, single=False, callback=flat_cb,
                          error_callback=error_callback)
        orig_get = res.get

        def get(timeout=None):
            return list(itertools.chain.from_iterable(orig_get(timeout)))
        res.get = get
        return res

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: int | None = None) -> list:
        self._check_open()
        chunks, _n = self._chunks(iterable, chunksize)
        refs = self._submit(fn, chunks, star=True)
        parts = ray_tpu.get(refs, timeout=None)
        return list(itertools.chain.from_iterable(parts))

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check_open()
        chunks, _n = self._chunks(iterable, chunksize)
        refs = self._submit(fn, chunks, star=False)
        for ref in refs:  # submission order
            yield from ray_tpu.get(ref, timeout=None)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check_open()
        chunks, _n = self._chunks(iterable, chunksize)
        refs = self._submit(fn, chunks, star=False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1,
                                          timeout=None)
            for ref in ready:
                yield from ray_tpu.get(ref, timeout=None)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
