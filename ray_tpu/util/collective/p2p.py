"""P2P host collectives over the native object plane (no head on the path).

Parity: the reference's GLOO groups move tensors peer-to-peer
(`util/collective/collective_group/gloo_collective_group.py:184`); here the
transport is each node's native peer server (`_native/peer_server.cpp`) —
the same zero-copy arena pulls the object plane already uses.

Protocol: collective payloads are published into the publisher's LOCAL
shared-memory arena under DETERMINISTIC object ids
(sha256(group | seq | tag | rank)[:16]) that every member derives without
communication. A consumer polls `objxfer.fetch_from_peer` against the
publisher node's peer port until the object appears, pulls it into its own
arena (same-node ranks short-circuit on `store.contains`), reads it, and
moves on. The head is involved ONLY at group setup (one KV exchange builds
the rank -> peer-address table); steady-state ops cost ZERO head messages.

Lifetime/cleanup: every op ends with a tiny rank-0-rooted fin barrier
(peer traffic, not head traffic), so when an op returns EVERY member has
finished it; with two generations retained, `begin_op` can only ever
delete objects from an op the whole group left behind. Authoritative
copies are always rank-keyed (a tree node RE-publishes the payload under
its own id for its children), and user-facing results are copied out of
the arena at the API boundary.

Topologies:
- broadcast: binary tree rooted at src — O(log n) depth, one tensor per
  link, so bandwidth stays flat as the world grows.
- allreduce / allgather: bandwidth-optimal ring (reduce-scatter +
  allgather: 2*(n-1)/n x tensor per link regardless of world size).
"""

from __future__ import annotations

import hashlib
import time

import numpy as np

from ray_tpu.core.ids import ObjectID


def _oid(group: str, seq: int, tag: str, rank: int) -> bytes:
    h = hashlib.sha256(
        f"p2pcoll|{group}|{seq}|{tag}|{rank}".encode()).digest()
    return h[:16]


class P2PTransport:
    """Store/peer plumbing for one group member."""

    def __init__(self, group: str, rank: int, addrs: list):
        from ray_tpu.core.runtime import get_runtime
        rt = get_runtime()
        self.group = group
        self.rank = rank
        self.addrs = addrs           # rank -> (host, port) peer endpoint
        self.store = rt.store
        self._held: list[bytes] = []        # current op's oids (own + pulled)
        self._last_gen: list[bytes] = []    # previous op's oids (lazy free)

    def begin_op(self):
        """Free the previous op's objects: its fin acks proved every direct
        consumer read them before that op returned."""
        for oid in self._last_gen:
            try:
                self.store.delete(ObjectID(oid))
            except Exception:  # noqa: BLE001 — freeing is best effort
                pass
        self._last_gen = self._held
        self._held = []

    def publish(self, oid: bytes, value) -> None:
        # Straight into the arena: numpy buffers ride pickle-5 out-of-band
        # through put_serialized, so the payload is written once (no
        # intermediate blob copy) and peers pull it zero-copy.
        self.store.put_serialized(ObjectID(oid),
                                  np.ascontiguousarray(value))
        self._held.append(oid)

    def fetch(self, oid: bytes, src_rank: int, timeout: float = 300.0):
        """Poll the publisher's node until the object exists, pull it into
        the local arena, and deserialize. Same-node publishers (including
        self) short-circuit on the shared arena. The poll rides ONE
        persistent peer connection per attempt (absent_wait_s), not a
        reconnect per probe.

        The returned array may alias the shared arena (zero-copy read);
        internal consumers reduce out of it immediately, and user-facing
        results are copied at the API boundary."""
        from ray_tpu.core import objxfer
        deadline = time.monotonic() + timeout
        addr = self.addrs[src_rank]
        ref = ObjectID(oid)
        while True:
            if self.store.contains(ref):
                break
            try:
                if addr is not None and objxfer.fetch_from_peer(
                        self.store, tuple(addr), oid,
                        absent_wait_s=min(
                            2.0, max(0.1,
                                     deadline - time.monotonic()))):
                    break
            except OSError:
                time.sleep(0.005)  # peer restarting — reconnect shortly
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"p2p collective fetch timed out on rank {src_rank} "
                    f"({self.group})")
        found, val = self.store.get_deserialized(ref, timeout=5.0)
        if not found:
            raise RuntimeError("p2p collective object vanished mid-read")
        if oid not in self._held:
            # Pulled copies are transient caches: free with this gen.
            self._held.append(oid)
        return val

    def finish(self, seq: int):
        """End-of-op barrier, rank-0-rooted: everyone publishes a fin;
        rank 0 collects all fins and publishes an all-done token; everyone
        waits for it. After this returns, EVERY rank has finished the op,
        so the op's objects are safely deletable one op later (two
        generations are retained regardless). Per-rank cost is O(1) tiny
        messages (rank 0 pays O(world) tiny fetches); no head involvement."""
        self.publish(_oid(self.group, seq, "fin", self.rank), 0)
        world = len(self.addrs)
        if self.rank == 0:
            for r in range(1, world):
                self.fetch(_oid(self.group, seq, "fin", r), r)
            self.publish(_oid(self.group, seq, "alldone", 0), 0)
        else:
            self.fetch(_oid(self.group, seq, "alldone", 0), 0)

    def destroy(self):
        for oid in self._last_gen + self._held:
            try:
                self.store.delete(ObjectID(oid))
            except Exception:  # noqa: BLE001
                pass
        self._last_gen, self._held = [], []


def _tree_children(vrank: int, world: int) -> list[int]:
    return [c for c in (2 * vrank + 1, 2 * vrank + 2) if c < world]


def tree_broadcast(tp: P2PTransport, seq: int, value, src_rank: int,
                   world: int):
    """Binary-tree broadcast re-rooted at src (virtual rank 0 == src)."""
    tp.begin_op()
    vrank = (tp.rank - src_rank) % world
    if vrank == 0:
        out = np.asarray(value)
    else:
        parent_v = (vrank - 1) // 2
        parent = (parent_v + src_rank) % world
        out = np.asarray(tp.fetch(_oid(tp.group, seq, "bc", parent),
                                  parent))
    children = [(c + src_rank) % world for c in _tree_children(vrank, world)]
    if children:
        # Authoritative copy for MY children under MY id: rank-keyed
        # ownership keeps same-node ranks' cleanups independent.
        tp.publish(_oid(tp.group, seq, "bc", tp.rank), out)
    tp.finish(seq)
    # Boundary copy: the fetched array may alias the shared arena, whose
    # object is freed an op later — the caller must own its result.
    return np.array(out, copy=True)


def ring_allreduce(tp: P2PTransport, seq: int, value, world: int,
                   reducer):
    """Bandwidth-optimal ring: reduce-scatter then allgather."""
    tp.begin_op()
    arr = np.asarray(value)
    if world == 1:
        return arr
    chunks = np.array_split(arr.reshape(-1), world)
    acc = [c.copy() for c in chunks]
    r = tp.rank
    prev = (r - 1) % world
    nxt = (r + 1) % world
    # reduce-scatter: at step t publish the chunk that entered the ring at
    # rank (r - t); pull the one that entered at (prev - t).
    for t in range(world - 1):
        tp.publish(_oid(tp.group, seq, f"rs{t}", r), acc[(r - t) % world])
        inc = tp.fetch(_oid(tp.group, seq, f"rs{t}", prev), prev)
        c = (prev - t) % world
        acc[c] = reducer([acc[c], np.asarray(inc)])
    # allgather: rank r owns the fully-reduced chunk (r + 1) % world.
    for t in range(world - 1):
        tp.publish(_oid(tp.group, seq, f"ag{t}", r), acc[(r + 1 - t) % world])
        acc[(r - t) % world] = np.asarray(
            tp.fetch(_oid(tp.group, seq, f"ag{t}", prev), prev))
    tp.finish(seq)
    out = np.concatenate([np.asarray(c) for c in acc])
    return out.reshape(arr.shape).astype(arr.dtype, copy=False)


def ring_allgather(tp: P2PTransport, seq: int, value, world: int) -> list:
    """Each rank's tensor visits every other rank once around the ring."""
    tp.begin_op()
    out: list = [None] * world
    out[tp.rank] = np.asarray(value)
    if world == 1:
        return out
    r = tp.rank
    prev = (r - 1) % world
    cur = out[r]
    src = r
    for t in range(world - 1):
        tp.publish(_oid(tp.group, seq, f"g{t}", r), cur)
        cur = np.asarray(tp.fetch(_oid(tp.group, seq, f"g{t}", prev), prev))
        src = (src - 1) % world
        out[src] = cur
    tp.finish(seq)
    # Boundary copies: gathered entries may alias the shared arena.
    return [np.array(x, copy=True) for x in out]
