"""Host-side collective groups over the KV + shared-memory object plane.

Protocol: every member of a group calls collectives in the same order (the
standard collective contract, same as the reference's NCCL groups). Each
call takes a fresh sequence number; contributions are published under
(group, seq, rank) — small ones directly in the head KV, large ones in the
shm object store with the KV carrying the ObjectRef — and a done-counter
deletes the round's keys after every member has read them.

Parity: reference `util/collective/collective.py` API surface;
`gloo_collective_group.py:184` role (CPU/host backend). The rendezvous-
via-KV design mirrors how the reference exchanges NCCL unique ids through
the GCS KV.

Two transports, picked per op:
- KV path: tiny payloads (< 32 KiB) round-trip the head's KV — one hop
  beats ring latency for scalars/barriers.
- P2P path (allreduce / broadcast / allgather of larger tensors): ring /
  binary-tree topologies over each node's native peer server
  (`util/collective/p2p.py`) — ZERO head messages per op after a one-time
  rank->address rendezvous, bandwidth-optimal and flat as the world grows
  (parity: the reference's p2p GLOO groups,
  `gloo_collective_group.py:184`).

SCOPE BOUNDARY: dense-math collectives INSIDE a jit-compiled program
(allreduce of model tensors, all-to-all of activations) belong to
jax.lax over ICI — that is the framework's data plane, and it never
touches this module (SURVEY §5.8: the collective plane is XLA's, not a
library's). This module is the HOST-side plane: weight broadcast to
runners, rendezvous, barriers, metric exchange.
"""

from __future__ import annotations

import pickle
import time

import numpy as np



class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda vals: np.sum(vals, axis=0),
    ReduceOp.PRODUCT: lambda vals: np.prod(vals, axis=0),
    ReduceOp.MIN: lambda vals: np.min(vals, axis=0),
    ReduceOp.MAX: lambda vals: np.max(vals, axis=0),
}


class _KV:
    """Uniform KV client: direct dict on the head, request RPC on workers.

    `ops` counts head round-trips issued by THIS process's collectives —
    tests assert the p2p path leaves it untouched per op."""

    ops = 0  # class-wide head-hop counter (per process)

    def __init__(self):
        from ray_tpu.core.runtime import Runtime, get_runtime
        self._rt = get_runtime()
        self._head = isinstance(self._rt, Runtime)

    def put(self, key, value: bytes):
        _KV.ops += 1
        if self._head:
            with self._rt.lock:
                self._rt.kv[key] = value
        else:
            self._rt.request("kv_put", (key, value))

    def get(self, key):
        _KV.ops += 1
        if self._head:
            return self._rt.kv.get(key)
        return self._rt.request("kv_get", key)

    def delete(self, key):
        _KV.ops += 1
        if self._head:
            self._rt.kv.pop(key, None)
        else:
            self._rt.request("kv_del", key)

    def incr(self, key) -> int:
        _KV.ops += 1
        if self._head:
            return self._rt.kv_incr(key)
        return self._rt.request("kv_incr", key)

    def wait(self, key, timeout: float = 300.0) -> bytes:
        deadline = time.monotonic() + timeout
        delay = 0.0005
        while True:
            v = self.get(key)
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective rendezvous timed out on {key}")
            time.sleep(delay)
            delay = min(delay * 2, 0.01)


def _blob(value) -> bytes:
    """Serialize a contribution. Values ride the KV directly: the transport
    frames numpy buffers out-of-band, the head holds each round's bytes only
    until the done-counter deletes them, and no object-store ref lifetime is
    in play (an earlier shm-ref design freed contributions before peers read
    them)."""
    return pickle.dumps(np.asarray(value), protocol=5)


def _unblob(blob: bytes):
    return pickle.loads(blob)


class _Group:
    def __init__(self, name: str, world_size: int, rank: int, backend: str):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.seq = 0
        self.p2p_seq: dict[tuple[int, int], int] = {}
        self.kv = _KV()
        self._p2p = None         # lazy P2PTransport
        self._p2p_failed = False

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    # -- p2p transport over the object plane ------------------------------

    _P2P_MIN_BYTES = 32 << 10  # tiny payloads: one KV hop beats ring RTTs

    def _my_peer_addr(self):
        from ray_tpu.core.runtime import Runtime
        rt = self.kv._rt
        if isinstance(rt, Runtime):
            return getattr(rt, "head_peer_addr", None)
        try:
            return rt.request("my_peer_addr")
        except Exception:  # noqa: BLE001
            return None

    def p2p_for(self, arr, force: bool = False):
        """The peer-to-peer transport, when this op should bypass the head:
        payload large enough (or `force` — broadcast receivers may hold
        placeholder buffers of any size, so its routing must not depend on
        the local tensor), backend not pinned to 'kv', and every member
        reachable over a peer endpoint. Symmetric-op routing relies on the
        standard collective contract: allreduce/allgather contributions
        have the same shape AND dtype on every rank, so the size gate
        decides identically everywhere. The rank->address table is built
        ONCE via the KV — the only head involvement p2p ops ever have."""
        if self.backend == "kv" or self._p2p_failed:
            return None
        if not force and getattr(arr, "nbytes", 0) < self._P2P_MIN_BYTES:
            return None
        if self._p2p is None:
            import os

            from ray_tpu.util.collective import p2p
            mine = self._my_peer_addr()
            # Rank 0's nonce salts every object id: a re-created group
            # (same name, fresh seq) must never alias a dead generation's
            # leftover objects in a shared arena.
            nonce = os.urandom(8).hex()
            enc = ("" if mine is None
                   else f"{mine[0]}:{int(mine[1])}|{nonce}")
            table = self.exchange(enc)  # contributions ride as strings
            decoded = [str(np.asarray(t).item()) for t in table]
            if any(not a for a in decoded):
                # A member without a peer endpoint (cluster server off):
                # stay on the KV path for this group's lifetime.
                self._p2p_failed = True
                return None
            gen = decoded[0].rsplit("|", 1)[1]
            addrs = []
            for a in decoded:
                hostport = a.rsplit("|", 1)[0]
                host, port = hostport.rsplit(":", 1)
                addrs.append((host, int(port)))
            self._p2p = p2p.P2PTransport(f"{self.name}#{gen}", self.rank,
                                         addrs)
        return self._p2p

    # -- rounds ----------------------------------------------------------

    def _key(self, *parts):
        return ("coll", self.name) + parts

    def exchange(self, value, fetch: bool = True):
        """All-to-all publish+read for one round; returns all contributions
        in rank order (None when fetch=False — rooted ops like reduce() skip
        the O(world) download on non-root ranks). Cleanup by the member whose
        done-increment completes the round: a rank only increments after it
        has finished reading, so keys are never deleted under a reader."""
        seq = self.next_seq()
        self.kv.put(self._key(seq, "d", self.rank), _blob(value))
        vals = None
        if fetch:
            vals = [
                _unblob(self.kv.wait(self._key(seq, "d", r)))
                for r in range(self.world_size)
            ]
        if self.kv.incr(self._key(seq, "done")) == self.world_size:
            for r in range(self.world_size):
                self.kv.delete(self._key(seq, "d", r))
            self.kv.delete(self._key(seq, "done"))
        return vals

    def one_to_all(self, value, src_rank: int):
        seq = self.next_seq()
        if self.rank == src_rank:
            self.kv.put(self._key(seq, "b"), _blob(value))
        out = _unblob(self.kv.wait(self._key(seq, "b")))
        if self.kv.incr(self._key(seq, "done")) == self.world_size:
            self.kv.delete(self._key(seq, "b"))
            self.kv.delete(self._key(seq, "done"))
        return out

    def barrier(self, timeout: float = 300.0):
        # A barrier is exchange(None): publish arrival, wait for all, with
        # _KV.wait's backoff and the shared cleanup protocol.
        self.exchange(None)


_groups: dict[str, _Group] = {}


def init_collective_group(world_size: int, rank: int,
                          backend: str = "shm",
                          group_name: str = "default") -> None:
    """Join a named collective group (parity: collective.py:123). Call once
    per member process with a distinct rank in [0, world_size)."""
    if backend not in ("shm", "kv", "gloo"):
        raise ValueError(f"unknown backend {backend!r}; host backend is 'shm'")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    if group_name in _groups:
        raise RuntimeError(f"group {group_name!r} already initialized here")
    _groups[group_name] = _Group(group_name, world_size, rank, backend)


def join_group(group_name: str, world_size: int,
               backend: str = "shm", timeout: float = 300.0) -> int:
    """Rank-free join: arrival order assigns ranks via an atomic KV counter,
    then a barrier gang-releases the full group. The actor-mesh rendezvous
    primitive (SURVEY §7 hard-part 3: SPMD-vs-actor impedance)."""
    kv = _KV()
    rank = kv.incr(("coll", group_name, "join")) - 1
    if rank >= world_size:
        raise RuntimeError(
            f"group {group_name!r} already has {world_size} members")
    init_collective_group(world_size, rank, backend, group_name)
    g = _groups[group_name]
    g.barrier(timeout)
    # Last member out of the barrier retires the join counter so the group
    # name is reusable by a later generation.
    if kv.incr(("coll", group_name, "join_done")) == world_size:
        kv.delete(("coll", group_name, "join"))
        kv.delete(("coll", group_name, "join_done"))
    return rank


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop(group_name, None)
    if g is not None and g._p2p is not None:
        g._p2p.destroy()


def _group(group_name: str) -> _Group:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            f"process; call init_collective_group() first")
    return g


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def _writeback(tensor, result):
    """In-place semantics for writable numpy tensors (parity: the reference
    mutates torch tensors); jax/immutable inputs rely on the return value."""
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        tensor[...] = result
    return result


def allreduce(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    g = _group(group_name)
    arr = np.asarray(tensor)
    tp = g.p2p_for(arr)
    if tp is not None:
        from ray_tpu.util.collective import p2p
        out = p2p.ring_allreduce(tp, g.next_seq(), arr, g.world_size,
                                 _REDUCERS[op])
        return _writeback(tensor, out)
    vals = g.exchange(tensor)
    return _writeback(tensor, _REDUCERS[op](np.stack(
        [np.asarray(v) for v in vals])))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM):
    g = _group(group_name)
    vals = g.exchange(tensor, fetch=(g.rank == dst_rank))
    if g.rank != dst_rank:
        return tensor
    return _writeback(tensor, _REDUCERS[op](np.stack(
        [np.asarray(v) for v in vals])))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _group(group_name)
    arr = np.asarray(tensor)
    # force=True: receivers legally hold placeholders of any size, so the
    # routing decision must not read the local tensor.
    tp = g.p2p_for(arr, force=True)
    if tp is not None:
        from ray_tpu.util.collective import p2p
        out = p2p.tree_broadcast(tp, g.next_seq(), arr, src_rank,
                                 g.world_size)
        return _writeback(tensor, out)
    out = g.one_to_all(tensor, src_rank)
    return _writeback(tensor, out)


def allgather(tensor_list, tensor, group_name: str = "default"):
    """Gather every rank's `tensor` into `tensor_list` (reference
    signature); also returns the list."""
    g = _group(group_name)
    arr = np.asarray(tensor)
    tp = g.p2p_for(arr)
    if tp is not None:
        from ray_tpu.util.collective import p2p
        vals = p2p.ring_allgather(tp, g.next_seq(), arr, g.world_size)
    else:
        vals = g.exchange(tensor)
    if tensor_list is not None:
        tensor_list[:] = vals
    return vals


def reducescatter(tensor, tensor_list, group_name: str = "default",
                  op: str = ReduceOp.SUM):
    """Reduce the concatenation of every rank's `tensor_list` and scatter:
    rank i receives the reduction of everyone's tensor_list[i]."""
    g = _group(group_name)
    vals = g.exchange(tensor_list)
    mine = _REDUCERS[op](np.stack([np.asarray(v[g.rank]) for v in vals]))
    return _writeback(tensor, mine)


def barrier(group_name: str = "default", timeout: float = 300.0):
    _group(group_name).barrier(timeout)


def send(tensor, dst_rank: int, group_name: str = "default"):
    g = _group(group_name)
    pair = (g.rank, dst_rank)
    seq = g.p2p_seq[pair] = g.p2p_seq.get(pair, 0) + 1
    g.kv.put(g._key("p2p", g.rank, dst_rank, seq), _blob(tensor))


def recv(tensor, src_rank: int, group_name: str = "default"):
    g = _group(group_name)
    pair = (src_rank, g.rank)
    seq = g.p2p_seq[pair] = g.p2p_seq.get(pair, 0) + 1
    key = g._key("p2p", src_rank, g.rank, seq)
    out = _unblob(g.kv.wait(key))
    g.kv.delete(key)
    return _writeback(tensor, out)
