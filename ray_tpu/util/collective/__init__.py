"""Collective communication between actors/tasks outside the object path.

Parity: reference `python/ray/util/collective/collective.py:123`
(init_collective_group, allreduce:268, barrier, reduce, broadcast,
allgather, reducescatter, send/recv) with its NCCL
(`collective_group/nccl_collective_group.py:128`) and GLOO
(`gloo_collective_group.py:184`) backends.

TPU-native stance (SURVEY §5.8): dense-math communication belongs INSIDE
jit-compiled programs as jax.lax collectives over ICI
(`ray_tpu.parallel.collectives`). This module is the HOST-side backend —
the analogue of the reference's GLOO group — used for control-plane
exchange (weight broadcast to env-runners, metric reduction, rendezvous):
small payloads ride the head KV, large tensors ride the shared-memory
object plane, with KV-sequenced rendezvous.
"""

from ray_tpu.util.collective.collective import (  # noqa: F401
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    join_group,
    recv,
    reduce,
    reducescatter,
    send,
)
