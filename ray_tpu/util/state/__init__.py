"""State API: cluster introspection (list/summarize live entities).

Parity: reference `python/ray/util/state/` (`ray list
tasks/actors/objects/nodes/workers`, `ray summary tasks` — backed by
`state_manager.py:107` fanning out to GCS + agents). Here the head runtime
IS the control plane, so listing reads its tables directly; remote callers
(workers, `ray_tpu.init(address=...)` clients, the CLI) go through the
head's request channel ("state" op).
"""

from __future__ import annotations

import time


def _query(kind: str, arg=None):
    from ray_tpu.core.runtime import Runtime, get_runtime
    rt = get_runtime()
    if isinstance(rt, Runtime):
        return _dispatch(rt, kind, arg)
    return rt.request("state", (kind, arg))


def _dispatch(rt, kind: str, arg=None):
    """Head-side execution of a state query (also invoked by the head's
    request handler for remote callers)."""
    fn = _HANDLERS[kind]
    return fn(rt) if arg is None else fn(rt, arg)


def list_nodes() -> list[dict]:
    return _query("nodes")


def list_workers() -> list[dict]:
    return _query("workers")


def list_actors() -> list[dict]:
    return _query("actors")


def list_tasks(limit: int = 1000) -> list[dict]:
    """Recent task state transitions, newest last (backed by the head's
    task-event ring, parity: gcs_task_manager.h:94 bounded storage)."""
    return _query("tasks", limit)


def list_objects(limit: int = 1000) -> list[dict]:
    return _query("objects", limit)


def list_placement_groups() -> list[dict]:
    return _query("placement_groups")


def summarize_tasks() -> dict:
    return _query("summarize_tasks")


def summary_tasks() -> dict:
    """Per-function rollup from the task-event pipeline (parity: `ray
    summary tasks`): attempt counts, state breakdown, mean queue/exec/
    total latencies, plus pipeline drop accounting. Works from remote
    callers (workers, clients) through the head's state channel."""
    return _query("summary_tasks")


def list_task_events(limit: int = 1000) -> list[dict]:
    """Merged per-attempt task events from the head's TaskEventStorage
    (parity: `ray list tasks --detail` backed by gcs_task_manager.h:94):
    each row carries the attempt's state-transition history with source
    node/worker, lease_seq and spill hops."""
    return _query("task_events", limit)


def summarize_actors() -> dict:
    return _query("summarize_actors")


def cluster_status() -> dict:
    """One-call overview (what `ray status` prints)."""
    return _query("status")


# ---- head-side implementations ----


def _nodes(rt) -> list[dict]:
    return rt.nodes_table()


def _workers(rt) -> list[dict]:
    out = []
    for wid, w in list(rt.workers.items()):
        out.append({
            "worker_id": wid.hex(),
            "node_id": w.node_id.hex() if w.node_id else "",
            "state": w.state,
            "is_actor": w.actor_id is not None,
            "pid": getattr(w.proc, "pid", None),
        })
    return out


def _actors(rt) -> list[dict]:
    registered = {aid: name for name, aid in rt.named_actors.items()}
    out = []
    for aid, st in list(rt.actors.items()):
        out.append({
            "actor_id": aid.hex(),
            "class_name": st.cspec.name,
            "state": st.state.upper(),
            "name": registered.get(aid, ""),
            "node_id": st.node_id.hex() if st.node_id else "",
            "restarts": st.cspec.restarts_used,
            "pending_calls": len(st.queued) + len(st.inflight),
        })
    return out


def _tasks(rt, limit: int = 1000) -> list[dict]:
    latest: dict[bytes, dict] = {}
    for ts, task_id, name, state in rt.task_events.snapshot():
        latest[task_id] = {"task_id": task_id.hex(), "name": name,
                           "state": state, "ts": ts}
    rows = sorted(latest.values(), key=lambda r: r["ts"])
    return rows[-limit:]


def _objects(rt, limit: int = 1000) -> list[dict]:
    out = []
    with rt.directory.lock:
        items = list(rt.directory.entries.items())[:limit]
    for oid, entry in items:
        kind = entry[0]
        locs = []
        if kind == "shm" and len(entry) > 1:
            locs = [nid.hex() for nid in entry[1]]
        out.append({"object_id": oid.hex(), "kind": kind,
                    "locations": locs})
    return out


def _placement_groups(rt) -> list[dict]:
    table = rt.placement_group_table()
    return [{"placement_group_id": pg_id, **row}
            for pg_id, row in table.items()]


def _summarize_tasks(rt) -> dict:
    by_state: dict[str, int] = {}
    for row in _tasks(rt, limit=100000):
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"by_state": by_state, "by_name": rt.task_events.summary()}


def _summary_tasks(rt) -> dict:
    rt.sync_task_store()
    return rt.task_store.summary()


def _task_events(rt, limit: int = 1000) -> list[dict]:
    rt.sync_task_store()
    return rt.task_store.list_events(limit)


def _summarize_actors(rt) -> dict:
    by_state: dict[str, int] = {}
    for row in _actors(rt):
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"by_state": by_state}


def _status(rt) -> dict:
    return {
        "timestamp": time.time(),
        "nodes": {"alive": sum(1 for n in rt.nodes_table() if n["alive"]),
                  "dead": sum(1 for n in rt.nodes_table()
                              if not n["alive"])},
        "resources": {"total": rt.cluster_resources(),
                      "available": rt.available_resources()},
        "pending_tasks": len(rt.task_queue),
        "actors": _summarize_actors(rt)["by_state"],
        "store": rt.store.stats(),
        "num_workers": len(rt.workers),
        "tasks_finished_total": rt.task_events.finished_total,
    }


_HANDLERS = {
    "nodes": _nodes,
    "workers": _workers,
    "actors": _actors,
    "tasks": _tasks,
    "objects": _objects,
    "placement_groups": _placement_groups,
    "summarize_tasks": _summarize_tasks,
    "summary_tasks": _summary_tasks,
    "task_events": _task_events,
    "summarize_actors": _summarize_actors,
    "status": _status,
}
