"""State API: cluster introspection (list/summarize live entities).

Parity: reference `python/ray/util/state/` (`ray list
tasks/actors/objects/nodes/workers`, `ray summary tasks` — backed by
`state_manager.py:107` fanning out to GCS + agents). Here the head runtime
IS the control plane, so listing reads its tables directly; remote callers
go through the worker request channel.
"""

from __future__ import annotations

import time


def _rt():
    from ray_tpu.core.runtime import Runtime, get_runtime
    rt = get_runtime()
    if not isinstance(rt, Runtime):
        raise RuntimeError("the state API runs on the driver (head) process")
    return rt


def list_nodes() -> list[dict]:
    return _rt().nodes_table()


def list_workers() -> list[dict]:
    rt = _rt()
    out = []
    for wid, w in list(rt.workers.items()):
        out.append({
            "worker_id": wid.hex(),
            "node_id": w.node_id.hex() if w.node_id else "",
            "state": w.state,
            "is_actor": w.actor_id is not None,
            "pid": getattr(w.proc, "pid", None),
        })
    return out


def list_actors() -> list[dict]:
    rt = _rt()
    registered = {aid: name for name, aid in rt.named_actors.items()}
    out = []
    for aid, st in list(rt.actors.items()):
        out.append({
            "actor_id": aid.hex(),
            "class_name": st.cspec.name,
            "state": st.state.upper(),
            "name": registered.get(aid, ""),
            "node_id": st.node_id.hex() if st.node_id else "",
            "restarts": st.cspec.restarts_used,
            "pending_calls": len(st.queued) + len(st.inflight),
        })
    return out


def list_tasks(limit: int = 1000) -> list[dict]:
    """Recent task state transitions, newest last (backed by the head's
    task-event ring, parity: gcs_task_manager.h:94 bounded storage)."""
    rt = _rt()
    latest: dict[bytes, dict] = {}
    for ts, task_id, name, state in rt.task_events.snapshot():
        latest[task_id] = {"task_id": task_id.hex(), "name": name,
                           "state": state, "ts": ts}
    rows = sorted(latest.values(), key=lambda r: r["ts"])
    return rows[-limit:]


def list_objects(limit: int = 1000) -> list[dict]:
    rt = _rt()
    out = []
    with rt.directory.lock:
        items = list(rt.directory.entries.items())[:limit]
    for oid, entry in items:
        kind = entry[0]
        locs = []
        if kind == "shm" and len(entry) > 1:
            locs = [nid.hex() for nid in entry[1]]
        out.append({"object_id": oid.hex(), "kind": kind,
                    "locations": locs})
    return out


def list_placement_groups() -> list[dict]:
    rt = _rt()
    table = rt.placement_group_table()
    return [{"placement_group_id": pg_id, **row}
            for pg_id, row in table.items()]


def summarize_tasks() -> dict:
    rt = _rt()
    by_state: dict[str, int] = {}
    for row in list_tasks(limit=100000):
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"by_state": by_state, "by_name": rt.task_events.summary()}


def summarize_actors() -> dict:
    by_state: dict[str, int] = {}
    for row in list_actors():
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"by_state": by_state}


def cluster_status() -> dict:
    """One-call overview (what `ray status` prints)."""
    rt = _rt()
    return {
        "timestamp": time.time(),
        "nodes": {"alive": sum(1 for n in rt.nodes_table() if n["alive"]),
                  "dead": sum(1 for n in rt.nodes_table()
                              if not n["alive"])},
        "resources": {"total": rt.cluster_resources(),
                      "available": rt.available_resources()},
        "pending_tasks": len(rt.task_queue),
        "actors": summarize_actors()["by_state"],
        "store": rt.store.stats(),
    }
