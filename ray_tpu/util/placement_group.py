"""Placement groups: atomically-reserved resource bundles.

Parity: reference `python/ray/util/placement_group.py:145` (placement_group,
PlacementGroup.ready/wait, remove_placement_group, placement_group_table)
with the strategies of `bundle_scheduling_policy.h:31-106`
(PACK/SPREAD/STRICT_PACK/STRICT_SPREAD). TPU-native addition:
``ICI_CONTIGUOUS`` asks for bundles mapped onto topologically contiguous
TPU sub-slices (generalizing the reference's `TPU-{type}-head` resource
trick, `_private/accelerators/tpu.py:422`, into the scheduler).

On the single-node runtime the reservation is a carve-out of the head's
resource pool per bundle; the 2-phase-commit across raylets
(`gcs_placement_group_scheduler.h:288`) collapses to one atomic reserve.
"""

from __future__ import annotations

from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.ids import ObjectID

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
              "ICI_CONTIGUOUS")


class PlacementGroup:
    """Handle to a created (or pending) placement group."""

    __slots__ = ("id", "bundle_specs", "_ready_oid")

    def __init__(self, pg_id: PlacementGroupID, bundle_specs, ready_oid=None):
        self.id = pg_id
        self.bundle_specs = bundle_specs
        self._ready_oid = ready_oid

    def ready(self) -> ObjectRef:
        """ObjectRef fulfilled once every bundle is reserved."""
        return ObjectRef(ObjectID(self._ready_oid))

    def wait(self, timeout_seconds: float | None = None) -> bool:
        import ray_tpu
        try:
            ray_tpu.get(self.ready(), timeout=timeout_seconds)
            return True
        except Exception:  # noqa: BLE001 — timeout or removal
            return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self._ready_oid))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]}, {self.bundle_specs})"


def placement_group(bundles, strategy: str = "PACK", name: str = "",
                    lifetime=None) -> PlacementGroup:
    if strategy not in STRATEGIES:
        raise ValueError(
            f"strategy must be one of {STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    clean = []
    for b in bundles:
        if not isinstance(b, dict):
            raise ValueError(f"bundle must be a dict, got {b!r}")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"bundle amounts must be >= 0: {b!r}")
        c = {k: float(v) for k, v in b.items() if v}
        if not c:
            raise ValueError(
                f"bundle must request a positive amount of at least one "
                f"resource, got {b!r}")
        clean.append(c)
    from ray_tpu.core.runtime import Runtime, get_runtime
    rt = get_runtime()
    pg_id = PlacementGroupID.from_random()
    if isinstance(rt, Runtime):
        ready_oid = rt.create_placement_group(
            pg_id.binary(), clean, strategy, name)
    else:
        ready_oid = rt.request(
            "create_pg", (pg_id.binary(), clean, strategy, name))
    return PlacementGroup(pg_id, clean, ready_oid)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.runtime import Runtime, get_runtime
    rt = get_runtime()
    if isinstance(rt, Runtime):
        rt.remove_placement_group(pg.id.binary())
    else:
        rt.request("remove_pg", pg.id.binary())


def placement_group_table() -> dict:
    from ray_tpu.core.runtime import Runtime, get_runtime
    rt = get_runtime()
    if isinstance(rt, Runtime):
        return rt.placement_group_table()
    return rt.request("pg_table")


def get_current_placement_group() -> PlacementGroup | None:
    """The placement group of the currently-executing task/actor, if any
    (parity: util/placement_group.py get_current_placement_group)."""
    from ray_tpu.core.runtime import current_runtime
    rt = current_runtime()
    strat = (getattr(rt, "current_scheduling_strategy", None)
             or getattr(rt, "actor_scheduling_strategy", None))
    return getattr(strat, "placement_group", None)
