"""On-demand stack sampling for live processes (head and workers).

Parity: the reference dashboard's reporter module shells out to py-spy /
memray (`python/ray/dashboard/modules/reporter/`). Neither tool assumes a
TPU VM image, so the sampler here is built in: a thread walks
`sys._current_frames()` at a fixed rate and aggregates stacks — enough to
see where a worker (or the head control plane) spends host-side time,
with zero dependencies and no ptrace capability requirements. Exposed as
`ray_tpu.util.state.profile_worker(...)` and the dashboard's
`/api/profile` route.
"""

from __future__ import annotations

import collections
import sys
import threading
import time


def sample_stacks(duration_s: float = 1.0, hz: float = 100.0,
                  depth: int = 24) -> dict:
    """Sample every thread's stack in THIS process for `duration_s`.

    Returns {"duration_s", "samples", "threads", "stacks": [{"stack":
    ["fn (file:line)", ... outermost last], "count"}]} sorted by count.
    """
    interval = 1.0 / max(hz, 1.0)
    counts: collections.Counter = collections.Counter()
    me = threading.get_ident()
    deadline = time.monotonic() + duration_s
    samples = 0
    thread_ids: set = set()
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            thread_ids.add(tid)
            stack = []
            f = frame
            while f is not None and len(stack) < depth:
                code = f.f_code
                stack.append(
                    f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}"
                    f":{f.f_lineno})")
                f = f.f_back
            counts[tuple(stack)] += 1
        samples += 1
        time.sleep(interval)
    return {
        "duration_s": duration_s,
        "samples": samples,
        "threads": len(thread_ids),
        "stacks": [{"stack": list(s), "count": c}
                   for s, c in counts.most_common()],
    }


def format_report(report: dict, top: int = 20) -> str:
    if "error" in report:
        return f"profiling failed: {report['error']}"
    total = max(report.get("samples", 1), 1)
    lines = [f"{report['samples']} samples over "
             f"{report['duration_s']:.1f}s across {report['threads']} "
             f"threads"]
    for entry in report["stacks"][:top]:
        pct = 100.0 * entry["count"] / total
        lines.append(f"\n{pct:5.1f}%  ({entry['count']} samples)")
        for frame in entry["stack"]:
            lines.append(f"        {frame}")
    return "\n".join(lines)
