"""Utility APIs layered on the core primitives.

Parity: reference `python/ray/util/` (placement groups, scheduling
strategies, ActorPool, queue, collective, state API).
"""

from ray_tpu.util.placement_group import (  # noqa: F401
    placement_group,
    placement_group_table,
    remove_placement_group,
    PlacementGroup,
)
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
