"""Reusable retry policy for RPC-shaped calls.

Parity: `src/ray/rpc/retryable_grpc_client.h` — the reference wraps its
gRPC clients in one retry/backoff policy instead of each call site
re-solving transient-failure handling. Here the callable IS the RPC
(an HTTP transport, a socket send, a cloud API call).
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    base_backoff_s: float = 0.5
    max_backoff_s: float = 8.0
    # Exception types considered transient. Anything else propagates
    # immediately (a 404 is an answer, not a flake).
    retryable: tuple = (OSError, TimeoutError)
    # Optional finer predicate: exc -> bool. When set it REPLACES the
    # type check (e.g. "URLError yes, but HTTPError < 500 no").
    should_retry: object = None


def http_should_retry(exc) -> bool:
    """Shared predicate for urllib-based transports: retry connection
    failures and HTTP 5xx, never 4xx (an answer, not a flake)."""
    import urllib.error
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500
    return isinstance(exc, (OSError, TimeoutError))


def call_with_retries(fn, *args, policy: RetryPolicy | None = None,
                      on_retry=None, **kwargs):
    """Run `fn(*args, **kwargs)`, retrying transient failures with
    exponential backoff. `on_retry(attempt, exc)` observes each retry
    (logging/metrics hook). The final failure propagates unchanged."""
    policy = policy or RetryPolicy()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — filtered right below
            transient = (policy.should_retry(e) if policy.should_retry
                         else isinstance(e, policy.retryable))
            attempt += 1
            if not transient or attempt >= policy.max_attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(min(policy.base_backoff_s * (2 ** (attempt - 1)),
                           policy.max_backoff_s))


def retryable(policy: RetryPolicy | None = None, on_retry=None):
    """Decorator form: wrap a client method in the shared policy."""
    def deco(fn):
        def wrapped(*args, **kwargs):
            return call_with_retries(fn, *args, policy=policy,
                                     on_retry=on_retry, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "retryable")
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco
