"""ActorPool: round-robin work distribution over a fixed set of actors.

Parity: reference `python/ray/util/actor_pool.py` (map/map_unordered/
submit/get_next/get_next_unordered/has_next/push/pop_idle).
"""

from __future__ import annotations

import ray_tpu


class ActorPool:
    def __init__(self, actors):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits = []

    def submit(self, fn, value):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout=None):
        if not self.has_next():
            raise StopIteration("no more results to get")
        if self._next_return_index >= self._next_task_index:
            raise ValueError("It is not allowed to call get_next() after "
                             "get_next_unordered().")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            # Probe first: a timeout must leave the pool untouched so the
            # caller can retry (mutating before the get would lose the result
            # and hand the still-busy actor back to the idle list).
            ready, _ = ray_tpu.wait([future], timeout=timeout)
            if not ready:
                from ray_tpu.core.status import GetTimeoutError
                raise GetTimeoutError(
                    f"get_next timed out after {timeout}s")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout=None):
        if not self.has_next():
            raise StopIteration("no more results to get")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            from ray_tpu.core.status import GetTimeoutError
            raise GetTimeoutError("timed out waiting for a result")
        future = ready[0]
        i, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(i, None)
        self._next_return_index = max(self._next_return_index, i + 1)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def map(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor):
        busy = {a for _, a in self._future_to_actor.values()}
        if actor in self._idle or actor in busy:
            raise ValueError("Actor already belongs to current ActorPool")
        self._return_actor(actor)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
