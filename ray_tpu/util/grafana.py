"""Grafana dashboard factory: metric definitions -> dashboard JSON.

Parity: `dashboard/modules/metrics/grafana_dashboard_factory.py` in the
reference, which generates the default / serve / data Grafana dashboards
from panel templates so operators get working boards without hand-built
JSON. Here panels derive from two sources: the fixed system gauges the
/metrics route always exposes, and whatever Counters/Gauges/Histograms
the application registered at generation time — counters render as
rate() graphs, histograms as p50/p95/p99 `histogram_quantile` overlays.

The artifact is a standard Grafana dashboard model (schemaVersion 36):
import it via the Grafana UI/API or provision it from disk; the
dashboard server also serves it at /api/grafana/<name>.json.
"""

from __future__ import annotations

import json

_PANEL_W = 12
_PANEL_H = 8

# The always-exposed cluster gauges (util/metrics.py _system_lines).
_SYSTEM_PANELS = [
    ("Object store fill", [
        ("ray_tpu_object_store_allocated_bytes", "allocated"),
        ("ray_tpu_object_store_capacity_bytes", "capacity")]),
    ("Objects in store", [
        ("ray_tpu_object_store_num_objects", "objects")]),
    ("Store evictions", [
        ("ray_tpu_object_store_num_evictions", "evictions")]),
    ("Pending tasks", [("ray_tpu_pending_tasks", "pending")]),
    ("Alive nodes", [("ray_tpu_alive_nodes", "nodes")]),
    ("Workers", [("ray_tpu_workers", "workers")]),
    ("Alive actors", [("ray_tpu_actors_alive", "actors")]),
]


def _target(expr: str, legend: str) -> dict:
    return {"expr": expr, "legendFormat": legend, "refId": "A"}


def _panel(pid: int, title: str, targets: list[dict], index: int) -> dict:
    for i, t in enumerate(targets):
        t["refId"] = chr(ord("A") + i)
    return {
        "id": pid,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": _PANEL_H, "w": _PANEL_W,
                    "x": (index % 2) * _PANEL_W,
                    "y": (index // 2) * _PANEL_H},
        "targets": targets,
        "fieldConfig": {"defaults": {"custom": {"fillOpacity": 10}},
                        "overrides": []},
    }


def _metric_targets(metric) -> tuple[str, list[dict]]:
    """PromQL targets for one registered Metric, by kind."""
    by = ("by ({}) ".format(", ".join(metric.tag_keys))
          if metric.tag_keys else "")
    legend = ("{{" + "}}-{{".join(metric.tag_keys) + "}}"
              if metric.tag_keys else metric.name)
    if metric.kind == "counter":
        return (f"{metric.name} (rate/s)",
                [_target(f"sum {by}(rate({metric.name}[5m]))", legend)])
    if metric.kind == "histogram":
        return (f"{metric.name} (latency quantiles)", [
            _target(
                f"histogram_quantile({q}, sum by (le) "
                f"(rate({metric.name}_bucket[5m])))", f"p{int(q * 100)}")
            for q in (0.5, 0.95, 0.99)])
    return (metric.name, [_target(f"sum {by}({metric.name})", legend)])


def generate_dashboard(name: str = "ray_tpu",
                       title: str = "ray_tpu cluster",
                       include_registry: bool = True) -> dict:
    """Build the dashboard model. `include_registry=True` adds one panel
    per application metric registered in util.metrics at call time (the
    factory runs at serve time, so late-registered metrics appear on the
    next fetch)."""
    panels = []
    pid = 1
    for i, (ptitle, series) in enumerate(_SYSTEM_PANELS):
        panels.append(_panel(
            pid, ptitle, [_target(expr, leg) for expr, leg in series], i))
        pid += 1
    if include_registry:
        from ray_tpu.util.metrics import _LOCK, _REGISTRY
        with _LOCK:
            metrics = sorted(_REGISTRY.values(), key=lambda m: m.name)
        for m in metrics:
            ptitle, targets = _metric_targets(m)
            panels.append(_panel(pid, ptitle, targets, len(panels)))
            pid += 1
    return {
        "uid": f"raytpu-{name}",
        "title": title,
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "schemaVersion": 36,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
            "current": {},
        }]},
        "panels": panels,
    }


def generate_serve_dashboard() -> dict:
    """The Serve board (parity: the reference's serve_dashboard_panels):
    per-deployment QPS, latency quantiles, error rate, replica counts —
    expressed over the serve_* metrics the proxy/router registers."""
    rows = [
        ("Requests/s by deployment",
         [_target('sum by (deployment) '
                  '(rate(serve_num_router_requests[5m]))',
                  "{{deployment}}")]),
        ("Request latency quantiles",
         [_target(f"histogram_quantile({q}, sum by (le) "
                  f"(rate(serve_request_latency_ms_bucket[5m])))",
                  f"p{int(q * 100)}") for q in (0.5, 0.95, 0.99)]),
        ("Replicas by deployment",
         [_target('sum by (deployment) (serve_num_replicas)',
                  "{{deployment}}")]),
    ]
    panels = [_panel(i + 1, t, targets, i)
              for i, (t, targets) in enumerate(rows)]
    base = generate_dashboard("serve", "ray_tpu serve",
                              include_registry=False)
    base["panels"] = panels
    base["uid"] = "raytpu-serve"
    return base


DASHBOARDS = {
    "ray_tpu": generate_dashboard,
    "serve": generate_serve_dashboard,
}


def dashboard_json(name: str) -> str:
    try:
        gen = DASHBOARDS[name]
    except KeyError:
        raise KeyError(f"no dashboard {name!r}; have {sorted(DASHBOARDS)}")
    return json.dumps(gen(), indent=1)
