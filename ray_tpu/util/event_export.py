"""Export API: durable JSONL stream of cluster state transitions.

Parity: reference `src/ray/protobuf/export_api/` + `src/ray/util/event.h:142`
(RayExportEvent/EventManager) — a file-based event stream external systems
tail for task/actor/node lifecycle changes, independent of the bounded
in-memory task-event ring. Enabled with the `export_events` config flag;
files land under `<session>/export_events/events_<kind>.jsonl`.
"""

from __future__ import annotations

import json
import os
import threading
import time


class ExportEventWriter:
    """Appends one JSON object per line, per event kind, flushed on every
    emit (tail -f friendly; emit volume is control-plane scale)."""

    def __init__(self, session_dir: str):
        self.dir = os.path.join(session_dir, "export_events")
        os.makedirs(self.dir, exist_ok=True)
        self._files: dict = {}
        self._lock = threading.Lock()

    def emit(self, kind: str, **fields):
        row = {"timestamp": time.time(), "kind": kind, **fields}
        line = json.dumps(row, default=repr) + "\n"
        with self._lock:
            f = self._files.get(kind)
            if f is None:
                f = open(os.path.join(self.dir, f"events_{kind}.jsonl"),
                         "a", buffering=1)
                self._files[kind] = f
            try:
                f.write(line)
            except (OSError, ValueError):
                pass

    def close(self):
        with self._lock:
            for f in self._files.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._files.clear()
