"""Remote pdb: breakpoints inside worker processes.

Parity: reference `python/ray/util/rpdb.py` (`ray.util.pdb.set_trace`) —
a worker has no terminal, so `set_trace()` opens a TCP listener and runs
pdb over the socket; connect with `nc <host> <port>` (the address is
printed to the worker's log and stored in the head KV under
`__rpdb__:<pid>`).
"""

from __future__ import annotations

import os
import socket
import sys


class _SocketIO:
    """File-like adapter pdb can read/write through."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._rfile = conn.makefile("r")
        self._wfile = conn.makefile("w")

    def readline(self):
        return self._rfile.readline()

    def write(self, data):
        self._wfile.write(data)
        return len(data)

    def flush(self):
        self._wfile.flush()

    def close(self):
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass
        try:
            self._conn.close()
        except OSError:
            pass


def _node_ip() -> str:
    """The IP other nodes can reach this worker on (outbound-route probe:
    a UDP connect sends no packets but resolves the egress interface —
    hostname lookup often lands on 127.0.1.1)."""
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect(("8.8.8.8", 80))
        ip = probe.getsockname()[0]
        probe.close()
        return ip
    except OSError:
        return "127.0.0.1"


def set_trace(breakpoint_uuid: str | None = None):
    """Block until a debugger client connects, then drop into pdb."""
    import pdb

    lsock = socket.socket()
    # Security default matches the reference (REMOTE_PDB_HOST /
    # RAY_DEBUGGER_EXTERNAL): pdb is arbitrary code execution, so bind
    # loopback unless the operator explicitly opts into external access
    # (needed when the driver debugs a worker on another node).
    external = os.environ.get("RAY_TPU_DEBUGGER_EXTERNAL", "0") not in (
        "0", "", "false", "False")
    bind_host = "0.0.0.0" if external else \
        os.environ.get("REMOTE_PDB_HOST", "127.0.0.1")
    lsock.bind((bind_host, 0))
    lsock.listen(1)
    _, port = lsock.getsockname()
    # Advertise an address that actually reaches the bound interface.
    host = _node_ip() if external else \
        ("127.0.0.1" if bind_host in ("127.0.0.1", "localhost")
         else bind_host)
    addr = f"{host}:{port}"
    tag = breakpoint_uuid or str(os.getpid())
    print(f"rpdb: waiting for debugger on {addr} "
          f"(connect with: nc {host} {port})", flush=True)
    try:
        from ray_tpu.experimental.internal_kv import _internal_kv_put
        _internal_kv_put(f"__rpdb__:{tag}", addr.encode())
    except Exception:  # noqa: BLE001 — KV is advisory
        pass
    conn, _peer = lsock.accept()
    lsock.close()
    io = _SocketIO(conn)
    debugger = pdb.Pdb(stdin=io, stdout=io)
    debugger.prompt = "(rpdb) "
    frame = sys._getframe().f_back
    debugger.set_trace(frame)


def list_breakpoints() -> dict:
    """Active rpdb listeners (driver-side helper): {tag: 'host:port'}."""
    from ray_tpu.experimental.internal_kv import (
        _internal_kv_get,
        _internal_kv_list,
    )
    out = {}
    for k in _internal_kv_list("__rpdb__:"):
        key = k.decode() if isinstance(k, bytes) else k
        v = _internal_kv_get(k)
        out[key.split(":", 1)[1]] = (v.decode()
                                     if isinstance(v, bytes) else v)
    return out
