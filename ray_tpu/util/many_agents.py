"""Many-agent scheduling workload, shared by the bench and the test suite.

One definition of the 16-agent fan-out (parity in spirit:
`release/benchmarks/distributed/test_many_tasks.py`) so bench.py's metric
and tests/test_cluster.py's correctness gate can never drift apart.
"""

from __future__ import annotations

import time


def run_many_agents(n_agents: int = 16, n_tasks: int = 400,
                    spawn_timeout: float = 240.0,
                    settle: bool = True) -> dict:
    """Spin `n_agents` node agents on this machine, fan `n_tasks` trivial
    tasks across them, and return {'rate': tasks/s, 'nodes_alive': int,
    'nodes_used': int, 'correct': bool, 'head_cpu_s': float,
    'tasks_per_head_cpu_s': float}. Caller owns no cluster before or
    after (shuts down on exit).

    head_cpu_s is the driver/head process's CPU time spent inside the
    timed window (the head runtime lives in this process), so
    tasks_per_head_cpu_s is the head-cost-per-task metric: the
    decentralized lease plane (cluster-view broadcast + agent->agent
    spillback) is working exactly when this number grows while wall-clock
    rate holds — the head is off the per-task critical path."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1,
                                "object_store_memory": 64 << 20})
    for _ in range(n_agents):
        c.add_node(num_cpus=1, object_store_memory=32 << 20)
    c.wait_for_nodes(n_agents + 1, timeout=spawn_timeout)
    try:
        @ray_tpu.remote(num_cpus=1)
        def f(x):
            return (x + 1, ray_tpu.get_node_id())

        # Warm every node's pool before the clock starts...
        ray_tpu.get([f.remote(i) for i in range(2 * n_agents)],
                    timeout=spawn_timeout)
        # ...then (bench mode) let the boot storm drain: agent zygotes
        # keep importing jax for several seconds after registration, and
        # on a small box that import CPU would be billed to the
        # measurement. `settle=False` skips the drain AND the throwaway
        # wave for callers that only hard-assert correctness/liveness
        # (the tier-1 test) — their `rate` print is then noisier, which
        # is exactly why the rate gate lives in bench.py alone.
        if settle:
            time.sleep(min(1.0 + 0.15 * n_agents, 12.0))
            # Throwaway measurement wave: the FIRST full fan-out after
            # boot consistently runs several-fold slower than steady
            # state (late zygote imports + first-touch page faults
            # across ~2N processes competing for this box's cores);
            # clocking it measured machine settling, not the scheduler.
            ray_tpu.get([f.remote(i) for i in range(max(n_agents,
                                                        n_tasks // 3))],
                        timeout=spawn_timeout)
        t0 = time.perf_counter()
        c0 = time.process_time()
        out = ray_tpu.get([f.remote(i) for i in range(n_tasks)],
                          timeout=300)
        head_cpu_s = max(1e-9, time.process_time() - c0)
        rate = n_tasks / (time.perf_counter() - t0)
        from ray_tpu.core.runtime import get_runtime
        rt = get_runtime()
        return {
            "rate": rate,
            "nodes_alive": sum(1 for n in rt.nodes.values()
                               if n.state == "ALIVE"),
            "nodes_used": len({nid for _v, nid in out}),
            "correct": [v for v, _nid in out] == list(
                range(1, n_tasks + 1)),
            "head_cpu_s": round(head_cpu_s, 3),
            "tasks_per_head_cpu_s": round(n_tasks / head_cpu_s, 1),
            "lease_spills": rt.lease_spills_total,
        }
    finally:
        c.shutdown()
