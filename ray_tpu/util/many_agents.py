"""Many-agent scheduling workload, shared by the bench and the test suite.

One definition of the 16-agent fan-out (parity in spirit:
`release/benchmarks/distributed/test_many_tasks.py`) so bench.py's metric
and tests/test_cluster.py's correctness gate can never drift apart.
"""

from __future__ import annotations

import time


def run_many_agents(n_agents: int = 16, n_tasks: int = 400,
                    spawn_timeout: float = 240.0,
                    settle: bool = True) -> dict:
    """Spin `n_agents` node agents on this machine, fan `n_tasks` trivial
    tasks across them, and return {'rate': tasks/s, 'nodes_alive': int,
    'nodes_used': int, 'correct': bool, 'head_cpu_s': float,
    'tasks_per_head_cpu_s': float}. Caller owns no cluster before or
    after (shuts down on exit).

    head_cpu_s is the driver/head process's CPU time spent inside the
    timed window (the head runtime lives in this process), so
    tasks_per_head_cpu_s is the head-cost-per-task metric: the
    decentralized lease plane (cluster-view broadcast + agent->agent
    spillback) is working exactly when this number grows while wall-clock
    rate holds — the head is off the per-task critical path."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1,
                                "object_store_memory": 64 << 20})
    for _ in range(n_agents):
        c.add_node(num_cpus=1, object_store_memory=32 << 20)
    c.wait_for_nodes(n_agents + 1, timeout=spawn_timeout)
    try:
        @ray_tpu.remote(num_cpus=1)
        def f(x):
            return (x + 1, ray_tpu.get_node_id())

        # Warm every node's pool before the clock starts...
        ray_tpu.get([f.remote(i) for i in range(2 * n_agents)],
                    timeout=spawn_timeout)
        # ...then (bench mode) let the boot storm drain: agent zygotes
        # keep importing jax for several seconds after registration, and
        # on a small box that import CPU would be billed to the
        # measurement. `settle=False` skips the drain AND the throwaway
        # wave for callers that only hard-assert correctness/liveness
        # (the tier-1 test) — their `rate` print is then noisier, which
        # is exactly why the rate gate lives in bench.py alone.
        if settle:
            time.sleep(min(1.0 + 0.15 * n_agents, 12.0))
            # Throwaway measurement wave: the FIRST full fan-out after
            # boot consistently runs several-fold slower than steady
            # state (late zygote imports + first-touch page faults
            # across ~2N processes competing for this box's cores);
            # clocking it measured machine settling, not the scheduler.
            ray_tpu.get([f.remote(i) for i in range(max(n_agents,
                                                        n_tasks // 3))],
                        timeout=spawn_timeout)
        t0 = time.perf_counter()
        c0 = time.process_time()
        out = ray_tpu.get([f.remote(i) for i in range(n_tasks)],
                          timeout=300)
        head_cpu_s = max(1e-9, time.process_time() - c0)
        rate = n_tasks / (time.perf_counter() - t0)
        from ray_tpu.core.runtime import get_runtime
        rt = get_runtime()
        return {
            "rate": rate,
            "nodes_alive": sum(1 for n in rt.nodes.values()
                               if n.state == "ALIVE"),
            "nodes_used": len({nid for _v, nid in out}),
            "correct": [v for v, _nid in out] == list(
                range(1, n_tasks + 1)),
            "head_cpu_s": round(head_cpu_s, 3),
            "tasks_per_head_cpu_s": round(n_tasks / head_cpu_s, 1),
            "lease_spills": rt.lease_spills_total,
        }
    finally:
        c.shutdown()


def run_emulated_storm(n_agents: int = 256, n_tasks: int = 2000,
                       head_shards: int = 0,
                       register_timeout: float = 120.0) -> dict:
    """256-agent-class head load without 256 OS processes: one real head
    (in THIS process, so `time.process_time()` is head CPU) plus an
    emulated-agent swarm (util/agent_emu.py) speaking the real agent wire
    protocol from a single subprocess. Returns the run_many_agents metric
    dict extended with the swarm's view-fanout spread percentiles and the
    shard/head tev routing split.

    `head_shards=N` boots the head with N directory/tev shard processes
    (core/head_shards.py) — the A/B axis of the cluster_scale bench row:
    the sharded head should hold tasks_per_head_cpu_s as n_agents grows,
    because directory WAL/mirror writes and task-event ingest leave its
    process entirely."""
    import json
    import os
    import subprocess
    import sys

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1,
                                "object_store_memory": 64 << 20,
                                "_system_config": {
                                    "head_shards": head_shards}})
    emu = None
    try:
        env = dict(os.environ)
        env.update(c.rt.config.to_env())
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        emu = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.util.agent_emu",
             "--head", c.address, "--n", str(n_agents)],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        ready = emu.stdout.readline()
        if not ready.startswith("EMU_READY"):
            raise RuntimeError(f"emu swarm failed to boot: {ready!r}")
        c.wait_for_nodes(n_agents + 1, timeout=register_timeout)

        @ray_tpu.remote(num_cpus=1)
        def f(x):
            return x + 1

        # Warm wave: fn blob distribution + first-touch of every emu
        # agent's lease path, off the clock (mirrors run_many_agents).
        ray_tpu.get([f.remote(i) for i in range(2 * n_agents)],
                    timeout=register_timeout)
        t0 = time.perf_counter()
        c0 = time.process_time()
        out = ray_tpu.get([f.remote(i) for i in range(n_tasks)],
                          timeout=300)
        head_cpu_s = max(1e-9, time.process_time() - c0)
        rate = n_tasks / (time.perf_counter() - t0)
        correct = out == list(range(1, n_tasks + 1))
        from ray_tpu.core.runtime import get_runtime
        rt = get_runtime()
        nodes_alive = sum(1 for n in rt.nodes.values()
                          if n.state == "ALIVE")
        # Drain the swarm: closing stdin asks it to print its stats line.
        emu.stdin.close()
        stats_line = emu.stdout.readline()
        emu.wait(timeout=30)
        stats = json.loads(stats_line) if stats_line.strip() else {}
        return {
            "rate": round(rate, 1),
            "n_agents": n_agents,
            "head_shards": head_shards,
            "nodes_alive": nodes_alive,
            "agents_used": stats.get("agents_used", 0),
            "correct": correct,
            "head_cpu_s": round(head_cpu_s, 3),
            "tasks_per_head_cpu_s": round(n_tasks / head_cpu_s, 1),
            "view_spread_p50_ms": stats.get("view_spread_p50_ms", 0.0),
            "view_spread_p95_ms": stats.get("view_spread_p95_ms", 0.0),
            "tev_shard": stats.get("tev_shard", 0),
            "tev_head": stats.get("tev_head", 0),
            "exec_errors": stats.get("exec_errors", -1),
        }
    finally:
        if emu is not None and emu.poll() is None:
            emu.kill()
        c.shutdown()
