"""Application metrics: Counter/Gauge/Histogram + Prometheus exposition.

Parity: reference `python/ray/util/metrics.py` (user-defined metrics via
the Cython metric bridge) and the per-node metrics agent's Prometheus
endpoint (`_private/metrics_agent.py:492`, `prometheus_exporter.py`). Here
metrics registered in the driver process are rendered straight into the
Prometheus text format by the dashboard's /metrics route.
"""

from __future__ import annotations

import threading

_REGISTRY: dict[str, "Metric"] = {}
_LOCK = threading.Lock()


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        with _LOCK:
            _REGISTRY[name] = self

    def _key(self, tags: dict | None) -> tuple:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def _fmt_labels(self, key: tuple) -> str:
        if not self.tag_keys:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in zip(self.tag_keys, key))
        return "{" + inner + "}"

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)] \
                if not self.tag_keys else list(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{self._fmt_labels(key)} {v}")
        return lines


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description="", boundaries=(0.1, 1, 10, 100),
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        self._buckets: dict[tuple, list] = {}
        self._sums: dict[tuple, float] = {}
        self._counts: dict[tuple, int] = {}

    def observe(self, value: float, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            b = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            for i, bound in enumerate(self.boundaries):
                if value <= bound:
                    b[i] += 1
                    break
            else:
                b[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for k, buckets in self._buckets.items():
                base = self._fmt_labels(k)[1:-1] if self.tag_keys else ""
                cum = 0
                for bound, n in zip(self.boundaries, buckets):
                    cum += n
                    sep = "," if base else ""
                    lines.append(
                        f'{self.name}_bucket{{{base}{sep}le="{bound}"}} '
                        f'{cum}')
                cum += buckets[-1]
                sep = "," if base else ""
                lines.append(
                    f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {cum}')
                suffix = "{" + base + "}" if base else ""
                lines.append(f"{self.name}_sum{suffix} {self._sums[k]}")
                lines.append(f"{self.name}_count{suffix} {self._counts[k]}")
        return lines


def _system_lines() -> list[str]:
    """Built-in cluster gauges rendered at scrape time (parity: the ~90
    C++ metric defs, stats/metric_defs.cc — the high-signal subset)."""
    from ray_tpu.core.runtime import Runtime, current_runtime
    rt = current_runtime()
    lines = []
    if not isinstance(rt, Runtime):
        return lines
    stats = rt.store.stats()
    rows = [
        ("ray_tpu_object_store_allocated_bytes", stats["allocated"]),
        ("ray_tpu_object_store_capacity_bytes", stats["capacity"]),
        ("ray_tpu_object_store_num_objects", stats["num_objects"]),
        ("ray_tpu_object_store_num_evictions", stats["num_evictions"]),
        ("ray_tpu_pending_tasks", len(rt.task_queue)),
        ("ray_tpu_alive_nodes",
         sum(1 for n in rt.nodes_table() if n["alive"])),
        ("ray_tpu_workers", len(rt.workers)),
        ("ray_tpu_actors_alive",
         sum(1 for a in rt.actors.values() if a.state == "alive")),
    ]
    for name, v in rows:
        lines += [f"# TYPE {name} gauge", f"{name} {v}"]
    # Serve replica gauges, rendered from controller state at scrape time
    # (the serve_* request/latency series come from router processes).
    try:
        from ray_tpu.serve import api as serve_api
        st = serve_api.status()
        if st:
            lines.append("# TYPE serve_num_replicas gauge")
            for app, info in st.items():
                for dep, d in info.get("deployments", {}).items():
                    lines.append(
                        f'serve_num_replicas{{application="{app}",'
                        f'deployment="{dep}"}} '
                        f'{d.get("running_replicas", 0)}')
    except Exception:  # noqa: BLE001 — serve absent or controller busy
        pass
    return lines


def prometheus_text() -> str:
    with _LOCK:
        metrics = list(_REGISTRY.values())
    lines: list[str] = _system_lines()
    for m in metrics:
        lines += m.expose()
    return "\n".join(lines) + "\n"
