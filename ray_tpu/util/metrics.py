"""Application metrics: Counter/Gauge/Histogram + Prometheus exposition.

Parity: reference `python/ray/util/metrics.py` (user-defined metrics via
the Cython metric bridge) and the per-node metrics agent's Prometheus
endpoint (`_private/metrics_agent.py:492`, `prometheus_exporter.py`).
Metrics registered in the driver process render straight into the
Prometheus text format by the dashboard's /metrics route; metrics
registered in WORKER processes ship dirty-registry deltas on the
task-event flush frames (core/worker.py) and merge here at scrape time,
tagged `WorkerId` — the role the reference's per-node metrics agent
plays for core-worker metrics.

Label values are escaped per the Prometheus exposition format
(backslash, double-quote and newline), so a tag value like `he said "hi"`
cannot corrupt the scrape.
"""

from __future__ import annotations

import threading

_REGISTRY: dict[str, "Metric"] = {}
_LOCK = threading.Lock()


def _escape_label_value(v: str) -> str:
    """Exposition-format label escaping: backslash first, then quote and
    newline (https://prometheus.io/docs/instrumenting/exposition_formats)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_label_pairs(keys, values) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"'
                    for k, v in zip(keys, values))


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        self._dirty = False  # set on writes, cleared by registry_delta()
        with _LOCK:
            _REGISTRY[name] = self

    def _key(self, tags: dict | None) -> tuple:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def _fmt_labels(self, key: tuple) -> str:
        if not self.tag_keys:
            return ""
        return "{" + _fmt_label_pairs(self.tag_keys, key) + "}"

    def header(self) -> list[str]:
        return [f"# HELP {self.name} {self.description}",
                f"# TYPE {self.name} {self.kind}"]

    def samples(self) -> list[str]:
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)] \
                if not self.tag_keys else list(self._values.items())
        return [f"{self.name}{self._fmt_labels(key)} {v}"
                for key, v in items]

    def expose(self) -> list[str]:
        return self.header() + self.samples()

    def snapshot(self) -> dict:
        """Pickle-friendly registry-delta entry (worker -> head)."""
        with self._lock:
            return {"name": self.name, "kind": self.kind,
                    "desc": self.description, "tags": self.tag_keys,
                    "values": dict(self._values)}


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value
            self._dirty = True


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)
            self._dirty = True


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description="", boundaries=(0.1, 1, 10, 100),
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        self._buckets: dict[tuple, list] = {}
        self._sums: dict[tuple, float] = {}
        self._counts: dict[tuple, int] = {}

    def observe(self, value: float, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            b = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            for i, bound in enumerate(self.boundaries):
                if value <= bound:
                    b[i] += 1
                    break
            else:
                b[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1
            self._dirty = True

    def samples(self) -> list[str]:
        lines: list[str] = []
        with self._lock:
            buckets = {k: list(v) for k, v in self._buckets.items()}
            sums, counts = dict(self._sums), dict(self._counts)
        for k, bks in buckets.items():
            lines += _histogram_sample_lines(
                self.name, self.boundaries, bks, sums[k], counts[k],
                self.tag_keys, k)
        return lines

    def expose(self) -> list[str]:
        return self.header() + self.samples()

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "kind": self.kind,
                    "desc": self.description, "tags": self.tag_keys,
                    "boundaries": self.boundaries,
                    "buckets": {k: list(v)
                                for k, v in self._buckets.items()},
                    "sums": dict(self._sums),
                    "counts": dict(self._counts)}


def _histogram_sample_lines(name, boundaries, buckets, total_sum,
                            total_count, tag_keys, tag_values,
                            extra: dict | None = None) -> list[str]:
    """Exposition sample lines for ONE labeled histogram series."""
    keys = list(tag_keys) + list(extra or ())
    values = list(tag_values) + list((extra or {}).values())
    base = _fmt_label_pairs(keys, values)
    sep = "," if base else ""
    lines = []
    cum = 0
    for bound, n in zip(boundaries, buckets):
        cum += n
        lines.append(f'{name}_bucket{{{base}{sep}le="{bound}"}} {cum}')
    cum += buckets[-1]
    lines.append(f'{name}_bucket{{{base}{sep}le="+Inf"}} {cum}')
    suffix = "{" + base + "}" if base else ""
    lines.append(f"{name}_sum{suffix} {total_sum}")
    lines.append(f"{name}_count{suffix} {total_count}")
    return lines


def registry_delta() -> list[dict]:
    """Snapshots of metrics written since the last call (the worker->head
    shipping unit; cumulative values, so 'latest snapshot wins' merge)."""
    with _LOCK:
        metrics = list(_REGISTRY.values())
    out = []
    for m in metrics:
        if not m._dirty:
            continue
        m._dirty = False
        out.append(m.snapshot())
    return out


def _render_snapshot_series(snap: dict, extra: dict) -> list[str]:
    """Sample lines for one shipped worker-metric snapshot, with `extra`
    labels (WorkerId) appended to every series."""
    name, keys = snap["name"], tuple(snap["tags"])
    if snap["kind"] == "histogram":
        lines: list[str] = []
        for k, buckets in snap["buckets"].items():
            lines += _histogram_sample_lines(
                name, snap["boundaries"], buckets, snap["sums"][k],
                snap["counts"][k], keys, k, extra)
        return lines
    all_keys = list(keys) + list(extra)
    return [
        f"{name}{{{_fmt_label_pairs(all_keys, list(k) + list(extra.values()))}}} {v}"
        if all_keys else f"{name} {v}"
        for k, v in snap["values"].items()]


def _worker_metric_lines(seen: set) -> list[str]:
    """Merge worker-process registries (shipped as deltas on the event
    flush frames) into the scrape, tagged WorkerId."""
    from ray_tpu.core.runtime import Runtime, current_runtime
    rt = current_runtime()
    if not isinstance(rt, Runtime):
        return []
    per_worker = rt.worker_metric_snapshots()
    by_name: dict[str, list] = {}
    headers: dict[str, dict] = {}
    for wid, metrics in per_worker.items():
        tag = {"WorkerId": wid.hex()}
        for snap in metrics.values():
            headers.setdefault(snap["name"], snap)
            by_name.setdefault(snap["name"], []).extend(
                _render_snapshot_series(snap, tag))
    lines: list[str] = []
    for name, series in by_name.items():
        if name not in seen:  # TYPE/HELP must appear once per name
            snap = headers[name]
            lines += [f"# HELP {name} {snap['desc']}",
                      f"# TYPE {name} {snap['kind']}"]
        lines += series
    return lines


_STAGE_BOUNDARIES = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                     5.0, 30.0)


def _task_pipeline_lines(rt) -> list[str]:
    """Per-stage task latency histograms + drop accounting, derived from
    the head's TaskEventStorage AT SCRAPE TIME (nothing aggregates on the
    hot path — the store keeps raw per-attempt events)."""
    lines: list[str] = []
    try:
        rt.sync_task_store()
        store = rt.task_store
        stages = store.stage_durations()
    except Exception:  # noqa: BLE001 — scrape must survive store churn
        return lines
    for stage, durations in stages.items():
        name = f"ray_tpu_task_{stage}_seconds"
        lines += [f"# HELP {name} task {stage} latency "
                  "(task-event pipeline, derived at scrape)",
                  f"# TYPE {name} histogram"]
        buckets = [0] * (len(_STAGE_BOUNDARIES) + 1)
        for d in durations:
            for i, bound in enumerate(_STAGE_BOUNDARIES):
                if d <= bound:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
        lines += _histogram_sample_lines(
            name, _STAGE_BOUNDARIES, buckets, sum(durations),
            len(durations), (), ())
    lines.append("# TYPE ray_tpu_task_events_dropped_total counter")
    lines.append('ray_tpu_task_events_dropped_total{site="source_rings"} '
                 f"{store.dropped_at_sources}")
    lines.append('ray_tpu_task_events_dropped_total{site="head_store"} '
                 f"{store.dropped_at_head}")
    return lines


def _system_lines() -> list[str]:
    """Built-in cluster gauges rendered at scrape time (parity: the ~90
    C++ metric defs, stats/metric_defs.cc — the high-signal subset)."""
    from ray_tpu.core.runtime import Runtime, current_runtime
    rt = current_runtime()
    lines = []
    if not isinstance(rt, Runtime):
        return lines
    stats = rt.store.stats()
    rows = [
        ("ray_tpu_object_store_allocated_bytes", stats["allocated"]),
        ("ray_tpu_object_store_capacity_bytes", stats["capacity"]),
        ("ray_tpu_object_store_num_objects", stats["num_objects"]),
        ("ray_tpu_object_store_num_evictions", stats["num_evictions"]),
        ("ray_tpu_pending_tasks", len(rt.task_queue)),
        ("ray_tpu_alive_nodes",
         sum(1 for n in rt.nodes_table() if n["alive"])),
        ("ray_tpu_workers", len(rt.workers)),
        ("ray_tpu_actors_alive",
         sum(1 for a in rt.actors.values() if a.state == "alive")),
    ]
    for name, v in rows:
        lines += [f"# TYPE {name} gauge", f"{name} {v}"]
    lines += _task_pipeline_lines(rt)
    # Serve replica gauges, rendered from controller state at scrape time
    # (the serve_* request/latency series come from router processes).
    try:
        from ray_tpu.serve import api as serve_api
        st = serve_api.status()
        if st:
            lines.append("# TYPE serve_num_replicas gauge")
            for app, info in st.items():
                for dep, d in info.get("deployments", {}).items():
                    labels = _fmt_label_pairs(
                        ("application", "deployment"), (app, dep))
                    lines.append(f"serve_num_replicas{{{labels}}} "
                                 f'{d.get("running_replicas", 0)}')
    except Exception:  # noqa: BLE001 — serve absent or controller busy
        pass
    return lines


def prometheus_text() -> str:
    with _LOCK:
        metrics = list(_REGISTRY.values())
    lines: list[str] = _system_lines()
    seen = set()
    for m in metrics:
        lines += m.expose()
        seen.add(m.name)
    try:
        lines += _worker_metric_lines(seen)
    except Exception:  # noqa: BLE001 — a torn snapshot must not 500 /metrics
        pass
    return "\n".join(lines) + "\n"
