"""Emulated-agent swarm: hundreds of control-plane-faithful node agents
in ONE subprocess.

The real `Cluster` rig (util/many_agents.py) tops out around 64 agents on
a dev box — each node agent is a full process with a shm arena, a worker
pool and a native lease loop, so 256 of them exhaust memory and pid
budgets long before the HEAD becomes the bottleneck. This module inverts
the ratio: the head under test stays real (and in the parent process, so
`time.process_time()` isolates head CPU), while the agents collapse into
one selector loop that speaks the agent wire protocol faithfully:

  * `register_node` with a unique 16-byte node id and {"CPU": cpus}
  * versioned `heartbeat` load views (inflight churns during a storm, so
    the head's cluster-view broadcast actually fans out)
  * lease ingest on BOTH grant planes — `node_exec` (object specs) and
    `node_exec_raw` (pickled sideband) — with the same (task_id,
    lease_seq) dedup ledger a real agent keeps
  * real execution: fn blobs are cloudpickle-loaded and cached by fn_id,
    args deserialized, results serialized back as inline `node_done`
    outs — the driver's ObjectRefs resolve to REAL values, so a
    256-agent storm still asserts end-to-end correctness
  * task events shipped as ring 6-tuples, routed to head shards by
    `bucket_of(task_id)` when a shard map has been adopted from the
    cluster-view broadcast (head fallback otherwise) — the sharded tev
    ingest plane sees the same traffic shape real agents generate
  * per-agent cluster-view arrival stamps, aggregated into the
    view-fanout spread (first-to-last arrival per version) that the
    `cluster_scale` bench row reports

What is NOT emulated: object arenas (every result rides inline), worker
pools, the agent<->agent spill plane. Those planes scale with NODES, not
with the head — this harness exists to load the head's scheduling and
view-fanout planes, which is exactly the axis the shard subsystem moves.

Protocol with the parent (util/many_agents.py):
  stdout line "EMU_READY <n>"  — all n agents registered
  stdin EOF                    — drain, then print one stats JSON line
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import selectors
import socket
import sys
import threading
import time

from ray_tpu.core.head_shards import SHARD_MAP_KEY, bucket_of
from ray_tpu.core.transport import FrameBuffer, dial, enable_nodelay, send_msg


class _EmuAgent:
    __slots__ = ("nid", "sock", "fbuf", "registered", "executed", "hb_v",
                 "seen", "next_hb", "done_since_hb")

    def __init__(self, nid: bytes, sock: socket.socket):
        self.nid = nid
        self.sock = sock
        self.fbuf = FrameBuffer()
        self.registered = False
        self.executed = 0
        self.hb_v = 0
        self.seen: set = set()          # (task_id, lease_seq) dedup ledger
        self.next_hb = 0.0
        self.done_since_hb = 0


class Swarm:
    def __init__(self, head_addr, n_agents: int, cpus: float = 1.0,
                 hb_period: float = 1.0):
        self.head_addr = head_addr
        self.n = n_agents
        self.cpus = cpus
        self.hb_period = hb_period
        self.sel = selectors.DefaultSelector()
        self.agents: list[_EmuAgent] = []
        self.fn_cache: dict = {}         # fn_id -> callable (shared: same
        self.fn_blobs: dict = {}         # storm fn on every agent)
        # Shard routing (process-wide: one TCP channel per shard, like the
        # head's own mirror flusher — 256 dials per shard would be noise).
        self.smap: dict | None = None
        self.shard_socks: dict[int, socket.socket] = {}
        self.tev_shard = 0
        self.tev_head = 0
        self.dedup_hits = 0
        self.exec_errors = 0
        # View-fanout accounting: version -> [first_arrival, last, count].
        self.view_arrivals: dict[int, list] = {}
        self.view_spreads: list[float] = []
        self.stop = False

    # ---------------- lifecycle ----------------

    def start(self):
        for _ in range(self.n):
            nid = os.urandom(16)
            sock = dial(self.head_addr, timeout=30.0)
            enable_nodelay(sock)
            sock.setblocking(False)
            ag = _EmuAgent(nid, sock)
            send_msg(sock, ("register_node", nid, {"CPU": self.cpus},
                            ("127.0.0.1", 1), "emu", os.getpid(),
                            [], None, []))
            self.sel.register(sock, selectors.EVENT_READ, ag)
            self.agents.append(ag)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            self._poll(0.05)
            if all(a.registered for a in self.agents):
                return
        raise TimeoutError("emu agents did not all register")

    def run(self):
        """Serve until stop is set (parent closed stdin)."""
        base = time.monotonic()
        for i, ag in enumerate(self.agents):   # staggered heartbeats
            ag.next_hb = base + self.hb_period * (i / max(1, self.n))
        while not self.stop:
            self._poll(0.05)
            now = time.monotonic()
            for ag in self.agents:
                if now >= ag.next_hb:
                    ag.next_hb = now + self.hb_period
                    ag.hb_v += 1
                    view = {"v": ag.hb_v, "idle": 1, "backlog": 0,
                            "inflight": ag.done_since_hb}
                    ag.done_since_hb = 0
                    try:
                        send_msg(ag.sock, ("heartbeat", ag.nid, view))
                    except OSError:
                        pass

    def stats(self) -> dict:
        spreads = sorted(self.view_spreads)

        def pct(p):
            if not spreads:
                return 0.0
            return spreads[min(len(spreads) - 1, int(p * len(spreads)))]

        return {
            "executed_total": sum(a.executed for a in self.agents),
            "agents_used": sum(1 for a in self.agents if a.executed),
            "dedup_hits": self.dedup_hits,
            "exec_errors": self.exec_errors,
            "tev_shard": self.tev_shard,
            "tev_head": self.tev_head,
            "view_versions_complete": len(self.view_spreads),
            "view_spread_p50_ms": round(pct(0.50) * 1e3, 3),
            "view_spread_p95_ms": round(pct(0.95) * 1e3, 3),
            "sharded": self.smap is not None,
        }

    def close(self):
        for ag in self.agents:
            try:
                ag.sock.close()
            except OSError:
                pass
        for s in self.shard_socks.values():
            try:
                s.close()
            except OSError:
                pass
        self.sel.close()

    # ---------------- frame plumbing ----------------

    def _poll(self, timeout: float):
        for key, _ in self.sel.select(timeout):
            ag: _EmuAgent = key.data
            try:
                data = ag.sock.recv(1 << 20)
            except BlockingIOError:
                continue
            except OSError:
                data = b""
            if not data:
                try:
                    self.sel.unregister(ag.sock)
                except (KeyError, ValueError):
                    pass
                continue
            ag.fbuf.feed(data)
            for msg in ag.fbuf.frames():
                self._handle(ag, msg)

    def _handle(self, ag: _EmuAgent, msg):
        op = msg[0]
        if op == "batch":
            for inner in msg[1]:
                self._handle(ag, inner)
        elif op == "node_ack":
            ag.registered = True
        elif op == "cluster_view":
            self._on_view(ag, msg[1], msg[2])
        elif op == "node_exec":
            self._exec(ag, [(spec.task_id, fn_id, spec.lease_seq or 0,
                             blob, spec) for fn_id, blob, spec in msg[1]])
        elif op == "node_exec_raw":
            self._exec(ag, [(e[0], e[1], e[2] or 0, e[3],
                             pickle.loads(e[4])) for e in msg[1]])
        elif op == "shutdown_node":
            self.stop = True
        # lease_reclaim / spawn_worker / seq_skip etc.: no backlog, no
        # workers — nothing to do.

    def _on_view(self, ag: _EmuAgent, version: int, entries):
        now = time.monotonic()
        rec = self.view_arrivals.get(version)
        if rec is None:
            rec = self.view_arrivals[version] = [now, now, 0]
        rec[1] = now
        rec[2] += 1
        if rec[2] == self.n:
            self.view_spreads.append(rec[1] - rec[0])
            del self.view_arrivals[version]
        for nid, e in entries:
            if nid == SHARD_MAP_KEY:
                smap = e.get("smap")
                if smap is not None and (self.smap is None or
                                         smap["epoch"] > self.smap["epoch"]):
                    self.smap = smap
                    for s in self.shard_socks.values():
                        try:
                            s.close()
                        except OSError:
                            pass
                    self.shard_socks.clear()

    # ---------------- execution ----------------

    def _exec(self, ag: _EmuAgent, entries):
        """entries: (task_id, fn_id, lease_seq, blob|None, spec)."""
        from ray_tpu.core import serialization
        import cloudpickle
        dones = []
        tev = []
        for tid, fn_id, seq, blob, spec in entries:
            if blob is not None and fn_id is not None:
                self.fn_blobs[fn_id] = blob
            key = (tid, seq)
            if key in ag.seen:
                self.dedup_hits += 1
                continue
            ag.seen.add(key)
            try:
                fn = self.fn_cache.get(fn_id)
                if fn is None:
                    fn = cloudpickle.loads(self.fn_blobs[fn_id])
                    self.fn_cache[fn_id] = fn
                args, kwargs = serialization.deserialize(
                    spec.payload, spec.buffers)
                payload, bufs, _ = serialization.serialize_value(
                    fn(*args, **kwargs))
                status = "inline"
            except BaseException as exc:  # noqa: BLE001 — becomes an
                self.exec_errors += 1     # "err" out, like a real worker
                payload, bufs, _ = serialization.serialize_value(exc)
                status = "err"
            outs = [(rid, status, payload, list(bufs))
                    for rid in spec.return_ids]
            dones.append((tid, outs))
            ag.executed += 1
            ag.done_since_hb += 1
            tev.append((tid, 0, "FINISHED", time.time(),
                        (spec.name, spec.method_name), None))
        if dones:
            try:
                send_msg(ag.sock, ("node_done", dones))
            except OSError:
                pass
        if tev:
            self._ship_tev(ag, tev)

    def _ship_tev(self, ag: _EmuAgent, events):
        """Route ring events to the owning shard (head fallback) — the
        same split a real agent's _ship_tev_shards performs."""
        smap = self.smap
        residue = events
        if smap is not None:
            buckets = smap["buckets"]
            per_shard: dict[int, list] = {}
            for ev in events:
                per_shard.setdefault(buckets[bucket_of(ev[0])],
                                     []).append(ev)
            residue = []
            for sid, evs in per_shard.items():
                if self._shard_send(sid, ("tev_ingest", ag.nid, evs, 0)):
                    self.tev_shard += len(evs)
                else:
                    residue.extend(evs)
        if residue:
            self.tev_head += len(residue)
            try:
                send_msg(ag.sock, ("task_events", residue, 0))
            except OSError:
                pass

    def _shard_send(self, sid: int, msg) -> bool:
        sock = self.shard_socks.get(sid)
        if sock is None:
            smap = self.smap
            addr = next(((h, p) for s, h, p in smap["shards"] if s == sid),
                        None)
            if addr is None:
                return False
            try:
                sock = dial(addr, timeout=5.0)
                enable_nodelay(sock)
            except OSError:
                return False
            self.shard_socks[sid] = sock
        try:
            send_msg(sock, msg)
            return True
        except OSError:
            self.shard_socks.pop(sid, None)
            try:
                sock.close()
            except OSError:
                pass
            return False


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--head", required=True, help="host:port of the head")
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--cpus", type=float, default=1.0)
    ap.add_argument("--hb-period", type=float, default=1.0)
    args = ap.parse_args(argv)
    host, port = args.head.rsplit(":", 1)
    swarm = Swarm((host, int(port)), args.n, cpus=args.cpus,
                  hb_period=args.hb_period)
    swarm.start()
    print(f"EMU_READY {args.n}", flush=True)

    def _watch_stdin():
        try:
            while sys.stdin.buffer.read(4096):
                pass
        except OSError:
            pass
        swarm.stop = True

    threading.Thread(target=_watch_stdin, daemon=True,
                     name="emu-stdin").start()
    swarm.run()
    print(json.dumps(swarm.stats()), flush=True)
    swarm.close()


if __name__ == "__main__":
    main()
