"""OpenTelemetry task tracing.

Parity: reference `python/ray/util/tracing/tracing_helper.py` — opt-in
spans around task/actor submit and execute, with the trace context
propagated to the worker so execute spans are children of submit spans
(the reference injects method decorators at `ray.init(_tracing_startup_hook)`;
here `setup_tracing()` flips a module flag the hot paths check — zero cost
when tracing is off).
"""

from __future__ import annotations

_enabled = False
_tracer = None


def setup_tracing(tracer_provider=None):
    """Enable span emission. With no provider, installs a basic SDK
    provider (spans go to any configured exporter; use
    opentelemetry-sdk's ConsoleSpanExporter for stdout)."""
    global _enabled, _tracer
    from opentelemetry import trace
    if tracer_provider is not None:
        trace.set_tracer_provider(tracer_provider)
    elif not isinstance(trace.get_tracer_provider(),
                        trace.ProxyTracerProvider):
        pass  # a real provider is already installed
    else:
        try:
            from opentelemetry.sdk.trace import TracerProvider
            trace.set_tracer_provider(TracerProvider())
        except ImportError:
            pass
    _tracer = trace.get_tracer("ray_tpu")
    _enabled = True
    # Workers spawned after this point self-enable at boot.
    import os
    os.environ["RAY_TPU_TRACING"] = "1"


def maybe_setup_from_env():
    """Worker boot hook: join tracing if the driver enabled it."""
    import os
    if os.environ.get("RAY_TPU_TRACING") == "1" and not _enabled:
        try:
            setup_tracing()
        except Exception:  # noqa: BLE001 — tracing must never break boot
            pass


def tracing_enabled() -> bool:
    return _enabled


def inject_context() -> dict | None:
    """W3C traceparent headers for the current span (rides the TaskSpec)."""
    if not _enabled:
        return None
    from opentelemetry.propagate import inject
    carrier: dict = {}
    inject(carrier)
    return carrier or None


def submit_span(name: str, kind: str):
    """Context manager for a submit-side span (no-op contextless when
    tracing is off)."""
    import contextlib
    if not _enabled:
        return contextlib.nullcontext()
    return _tracer.start_as_current_span(
        f"{name}.remote()", attributes={"ray_tpu.kind": kind})


def execute_span(name: str, carrier: dict | None):
    """Worker-side execute span, child of the submitter's span."""
    import contextlib
    if not _enabled:
        return contextlib.nullcontext()
    from opentelemetry import context as otel_ctx
    from opentelemetry.propagate import extract
    ctx = extract(carrier) if carrier else otel_ctx.get_current()
    return _tracer.start_as_current_span(
        name, context=ctx, attributes={"ray_tpu.side": "execute"})
