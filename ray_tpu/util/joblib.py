"""joblib backend: scikit-learn's Parallel(...) on the cluster.

Parity: reference `python/ray/util/joblib/` (`register_ray` +
`ray_backend.py`). After `register_ray()`, `with
joblib.parallel_backend("ray_tpu"):` routes every joblib batch (e.g. a
scikit-learn grid search) through task submission.
"""

from __future__ import annotations

import threading

import ray_tpu


_backend_cls = None


def register_ray():
    """Register the 'ray_tpu' joblib parallel backend."""
    global _backend_cls
    from joblib import register_parallel_backend
    if _backend_cls is None:
        _backend_cls = _make_backend_class()
    register_parallel_backend("ray_tpu", _backend_cls)


class _BatchResult:
    def __init__(self, ref, callback):
        self._ref = ref
        if callback is not None:
            def run():
                try:
                    callback(self.get())
                except BaseException:  # noqa: BLE001 — joblib retries
                    pass
            threading.Thread(target=run, daemon=True).start()

    def get(self, timeout=None):
        return ray_tpu.get(self._ref, timeout=timeout)


def _make_backend_class():
    from joblib._parallel_backends import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        uses_threads = False
        supports_sharedmem = False

        def configure(self, n_jobs=1, parallel=None, **_kw):
            self.parallel = parallel
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
            if n_jobs is None or n_jobs < 0:
                return cpus
            return min(n_jobs, cpus)

        def apply_async(self, func, callback=None):
            # func is a joblib BatchedCalls: zero-arg callable returning a
            # list of results; it pickles via cloudpickle like any task arg.
            @ray_tpu.remote
            def _run_batch(f):
                return f()

            return _BatchResult(_run_batch.remote(func), callback)

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    return RayTpuBackend


