"""Distributed FIFO queue backed by an actor.

Parity: reference `python/ray/util/queue.py` (Queue actor wrapping
asyncio.Queue). Blocking semantics ride the actor's async concurrency.
"""

from __future__ import annotations

import ray_tpu


class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio
        self.q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout=None):
        import asyncio
        await asyncio.wait_for(self.q.put(item), timeout)
        return True

    async def get(self, timeout=None):
        import asyncio
        return await asyncio.wait_for(self.q.get(), timeout)

    async def qsize(self):
        return self.q.qsize()

    async def empty(self):
        return self.q.empty()

    async def full(self):
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        cls = ray_tpu.remote(**(actor_options or {"num_cpus": 0}))(
            _QueueActor)
        self.actor = cls.remote(maxsize)

    def put(self, item, block: bool = True, timeout: float | None = None):
        """Raises queue.Full on a non-blocking/timed-out put (reference
        ray.util.queue contract). Note block=False still costs one actor
        round trip — the queue state lives in the actor."""
        import queue as stdq
        try:
            ray_tpu.get(self.actor.put.remote(
                item, timeout if block else 0.001), timeout=None)
        except TimeoutError:  # asyncio.TimeoutError is this alias
            raise stdq.Full from None

    def get(self, block: bool = True, timeout: float | None = None):
        """Raises queue.Empty on a non-blocking/timed-out get."""
        import queue as stdq
        try:
            return ray_tpu.get(self.actor.get.remote(
                timeout if block else 0.001), timeout=None)
        except TimeoutError:
            raise stdq.Empty from None

    def put_async(self, item):
        return self.actor.put.remote(item)

    def get_async(self):
        return self.actor.get.remote()

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote(), timeout=60)

    def shutdown(self):
        ray_tpu.kill(self.actor)
