"""Generic channelized pubsub over the head hub.

Parity: `src/ray/pubsub/publisher.h:300` / `subscriber.h:73` — the
reusable publisher/subscriber channel the reference's subsystems share
(GCS pubsub, object-location subs), instead of each subsystem re-solving
delivery. Works from the driver (head process) and from any worker:

    from ray_tpu.util import pubsub
    pubsub.subscribe("jobs", "job-1", lambda m: print(m))
    pubsub.publish("jobs", "job-1", {"state": "RUNNING"})

Semantics: at-most-once doorbell delivery to every live subscriber of
(channel, key). Payloads of record belong in durable state (KV, object
store); the message is the wake-up. Subscriptions die with their worker.
`wait_for(channel, key)` is the blocking convenience built on it.
"""

from __future__ import annotations

import threading


def _rt():
    from ray_tpu.core.runtime import get_runtime
    return get_runtime()


def subscribe(channel: str, key: str, callback) -> None:
    """Register `callback(message)` for every publish to (channel, key)."""
    _rt().pubsub_subscribe(channel, key, callback)


def unsubscribe(channel: str, key: str, callback) -> None:
    _rt().pubsub_unsubscribe(channel, key, callback)


def publish(channel: str, key: str, message=None) -> None:
    """Deliver `message` to every current subscriber of (channel, key)."""
    _rt().pubsub_publish(channel, key, message)


def wait_for(channel: str, key: str, timeout: float | None = None):
    """Block until one message arrives on (channel, key); returns it.
    Raises TimeoutError on expiry."""
    ev = threading.Event()
    box: list = []

    def cb(message):
        box.append(message)
        ev.set()

    subscribe(channel, key, cb)
    try:
        if not ev.wait(timeout):
            raise TimeoutError(
                f"no message on ({channel!r}, {key!r}) in {timeout}s")
        return box[0]
    finally:
        unsubscribe(channel, key, cb)
