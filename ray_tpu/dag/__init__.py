"""Compiled graphs: static actor DAGs over mutable shm channels.

Parity: reference `python/ray/dag/` — build a DAG of actor method calls
(`dag_node.py`, `class_node.py`), `experimental_compile`
(`dag_node.py:265`) -> `CompiledDAG` (`compiled_dag_node.py:805`) whose
per-actor exec loops run once and stream values over mutable channels
(`do_exec_tasks`, `compiled_dag_node.py:193`); execute() writes the input
channel and returns a ref resolved from the output channel — no per-call
task submission RPCs.

TPU usage note (same as the reference's): the win is pipeline-parallel
inference — each stage actor holds a jitted program; channels carry host
arrays between stages while XLA overlaps per-stage device work.
"""

from __future__ import annotations

import threading

from ray_tpu.experimental.channel import (
    Channel,
    ChannelClosedError,
    TensorChannel,
)

__all__ = ["InputNode", "MultiOutputNode", "CompiledDAG",
           "ChannelClosedError"]


def _chan_cls(channel_type: str):
    if channel_type == "tensor":
        return TensorChannel
    if channel_type == "pickle":
        return Channel
    raise ValueError(f"unknown channel_type {channel_type!r} "
                     "(expected 'tensor' or 'pickle')")


def _default_channel_type() -> str:
    try:
        from ray_tpu.core.config import get_config
        return get_config().dag_channel_type
    except Exception:  # noqa: BLE001 — config not importable (bare tests)
        return "tensor"


class DAGNode:
    def experimental_compile(self, buffer_size_bytes: int = 1 << 20,
                             channel_type: str | None = None
                             ) -> "CompiledDAG":
        """channel_type: 'tensor' (default; array leaves cross each hop
        as one memcpy, no pickle) or 'pickle' (the legacy whole-value
        pickle frames)."""
        return CompiledDAG(self, buffer_size_bytes,
                           channel_type or _default_channel_type())

    def _deps(self):
        return [a for a in getattr(self, "args", ())
                if isinstance(a, DAGNode)]


class InputNode(DAGNode):
    """`with InputNode() as inp:` — the DAG's parameter (parity:
    dag/input_node.py)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    def __init__(self, handle, method_name: str, args, kwargs):
        self.handle = handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        if kwargs:
            raise ValueError("compiled graphs take positional args only")


class MultiOutputNode(DAGNode):
    def __init__(self, outputs):
        self.args = list(outputs)


def _exec_loop(instance, schedule, in_specs, out_path,
               channel_type: str = "pickle"):
    """Runs INSIDE the actor (via __run_with_instance__): read inputs,
    apply methods, write outputs, forever — until the input channels close.
    schedule: [(method_name, [arg_src...], out_idx)] in topo order; arg_src
    is ("chan", i) or ("const", value) or ("local", j) for a value produced
    earlier in this actor's own schedule. in_specs: [(path, reader_idx)].

    Tensor channels hand numpy leaves to the stage as READ-ONLY views
    aliasing the input channel; the ack (which lets the upstream writer
    overwrite) is released only AFTER the stage's output is written —
    writing forces the computation, so the input bytes are consumed by
    then. Stage methods must not retain input views across calls."""
    cls = _chan_cls(channel_type)
    ins = [cls(p, reader_idx=ri) for p, ri in in_specs]
    out = cls(out_path)
    tensor = channel_type == "tensor"
    try:
        while True:
            try:
                chan_vals = [ch.read(timeout=None) for ch in ins]
            except ChannelClosedError:
                out.close_writer()  # propagate EOF down the pipeline
                return "closed"
            local_vals = {}
            for method_name, arg_srcs, out_idx in schedule:
                args = []
                for kind, i in arg_srcs:
                    if kind == "chan":
                        args.append(chan_vals[i])
                    elif kind == "local":
                        args.append(local_vals[i])
                    else:
                        args.append(i)
                local_vals[out_idx] = getattr(instance, method_name)(*args)
            out.write(local_vals[schedule[-1][2]])
            if tensor:
                del chan_vals, args, local_vals  # drop borrowed views
                for ch in ins:
                    ch.release()
    finally:
        for ch in ins:
            ch.close()
        out.close()


class CompiledDAGRef:
    """Future over the compiled DAG's output channel (parity:
    CompiledDAGRef). Results must be consumed in execution order."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: float | None = 60.0):
        return self._dag._result(self._seq, timeout)


class CompiledDAG:
    def __init__(self, output_node: DAGNode, buffer_size_bytes: int,
                 channel_type: str = "tensor"):
        self._buffer = buffer_size_bytes
        self._channel_type = channel_type
        self._cls = _chan_cls(channel_type)
        self._lock = threading.Lock()
        self._seq = 0
        self._read_seq = 0
        self._results: dict[int, object] = {}
        self._build(output_node)

    # ---- compilation ----

    def _build(self, output_node: DAGNode):
        # Topo order over the node graph.
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(n: DAGNode):
            if id(n) in seen:
                return
            seen.add(id(n))
            for d in n._deps():
                visit(d)
            order.append(n)

        visit(output_node)
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) != 1:
            raise ValueError("a compiled DAG needs exactly one InputNode")
        if isinstance(output_node, MultiOutputNode):
            raise NotImplementedError(
                "MultiOutputNode compilation lands with multi-channel "
                "output support")

        # Per actor: schedule of its ops; channels between actors.
        node_actor = {}
        for n in order:
            if isinstance(n, ClassMethodNode):
                node_actor[id(n)] = n.handle._actor_id
        # A node needs an output channel iff a DIFFERENT actor (or the
        # driver, for the final node) consumes it. n_readers must equal the
        # number of reader CURSORS actually opened — one per consuming
        # ACTOR (an actor consuming a value in several ops still opens one
        # cursor), plus the driver on the output channel — or the writer's
        # per-reader ack backpressure waits on slots nobody writes.
        consumers: dict[int, set] = {id(output_node): {b"__driver__"}}
        input_actors: set = set()
        for n in order:
            if not isinstance(n, ClassMethodNode):
                continue
            aid = node_actor[id(n)]
            for d in n._deps():
                if isinstance(d, InputNode):
                    input_actors.add(aid)
                elif node_actor.get(id(d)) != aid:
                    consumers.setdefault(id(d), set()).add(aid)
        self._input_chan = self._cls(create=True, capacity=self._buffer,
                                     n_readers=max(1, len(input_actors)))
        chans: dict[int, Channel] = {
            nid: self._cls(create=True, capacity=self._buffer,
                           n_readers=len(aids))
            for nid, aids in consumers.items()}
        next_reader: dict[str, int] = {}  # channel path -> next reader idx
        # Reserve the driver's cursor (reader_idx 0) on the output channel.
        next_reader[chans[id(output_node)].path] = 1

        # Group consecutive ops per actor (topo order preserves deps).
        actor_plans: dict[bytes, dict] = {}
        local_idx: dict[int, tuple] = {}  # node id -> (actor_id, slot)

        def chan_arg(plan, path):
            paths = [p for p, _ in plan["in_specs"]]
            if path not in paths:
                ri = next_reader.get(path, 0)
                next_reader[path] = ri + 1
                plan["in_specs"].append((path, ri))
                paths.append(path)
            return "chan", paths.index(path)

        for n in order:
            if not isinstance(n, ClassMethodNode):
                continue
            aid = node_actor[id(n)]
            plan = actor_plans.setdefault(
                aid, {"handle": n.handle, "in_specs": [], "schedule": [],
                      "slots": 0})
            arg_srcs = []
            for a in n.args:
                if isinstance(a, InputNode):
                    arg_srcs.append(chan_arg(plan, self._input_chan.path))
                elif isinstance(a, DAGNode):
                    owner, slot = local_idx[id(a)]
                    if owner == aid:
                        arg_srcs.append(("local", slot))
                    else:
                        arg_srcs.append(chan_arg(plan, chans[id(a)].path))
                else:
                    arg_srcs.append(("const", a))
            slot = plan["slots"]
            plan["slots"] += 1
            plan["schedule"].append((n.method_name, arg_srcs, slot))
            local_idx[id(n)] = (aid, slot)

        # Each actor writes ONE channel (its last op) in this v1 — enforce
        # the common pipeline shape (a chain across actors).
        for nid in chans:
            owner_aid = node_actor.get(nid)
            if owner_aid is None:
                continue
            plan = actor_plans[owner_aid]
            last_slot = plan["schedule"][-1][2]
            if local_idx[nid][1] != last_slot:
                raise NotImplementedError(
                    "only pipeline-shaped DAGs are compiled in v1: each "
                    "actor's final op must be its cross-actor output")
            plan["out_path"] = chans[nid].path

        self._out_chan = chans[id(output_node)]
        self._loops = []
        from ray_tpu.core.actor import ActorMethod
        for aid, plan in actor_plans.items():
            m = ActorMethod(plan["handle"], "__run_with_instance__")
            ref = m._remote((_exec_loop, plan["schedule"],
                             plan["in_specs"], plan["out_path"],
                             self._channel_type), {})
            self._loops.append(ref)
        self._chans = list(chans.values())
        # The driver drains the output channel eagerly so backpressure
        # never waits on a user calling .get().
        self._cv = threading.Condition()
        self._drain = threading.Thread(target=self._drain_loop, daemon=True,
                                       name="dag-drain")
        self._drain.start()

    # ---- execution ----

    def execute(self, value) -> CompiledDAGRef:
        with self._lock:
            self._input_chan.write(value)
            self._seq += 1
            return CompiledDAGRef(self, self._seq)

    def _drain_loop(self):
        tensor = self._channel_type == "tensor"
        while True:
            try:
                # copy=True: the user may hold the result indefinitely, so
                # numpy leaves must not borrow the channel region.
                val = (self._out_chan.read(timeout=None, copy=True)
                       if tensor else self._out_chan.read(timeout=None))
            except (ChannelClosedError, OSError, ValueError):
                return
            with self._cv:
                self._read_seq += 1
                self._results[self._read_seq] = val
                self._cv.notify_all()

    def _result(self, seq: int, timeout):
        with self._cv:
            if not self._cv.wait_for(lambda: seq in self._results,
                                     timeout=timeout):
                raise TimeoutError(f"compiled DAG result {seq} timed out")
            return self._results.pop(seq)

    def teardown(self):
        self._input_chan.close_writer()
        import ray_tpu
        for ref in self._loops:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:  # noqa: BLE001 — loop may already be gone
                pass
        seen = set()
        for ch in [self._input_chan, self._out_chan, *self._chans]:
            if ch.path in seen:
                continue
            seen.add(ch.path)
            ch.close()
            ch.unlink()
