"""Llama-family decoder: RMSNorm + RoPE + GQA attention + SwiGLU (or MoE).

TPU-first choices:
- layers stacked on a leading axis and iterated with lax.scan: one compiled
  layer body regardless of depth (fast compiles, remat-friendly);
- attention pluggable: pallas flash (single shard), ring (sp over ICI ring),
  ulysses (sp all-to-all) — long-context parallelism is a config, not a fork;
- MoE in GSPMD dense form: experts on the "ep" mesh axis, einsum over the
  expert dimension so the partitioner places each expert's FLOPs on its
  owner device;
- bfloat16 params/activations, fp32 logits + softmax accumulation.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.layers import apply_rope, rmsnorm, rope, swiglu


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1408
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    # MoE: 0 = dense. When > 0 every layer is a top-k MoE layer.
    moe_experts: int = 0
    moe_top_k: int = 2
    dtype: str = "float32"
    remat: bool = False
    attn_impl: str = "auto"  # auto|pallas|reference|interpret|ring|ulysses
    tie_embeddings: bool = True
    # Layer-loop lowering: None = auto (unroll small models — the scan's
    # per-iteration dynamic-update-slice activation stacking costs ~13% of
    # a GPT-small train step; at billion-param scale the copies amortize
    # and scan keeps compiles fast). True/False forces it.
    unroll_layers: bool | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def init_params(config: ModelConfig, key) -> dict:
    c = config
    dt = c.jdtype
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, hd = c.d_model, c.head_dim

    def norm_init(shape):
        return jnp.ones(shape, dt)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    L = c.n_layers
    ks = jax.random.split(k_layers, 8)
    layer = {
        "attn_norm": norm_init((L, d)),
        "wq": dense_init(ks[0], (L, d, c.n_heads * hd), d),
        "wk": dense_init(ks[1], (L, d, c.n_kv_heads * hd), d),
        "wv": dense_init(ks[2], (L, d, c.n_kv_heads * hd), d),
        "wo": dense_init(ks[3], (L, c.n_heads * hd, d), c.n_heads * hd),
        "mlp_norm": norm_init((L, d)),
    }
    if c.moe_experts:
        X = c.moe_experts
        layer.update({
            "router": dense_init(ks[4], (L, d, X), d),
            "wg": dense_init(ks[5], (L, X, d, c.d_ff), d),
            "wu": dense_init(ks[6], (L, X, d, c.d_ff), d),
            "wd": dense_init(ks[7], (L, X, c.d_ff, d), c.d_ff),
        })
    else:
        layer.update({
            "wg": dense_init(ks[5], (L, d, c.d_ff), d),
            "wu": dense_init(ks[6], (L, d, c.d_ff), d),
            "wd": dense_init(ks[7], (L, c.d_ff, d), c.d_ff),
        })
    params = {
        "embed": (jax.random.normal(k_embed, (c.vocab, d), jnp.float32)
                  * 0.02).astype(dt),
        "layers": layer,
        "final_norm": norm_init((d,)),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense_init(k_head, (d, c.vocab), d)
    return params


def param_logical_axes(config: ModelConfig) -> dict:
    """Logical sharding axes per param (leading scan axis = "layer")."""
    c = config
    layer = {
        "attn_norm": ("layer", None),
        "wq": ("layer", "embed", "heads"),
        "wk": ("layer", "embed", "kv_heads"),
        "wv": ("layer", "embed", "kv_heads"),
        "wo": ("layer", "heads", "embed"),
        "mlp_norm": ("layer", None),
    }
    if c.moe_experts:
        layer.update({
            "router": ("layer", "embed", None),
            "wg": ("layer", "expert", "embed", "mlp"),
            "wu": ("layer", "expert", "embed", "mlp"),
            "wd": ("layer", "expert", "mlp", "embed"),
        })
    else:
        layer.update({
            "wg": ("layer", "embed", "mlp"),
            "wu": ("layer", "embed", "mlp"),
            "wd": ("layer", "mlp", "embed"),
        })
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": (None,),
    }
    if not c.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _attention(x, lp, c: ModelConfig, sin, cos, mesh):
    b, s, d = x.shape
    h, hkv, hd = c.n_heads, c.n_kv_heads, c.head_dim
    q = jnp.einsum("bsd,dk->bsk", x, lp["wq"]).reshape(b, s, h, hd)
    k = jnp.einsum("bsd,dk->bsk", x, lp["wk"]).reshape(b, s, hkv, hd)
    v = jnp.einsum("bsd,dk->bsk", x, lp["wv"]).reshape(b, s, hkv, hd)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    if c.attn_impl in ("ring", "ulysses"):
        if hkv != h:  # GQA broadcast before the sp collective
            rep = h // hkv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if c.attn_impl == "ring":
            from ray_tpu.parallel.ring_attention import ring_attention
            o = ring_attention(q, k, v, mesh, causal=True)
        else:
            from ray_tpu.parallel.ulysses import ulysses_attention
            o = ulysses_attention(q, k, v, mesh, causal=True)
    else:
        o = flash_attention(q, k, v, causal=True, impl=c.attn_impl)
    o = o.reshape(b, s, h * hd)
    return jnp.einsum("bsk,kd->bsd", o, lp["wo"])


def _moe(x, lp, c: ModelConfig):
    """Top-k MoE in GSPMD dense form: every expert computes, the router's
    top-k weights zero the rest; the "expert" einsum axis shards over "ep"."""
    probs = jax.nn.softmax(
        jnp.einsum("bsd,dx->bsx", x, lp["router"],
                   preferred_element_type=jnp.float32), axis=-1)
    top_w, top_i = jax.lax.top_k(probs, c.moe_top_k)          # [b,s,k]
    top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
    gate = jnp.zeros_like(probs).at[
        jnp.arange(probs.shape[0])[:, None, None],
        jnp.arange(probs.shape[1])[None, :, None],
        top_i].set(top_w.astype(probs.dtype))                  # [b,s,X]
    h = jnp.einsum("bsd,xdf->bsxf", x, lp["wg"])
    u = jnp.einsum("bsd,xdf->bsxf", x, lp["wu"])
    act = jax.nn.silu(h) * u
    y = jnp.einsum("bsxf,xfd->bsxd", act, lp["wd"])
    return jnp.einsum("bsxd,bsx->bsd", y, gate.astype(x.dtype))


def _mlp(x, lp):
    return swiglu(x, lp["wg"], lp["wu"], lp["wd"])


def hidden_states(params, tokens, config: ModelConfig, mesh=None):
    """tokens [batch, seq] -> final-norm hidden states [batch, seq, d]."""
    c = config
    if mesh is not None and mesh.devices.size > 1:
        # One-hot matmul lookup instead of gather (the iota-embed trick):
        # the SPMD partitioner handles a [b,s,v] x [v,d] contraction over
        # the tp-sharded vocab axis cleanly (masked matmul + psum), where
        # the equivalent gather forced "Involuntary full rematerialization"
        # (spmd_partitioner.cc:652) of the embedding activation in fwd AND
        # bwd — the table's embed axis is fsdp-sharded on a transposed
        # device order the partitioner cannot leave cheaply. The explicit
        # constraint pins the result to the activation layout (batch over
        # the data axes, embed replicated) so the bwd table grad
        # partitions as a plain matmul too.
        from ray_tpu.parallel.sharding import activation_batch_sharded
        table = params["embed"]
        onehot = jax.nn.one_hot(tokens, table.shape[0], dtype=table.dtype)
        x = jnp.einsum("bsv,vd->bsd", onehot, table)
        x = activation_batch_sharded(x, mesh)
    else:
        x = jnp.take(params["embed"], tokens, axis=0)
    positions = jnp.arange(tokens.shape[1])
    sin, cos = rope(positions, c.head_dim, c.rope_theta)

    def layer_body(x, lp):
        h = x + _attention(rmsnorm(x, lp["attn_norm"], c.norm_eps),
                           lp, c, sin, cos, mesh)
        normed = rmsnorm(h, lp["mlp_norm"], c.norm_eps)
        out = h + (_moe(normed, lp, c) if c.moe_experts else _mlp(normed, lp))
        return out, None

    body = layer_body
    if c.remat:
        body = jax.checkpoint(layer_body)
    unroll = c.unroll_layers
    if unroll is None:
        unroll = (not c.remat and c.n_layers <= 12 and c.d_model <= 1024)
    x, _ = jax.lax.scan(body, x, params["layers"],
                        unroll=c.n_layers if unroll else 1)
    return rmsnorm(x, params["final_norm"], c.norm_eps)


def forward(params, tokens, config: ModelConfig, mesh=None):
    """tokens [batch, seq] -> logits [batch, seq, vocab] (fp32).

    The head matmul keeps bf16 inputs with an fp32 accumulator
    (preferred_element_type): full MXU rate, fp32 logits out — upcasting the
    operands first would run the largest matmul in the model at fp32 rate.
    """
    x = hidden_states(params, tokens, config, mesh)
    head = (params["embed"].T if config.tie_embeddings else params["lm_head"])
    return jnp.einsum("bsd,dv->bsv", x, head,
                      preferred_element_type=jnp.float32)


def _xent(x, head, targets):
    """Cross entropy of one sequence chunk; logits never leave this scope.

    Gathers target logits and subtracts the row logsumexp directly rather
    than materializing the full log-softmax tensor (which would double the
    [b, s, vocab] fp32 footprint)."""
    logits = jnp.einsum("bsd,dv->bsv", x, head,
                        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return tgt - lse


def loss_fn(params, batch, config: ModelConfig, mesh=None,
            loss_chunk: int = 512):
    """Next-token cross entropy; batch = {"tokens": [b, s+1]} or
    {"inputs": [b,s], "targets": [b,s]}.

    The [b, s, vocab] fp32 logits tensor dominates training HBM at scale, so
    the head+softmax runs in rematerialized sequence chunks: peak logits
    memory is b*loss_chunk*vocab and the backward recomputes each chunk.
    """
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    x = hidden_states(params, inputs, config, mesh)
    head = (params["embed"].T if config.tie_embeddings else params["lm_head"])
    b, s, d = x.shape
    # Chunk only when the full fp32 logits tensor would be large enough to
    # matter (>1 GiB); below that the extra scan costs more than it saves.
    if (s % loss_chunk == 0 and s > loss_chunk
            and 4 * b * s * config.vocab > (1 << 30)):
        nc = s // loss_chunk
        xc = x.reshape(b, nc, loss_chunk, d).transpose(1, 0, 2, 3)
        tc = targets.reshape(b, nc, loss_chunk).transpose(1, 0, 2)
        ll = jax.lax.map(
            jax.checkpoint(lambda args: _xent(args[0], head, args[1])),
            (xc, tc))                                # [nc, b, loss_chunk]
        ll = ll.transpose(1, 0, 2).reshape(b, s)
    else:
        ll = _xent(x, head, targets)
    mask = batch.get("mask")
    if mask is None:
        return -jnp.mean(ll)
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
