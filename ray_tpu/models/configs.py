"""Named model configs (tiny test configs through Llama-3-8B class)."""

from __future__ import annotations

from ray_tpu.models.transformer import ModelConfig


def tiny(**kw) -> ModelConfig:
    """CPU-test scale."""
    return ModelConfig(vocab=256, d_model=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=128, **kw)


def tiny_moe(**kw) -> ModelConfig:
    return ModelConfig(vocab=256, d_model=64, n_layers=2, n_heads=4,
                       n_kv_heads=4, d_ff=128, moe_experts=4, moe_top_k=2,
                       **kw)


def llama3_8b(**kw) -> ModelConfig:
    """Llama-3-8B geometry (BASELINE north-star FSDP config)."""
    return ModelConfig(vocab=128256, d_model=4096, n_layers=32, n_heads=32,
                       n_kv_heads=8, d_ff=14336, rope_theta=500000.0,
                       dtype="bfloat16", remat=True, **kw)


def llama3_1b(**kw) -> ModelConfig:
    return ModelConfig(vocab=128256, d_model=2048, n_layers=16, n_heads=32,
                       n_kv_heads=8, d_ff=8192, rope_theta=500000.0,
                       dtype="bfloat16", **kw)


def bench_125m(**kw) -> ModelConfig:
    """Single-chip bench scale (GPT-small geometry)."""
    return ModelConfig(vocab=32000, d_model=768, n_layers=12, n_heads=12,
                       n_kv_heads=12, d_ff=3072, dtype="bfloat16", **kw)


def llama_125m(**kw) -> ModelConfig:
    """Default serving scale (alias of the bench geometry)."""
    return bench_125m(**kw)


def llama3_70b(**kw) -> ModelConfig:
    """Llama-3-70B geometry (multi-slice FSDP+TP target)."""
    return ModelConfig(vocab=128256, d_model=8192, n_layers=80, n_heads=64,
                       n_kv_heads=8, d_ff=28672, rope_theta=500000.0,
                       dtype="bfloat16", remat=True, **kw)


def mixtral_8x7b(**kw) -> ModelConfig:
    """Mixtral-8x7B geometry: 8-expert top-2 MoE (the EP mesh-axis
    flagship)."""
    return ModelConfig(vocab=32000, d_model=4096, n_layers=32, n_heads=32,
                       n_kv_heads=8, d_ff=14336, rope_theta=1e6,
                       moe_experts=8, moe_top_k=2,
                       dtype="bfloat16", remat=True, **kw)


def qwen2_7b(**kw) -> ModelConfig:
    """Qwen-2-7B-class geometry (GQA, untied head)."""
    return ModelConfig(vocab=152064, d_model=3584, n_layers=28, n_heads=28,
                       n_kv_heads=4, d_ff=18944, rope_theta=1e6,
                       dtype="bfloat16", remat=True, tie_embeddings=False,
                       **kw)
