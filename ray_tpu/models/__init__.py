"""Model family: Llama-style decoder transformers, dense and MoE.

Functional style (pure pytrees + apply fns), not a port of the reference's
torch models: parameters carry logical sharding axes so one model definition
lowers to DP/FSDP/TP/SP/EP via the rules table in ray_tpu.parallel.sharding.
"""

from ray_tpu.models.transformer import (
    ModelConfig,
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.models import configs

__all__ = ["ModelConfig", "init_params", "forward", "loss_fn",
           "param_logical_axes", "configs"]
