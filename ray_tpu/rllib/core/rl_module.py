"""RLModule: the network abstraction of the RL stack.

Parity: reference `rllib/core/rl_module/rl_module.py:260` (forward_train /
forward_exploration / forward_inference over a framework-specific network).
TPU-native redesign: a module is a *pure-function spec* — `init(key)` builds
a param pytree, `forward(params, obs)` is a jit-compiled pure function — so
the same module runs unmodified inside `jax.jit`, `pjit` over a learner
mesh, or on an env-runner's CPU backend. No nn.Module state, no framework
switch (reference carries torch+tf2 twins, torch_rl_module.py/tf_rl_module.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


def _dense_init(key, shape, scale=None):
    fan_in = shape[0]
    scale = scale if scale is not None else 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -scale, scale)


@dataclass
class MLPSpec:
    """Shared MLP torso spec."""

    obs_dim: int
    hidden: tuple = (64, 64)
    activation: str = "tanh"

    def init(self, key):
        params = []
        dims = [self.obs_dim, *self.hidden]
        for i in range(len(dims) - 1):
            key, k1, k2 = jax.random.split(key, 3)
            params.append({"w": _dense_init(k1, (dims[i], dims[i + 1])),
                           "b": jnp.zeros((dims[i + 1],))})
        return params

    def apply(self, params, x):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        for layer in params:
            x = act(x @ layer["w"] + layer["b"])
        return x


@dataclass
class ActorCriticModule:
    """Policy + value heads over a shared-or-split MLP torso (the default
    module for PPO/IMPALA, parity: rllib's default PPO RLModule/catalog)."""

    obs_dim: int
    num_actions: int
    hidden: tuple = (64, 64)
    free_log_std: bool = False  # continuous-action variant flag

    def init(self, key) -> dict:
        kp, kv, k1, k2 = jax.random.split(key, 4)
        pi_torso = MLPSpec(self.obs_dim, self.hidden)
        vf_torso = MLPSpec(self.obs_dim, self.hidden)
        return {
            "pi": pi_torso.init(kp),
            "vf": vf_torso.init(kv),
            "pi_head": {"w": _dense_init(k1, (self.hidden[-1], self.num_actions), 0.01),
                        "b": jnp.zeros((self.num_actions,))},
            "vf_head": {"w": _dense_init(k2, (self.hidden[-1], 1), 1.0),
                        "b": jnp.zeros((1,))},
        }

    def forward(self, params, obs):
        """Returns (logits, value). Pure; safe under jit/pjit/vmap."""
        torso = MLPSpec(self.obs_dim, self.hidden)
        hp = torso.apply(params["pi"], obs)
        hv = torso.apply(params["vf"], obs)
        logits = hp @ params["pi_head"]["w"] + params["pi_head"]["b"]
        value = (hv @ params["vf_head"]["w"] + params["vf_head"]["b"])[..., 0]
        return logits, value

    # --- the three forward modes (parity: rl_module.py:260) ---

    def forward_inference(self, params, obs):
        logits, _ = self.forward(params, obs)
        return jnp.argmax(logits, axis=-1)

    def forward_exploration(self, params, obs, key):
        logits, value = self.forward(params, obs)
        action = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[..., None], -1)[..., 0]
        return action, logp_a, value

    def forward_train(self, params, obs):
        return self.forward(params, obs)


@dataclass
class QModule:
    """Q-network for DQN (online + target param trees)."""

    obs_dim: int
    num_actions: int
    hidden: tuple = (64, 64)
    dueling: bool = True

    def init(self, key) -> dict:
        kt, ka, kv = jax.random.split(key, 3)
        torso = MLPSpec(self.obs_dim, self.hidden)
        p = {"torso": torso.init(kt),
             "adv": {"w": _dense_init(ka, (self.hidden[-1], self.num_actions)),
                     "b": jnp.zeros((self.num_actions,))}}
        if self.dueling:
            p["val"] = {"w": _dense_init(kv, (self.hidden[-1], 1)),
                        "b": jnp.zeros((1,))}
        return p

    def forward(self, params, obs):
        torso = MLPSpec(self.obs_dim, self.hidden)
        h = torso.apply(params["torso"], obs)
        adv = h @ params["adv"]["w"] + params["adv"]["b"]
        if self.dueling:
            val = h @ params["val"]["w"] + params["val"]["b"]
            return val + adv - adv.mean(axis=-1, keepdims=True)
        return adv

    forward_train = forward

    def forward_inference(self, params, obs):
        return jnp.argmax(self.forward(params, obs), axis=-1)

    def forward_exploration(self, params, obs, key, tau: float = 1.0):
        """Boltzmann exploration over Q values (fits the shared env-runner
        interface; the reference's epsilon-greedy schedule is a stateful
        connector — softmax exploration needs no schedule plumbing)."""
        q = self.forward(params, obs)
        action = jax.random.categorical(key, q / tau)
        logp = jax.nn.log_softmax(q / tau)
        logp_a = jnp.take_along_axis(logp, action[..., None], -1)[..., 0]
        return action, logp_a, q.max(axis=-1)


@dataclass
class SquashedGaussianModule:
    """Tanh-squashed Gaussian policy + twin Q critics for continuous
    control (the SAC module; parity: rllib's default SAC RLModule).
    Actions live in [low, high] via tanh rescaling; log-probs carry the
    tanh change-of-variables correction."""

    obs_dim: int
    action_dim: int
    low: tuple
    high: tuple
    hidden: tuple = (64, 64)

    action_kind = "continuous"
    LOG_STD_MIN = -10.0
    LOG_STD_MAX = 2.0

    def _scale(self):
        low = jnp.asarray(self.low)
        high = jnp.asarray(self.high)
        return (high - low) / 2.0, (high + low) / 2.0

    def init(self, key) -> dict:
        kp, kh, k1 = jax.random.split(key, 3)
        torso = MLPSpec(self.obs_dim, self.hidden, activation="relu")
        qspec = MLPSpec(self.obs_dim + self.action_dim, self.hidden,
                        activation="relu")
        kq1, kq2, kh1, kh2 = jax.random.split(kh, 4)
        return {
            "pi": torso.init(kp),
            "pi_head": {"w": _dense_init(k1, (self.hidden[-1],
                                              2 * self.action_dim), 0.01),
                        "b": jnp.zeros((2 * self.action_dim,))},
            "q1": qspec.init(kq1),
            "q1_head": {"w": _dense_init(kh1, (self.hidden[-1], 1)),
                        "b": jnp.zeros((1,))},
            "q2": qspec.init(kq2),
            "q2_head": {"w": _dense_init(kh2, (self.hidden[-1], 1)),
                        "b": jnp.zeros((1,))},
        }

    def pi(self, params, obs):
        torso = MLPSpec(self.obs_dim, self.hidden, activation="relu")
        h = torso.apply(params["pi"], obs)
        out = h @ params["pi_head"]["w"] + params["pi_head"]["b"]
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mean, log_std

    def sample(self, params, obs, key):
        """Reparameterized sample -> (action in env bounds, logp)."""
        mean, log_std = self.pi(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre_tanh = mean + std * eps
        tanh_a = jnp.tanh(pre_tanh)
        # N(mean, std) logp minus the tanh Jacobian (numerically stable).
        logp = (-0.5 * (eps ** 2) - log_std
                - 0.5 * np.log(2 * np.pi)).sum(-1)
        logp -= (2 * (np.log(2.0) - pre_tanh
                      - jax.nn.softplus(-2 * pre_tanh))).sum(-1)
        scale, shift = self._scale()
        # Affine rescale to env bounds has its own Jacobian: |d a/d tanh| =
        # scale per dim.
        logp -= jnp.log(scale).sum()
        return tanh_a * scale + shift, logp

    def log_prob(self, params, obs, action):
        """log pi(action | obs) for env-bounded actions (inverse of
        `sample`'s squash-and-rescale; used by CQL's BC warmup)."""
        scale, shift = self._scale()
        tanh_a = jnp.clip((action - shift) / scale, -0.999999, 0.999999)
        pre_tanh = jnp.arctanh(tanh_a)
        mean, log_std = self.pi(params, obs)
        std = jnp.exp(log_std)
        logp = (-0.5 * jnp.square((pre_tanh - mean) / std) - log_std
                - 0.5 * np.log(2 * np.pi)).sum(-1)
        logp -= (2 * (np.log(2.0) - pre_tanh
                      - jax.nn.softplus(-2 * pre_tanh))).sum(-1)
        logp -= jnp.log(scale).sum()
        return logp

    def q_values(self, params, obs, action):
        qspec = MLPSpec(self.obs_dim + self.action_dim, self.hidden,
                        activation="relu")
        x = jnp.concatenate([obs, action], axis=-1)
        h1 = qspec.apply(params["q1"], x)
        h2 = qspec.apply(params["q2"], x)
        q1 = (h1 @ params["q1_head"]["w"] + params["q1_head"]["b"])[..., 0]
        q2 = (h2 @ params["q2_head"]["w"] + params["q2_head"]["b"])[..., 0]
        return q1, q2

    # --- env-runner interface ---

    def forward_exploration(self, params, obs, key):
        action, logp = self.sample(params, obs, key)
        return action, logp, jnp.zeros(obs.shape[0])

    def forward_inference(self, params, obs):
        mean, _ = self.pi(params, obs)
        scale, shift = self._scale()
        return jnp.tanh(mean) * scale + shift


@dataclass
class ConvSpec:
    """Conv torso for image observations (parity: rllib catalog CNN
    stacks). Channel-last NHWC layout — the natural layout for TPU, where
    XLA tiles channels onto MXU lanes. Input may arrive flat [B, H*W*C]
    (the env-runner's layout); it is reshaped here."""

    obs_shape: tuple  # (H, W, C)
    filters: tuple    # ((out_channels, kernel, stride), ...)
    dense: int = 128

    def init(self, key):
        params = []
        c_in = self.obs_shape[-1]
        h, w = self.obs_shape[0], self.obs_shape[1]
        for out_c, k, s in self.filters:
            key, kk = jax.random.split(key)
            fan_in = k * k * c_in
            params.append({"w": _dense_init(kk, (k, k, c_in, out_c),
                                            1.0 / math.sqrt(fan_in)),
                           "b": jnp.zeros((out_c,))})
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            c_in = out_c
        key, kd = jax.random.split(key)
        params.append({"w": _dense_init(kd, (h * w * c_in, self.dense)),
                       "b": jnp.zeros((self.dense,))})
        return params

    def apply(self, params, x):
        # Accept any leading batch dims ([B, ...] or IMPALA's [T, E, ...]),
        # flat or image-shaped trailing dims.
        shape = tuple(self.obs_shape)
        if x.shape[-len(shape):] == shape:
            lead = x.shape[:-len(shape)]
        else:
            lead = x.shape[:-1]  # flat [..., H*W*C]
        x = x.reshape((-1,) + shape)
        for (out_c, k, s), layer in zip(self.filters, params[:-1]):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + layer["b"])
        x = x.reshape(x.shape[0], -1)
        head = params[-1]
        out = jax.nn.relu(x @ head["w"] + head["b"])
        return out.reshape(lead + (self.dense,))


# Standard conv stacks: the small net for 10x10 MinAtar-class grids, the
# nature-CNN for 84x84 Atari frames (parity: rllib catalog defaults).
MINATAR_FILTERS = ((16, 3, 1),)
NATURE_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


@dataclass
class CNNActorCriticModule:
    """Policy + value heads over a shared conv torso, for image obs
    (parity: rllib's default CNN PPO module; shared torso because conv
    features transfer between heads and halve the FLOPs)."""

    obs_shape: tuple
    num_actions: int
    filters: tuple = MINATAR_FILTERS
    dense: int = 128

    def _torso(self):
        return ConvSpec(self.obs_shape, self.filters, self.dense)

    def init(self, key) -> dict:
        kt, k1, k2 = jax.random.split(key, 3)
        torso = self._torso()
        return {
            "torso": torso.init(kt),
            "pi_head": {"w": _dense_init(k1, (self.dense,
                                              self.num_actions), 0.01),
                        "b": jnp.zeros((self.num_actions,))},
            "vf_head": {"w": _dense_init(k2, (self.dense, 1), 1.0),
                        "b": jnp.zeros((1,))},
        }

    def forward(self, params, obs):
        h = self._torso().apply(params["torso"], obs)
        logits = h @ params["pi_head"]["w"] + params["pi_head"]["b"]
        value = (h @ params["vf_head"]["w"] + params["vf_head"]["b"])[..., 0]
        return logits, value

    forward_train = forward

    def forward_inference(self, params, obs):
        logits, _ = self.forward(params, obs)
        return jnp.argmax(logits, axis=-1)

    def forward_exploration(self, params, obs, key):
        logits, value = self.forward(params, obs)
        action = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[..., None], -1)[..., 0]
        return action, logp_a, value


def module_for_env(env_like, hidden=(64, 64), kind="actor_critic"):
    """Build the default module from (obs_space, action_space) shapes;
    Box action spaces get the continuous (squashed-Gaussian) module."""
    import gymnasium as gym
    obs_dim = int(np.prod(env_like.observation_space.shape))
    space = env_like.action_space
    if isinstance(space, gym.spaces.Box):
        if kind != "sac":
            raise ValueError(
                f"only SAC supports continuous (Box) action spaces so far; "
                f"{kind!r} modules need a Discrete space (got {space})")
        low = np.asarray(space.low, np.float32).ravel()
        high = np.asarray(space.high, np.float32).ravel()
        if not (np.isfinite(low).all() and np.isfinite(high).all()):
            raise ValueError(
                f"continuous control needs bounded actions; got Box with "
                f"low={space.low}, high={space.high}")
        return SquashedGaussianModule(
            obs_dim, int(np.prod(space.shape)),
            tuple(low.tolist()), tuple(high.tolist()), hidden)
    num_actions = int(space.n)
    obs_shape = tuple(env_like.observation_space.shape)
    if kind == "actor_critic" and len(obs_shape) == 3 and obs_shape[0] >= 8:
        # Image observations get the conv module (parity: rllib catalog
        # picking a CNN stack for 2D obs): small net for MinAtar-class
        # grids, nature-CNN for Atari-sized frames.
        filters, dense = ((NATURE_FILTERS, 512) if obs_shape[0] >= 64
                          else (MINATAR_FILTERS, 128))
        return CNNActorCriticModule(obs_shape, num_actions,
                                    filters=filters, dense=dense)
    if kind == "q":
        return QModule(obs_dim, num_actions, hidden)
    return ActorCriticModule(obs_dim, num_actions, hidden)
