"""Fully on-device PPO training: rollout + GAE + minibatch epochs in ONE
compiled program.

Parity target: the reference's PPO training_step
(`rllib/algorithms/ppo/ppo.py:388` — synchronous_parallel_sample on host
workers, obs tensors shipped to a torch-GPU learner). TPU-native
redesign: with a jax-native env (env/jax_env.py), the entire training
iteration — T env steps x B envs of policy forwards + env dynamics +
frame rendering, GAE over the trajectory, advantage normalization, and
the epochs x shuffled-minibatches PPO update — is a single `jax.jit`
dispatch. Observations never leave the accelerator; the host fetches
five scalars per iteration. On a tunneled chip this turns a ~50ms
round-trip per *step* into one per *iteration*.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax

from ray_tpu.rllib.env.jax_env import JaxVecEnv, build_rollout


def build_ppo_train_iter(vec_env: JaxVecEnv, module, *, T: int,
                         num_epochs: int, minibatch_size: int,
                         gamma: float, lam: float, clip: float,
                         vf_coef: float, ent_coef: float, tx):
    """Returns jit(train_iter)(params, opt_state, vec_state, key) ->
    (params, opt_state, vec_state, key, metrics). `tx` is the optax
    transform shared with the Learner so checkpoints stay compatible."""
    from ray_tpu.rllib.algorithms.ppo import ppo_loss

    rollout = build_rollout(vec_env, module, T)
    B = vec_env.num_envs
    n = T * B
    if n % minibatch_size:
        raise ValueError(f"T*B={n} must tile into minibatches "
                         f"of {minibatch_size}")
    nmb = n // minibatch_size

    loss_fn = functools.partial(ppo_loss, module=module, clip=clip,
                                vf_coef=vf_coef, ent_coef=ent_coef)

    def sgd_step(params, opt_state, mb):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, mb)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, aux

    def gae(rew, val, done, last_val):
        def step(carry, xs):
            r, v, d, v_next = xs
            delta = r + gamma * (1.0 - d) * v_next - v
            adv = delta + gamma * lam * (1.0 - d) * carry
            return adv, adv
        v_next = jnp.concatenate([val[1:], last_val[None]], axis=0)
        _, advs = jax.lax.scan(step, jnp.zeros_like(last_val),
                               (rew, val, done, v_next), reverse=True)
        return advs, advs + val

    def train_iter(params, opt_state, vs, key):
        vs, key, traj = rollout(params, vs, key)
        adv, ret = gae(traj["rewards"], traj["values"], traj["dones"],
                       traj["last_values"])
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        flat = {
            "obs": traj["obs"].reshape((n,) + traj["obs"].shape[2:]),
            "actions": traj["actions"].reshape((n,)
                                               + traj["actions"].shape[2:]),
            "logp": traj["logp"].reshape(n),
            "advantages": adv.reshape(n),
            "returns": ret.reshape(n),
        }

        def one_minibatch(carry, idx):
            params, opt_state = carry
            mb = jax.tree_util.tree_map(
                lambda x: jnp.take(x, idx, axis=0), flat)
            params, opt_state, loss, aux = sgd_step(params, opt_state, mb)
            return (params, opt_state), (loss, aux)

        def one_epoch(carry, ekey):
            perm = jax.random.permutation(ekey, n).reshape(
                nmb, minibatch_size)
            return jax.lax.scan(one_minibatch, carry, perm)

        key, ekey = jax.random.split(key)
        (params, opt_state), (losses, auxs) = jax.lax.scan(
            one_epoch, (params, opt_state),
            jax.random.split(ekey, num_epochs))
        metrics = {k: v[-1, -1] for k, v in auxs.items()}
        metrics["total_loss"] = losses[-1, -1]
        metrics["ep_ret_sum"] = vs.done_ret_sum
        metrics["ep_len_sum"] = vs.done_len_sum
        metrics["ep_count"] = vs.done_count
        return params, opt_state, vs, key, metrics

    # No donation: freshly-initialized optimizer states can alias
    # identical zero buffers, which XLA rejects as double-donation.
    return jax.jit(train_iter)


def build_impala_train_iter(vec_env: JaxVecEnv, module, *, T: int,
                            minibatch_size: int, gamma: float,
                            rho_bar: float, c_bar: float, vf_coef: float,
                            ent_coef: float, tx):
    """On-device IMPALA (the Anakin/Podracer architecture: DeepMind's
    published TPU formulation of IMPALA — sebulba/anakin, Hessel et al.
    2021): envs live on the accelerator, acting uses a STALE behavior
    policy, and V-trace corrects the off-policyness, all in ONE compiled
    dispatch. The host refreshes behavior params every
    broadcast_interval iterations (same knob as the async actor-learner
    path), so the off-policy gap the reference creates with queue lag is
    created here with deliberate staleness.

    Returns jit(train_iter)(params, behavior_params, opt_state, vs, key)
    -> (params, opt_state, vs, key, metrics)."""
    from ray_tpu.rllib.algorithms.impala import _vtrace_core, impala_loss

    rollout = build_rollout(vec_env, module, T)
    B = vec_env.num_envs
    n = T * B
    if n % minibatch_size:
        raise ValueError(f"T*B={n} must tile into minibatches "
                         f"of {minibatch_size}")
    nmb = n // minibatch_size
    loss_fn = functools.partial(impala_loss, module=module,
                                vf_coef=vf_coef, ent_coef=ent_coef)

    def train_iter(params, behavior_params, opt_state, vs, key):
        # Act with the stale behavior policy; traj["logp"]/["values"]
        # are the BEHAVIOR policy's.
        vs, key, traj = rollout(behavior_params, vs, key)
        obs = traj["obs"]                       # [T, B, ...]
        flat_obs = obs.reshape((n,) + obs.shape[2:])
        # Learner-side forward: target logp + current value estimates.
        logits, values_l = module.forward_train(params, flat_obs)
        logp_all = jax.nn.log_softmax(logits)
        acts = traj["actions"].reshape(n)
        target_logp = jnp.take_along_axis(
            logp_all, acts[:, None].astype(jnp.int32), -1)[:, 0]
        last_vals = traj["last_values"]  # behavior bootstrap (host path
        #                                  uses the same approximation)
        vs_t, pg_adv = _vtrace_core(
            traj["logp"], target_logp.reshape(T, B), traj["rewards"],
            values_l.reshape(T, B), traj["dones"], last_vals,
            gamma=gamma, rho_bar=rho_bar, c_bar=c_bar)
        flat = {
            "obs": flat_obs,
            "actions": acts,
            "vs": vs_t.reshape(n),
            "pg_advantages": pg_adv.reshape(n),
        }

        def one_minibatch(carry, idx):
            params, opt_state = carry
            mb = jax.tree_util.tree_map(
                lambda x: jnp.take(x, idx, axis=0), flat)
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), (
                loss, aux)

        key, pkey = jax.random.split(key)
        perm = jax.random.permutation(pkey, n).reshape(nmb,
                                                       minibatch_size)
        (params, opt_state), (losses, auxs) = jax.lax.scan(
            one_minibatch, (params, opt_state), perm)
        metrics = {k: v[-1] for k, v in auxs.items()}
        metrics["total_loss"] = losses[-1]
        metrics["ep_ret_sum"] = vs.done_ret_sum
        metrics["ep_len_sum"] = vs.done_len_sum
        metrics["ep_count"] = vs.done_count
        return params, opt_state, vs, key, metrics

    return jax.jit(train_iter)


class OnDeviceSamplerGroup:
    """Stands in for EnvRunnerGroup when the env is jax-native: episode
    statistics live on-device (banked by JaxVecEnv.step) and surface
    through the same aggregate_metrics() interface."""

    def __init__(self):
        self._ret_sum = 0.0
        self._len_sum = 0.0
        self._count = 0
        self._window = []  # recent completed-episode means per iter

    def record(self, ret_sum: float, len_sum: float, count: float):
        d_ret = ret_sum - self._ret_sum
        d_len = len_sum - self._len_sum
        d_n = count - self._count
        self._ret_sum, self._len_sum, self._count = ret_sum, len_sum, count
        if d_n > 0:
            self._window.append((d_ret / d_n, d_len / d_n, d_n))
            self._window = self._window[-100:]

    def aggregate_metrics(self) -> dict:
        if not self._window:
            return {"episode_return_mean": float("nan"),
                    "episode_len_mean": float("nan"), "num_episodes": 0}
        rets = [r for r, _, _ in self._window]
        lens = [l for _, l, _ in self._window]
        return {"episode_return_mean": float(sum(rets) / len(rets)),
                "episode_len_mean": float(sum(lens) / len(lens)),
                "num_episodes": int(self._count)}

    def sample(self, *a, **kw):  # pragma: no cover - guard rail
        raise RuntimeError("on-device PPO does not sample via runners")

    def stop(self):
        pass
