"""Learner / LearnerGroup: jit-compiled gradient updates on the accelerator.

Parity: reference `rllib/core/learner/learner.py` + torch-DDP
`core/learner/torch/torch_learner.py` and `learner_group.py:72`.
TPU-native redesign: an update is ONE jit-compiled pure function
(loss+grad+optax apply) — data-parallel scaling is a `jax.sharding` batch
sharding over the learner's device mesh (XLA inserts the psum over ICI),
not a DDP wrapper. Multi-host learner groups are learner *actors* whose
gradients ride the host collective layer (`ray_tpu.util.collective`),
mirroring the reference's NCCL group between learner workers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu


class Learner:
    """Owns params + optimizer state; `update(batch)` is jitted once.

    `loss_fn(params, batch, **cfg)` -> (loss, aux_dict) is supplied by the
    algorithm; the learner is algorithm-agnostic (parity: Learner.update
    driving compute_loss_for_module)."""

    def __init__(self, module, loss_fn, *, lr=3e-4, seed=0,
                 grad_clip: float | None = None, optimizer=None,
                 loss_cfg: dict | None = None, mesh=None, fused=True):
        self.module = module
        self.params = module.init(jax.random.PRNGKey(seed))
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        tx.append(optimizer if optimizer is not None else optax.adam(lr))
        self.tx = optax.chain(*tx)
        self.opt_state = self.tx.init(self.params)
        self.mesh = mesh
        loss_cfg = dict(loss_cfg or {})
        self._loss_fn = loss_fn
        self._loss_cfg = loss_cfg
        self._fused_epochs: dict = {}  # shape signature -> compiled sweep
        if not fused:
            # Subclasses that split grad/allreduce/apply skip the fused jit
            # (it would just hold a dead second copy of the pipeline).
            self._update = None
            self._step_fn = None
            return

        def _step(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, **loss_cfg)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._step_fn = _step  # shared by the fused multi-epoch sweep
        _update = _step

        # params/opt_state are threaded through the step and immediately
        # replaced by the caller, so donate them: without donation XLA
        # holds BOTH generations of every param + both adam moments live
        # across the update (graphcheck donation-missing finding; 3x the
        # steady-state footprint at scale). tx.init here is EAGER, so the
        # moment buffers are real distinct allocations — the zero-buffer
        # double-donation hazard that keeps ondevice.py's fused iter
        # un-donated does not apply.
        if mesh is not None:
            # Batch rides the "dp" mesh axis; params replicated. XLA lowers
            # the mean-gradient to a psum over ICI (scaling-book recipe).
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(mesh, P())
            data = NamedSharding(mesh, P("dp"))
            self._update = jax.jit(
                _update,
                in_shardings=(rep, rep, data),
                out_shardings=(rep, rep, rep, rep),
                donate_argnums=(0, 1))
        else:
            self._update = jax.jit(_update, donate_argnums=(0, 1))

    @staticmethod
    def _finalize_metrics(loss, aux) -> dict:
        # ONE device fetch for every metric — per-scalar float() costs a
        # blocking round trip each (painful on remote/tunneled devices).
        loss, aux = jax.device_get((loss, aux))
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in aux.items()})
        return out

    def update(self, batch: dict) -> dict:
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        self.params, self.opt_state, loss, aux = self._update(
            self.params, self.opt_state, batch)
        return self._finalize_metrics(loss, aux)

    def update_epochs(self, batch: dict, *, num_epochs: int,
                      minibatch_size: int, seed: int = 0) -> dict | None:
        """The whole epochs x shuffled-minibatches sweep as ONE jit call
        (lax.scan over epochs, nested scan over minibatches). One
        dispatch + one metrics fetch per training step instead of one per
        minibatch — the difference between an accelerator-bound and a
        dispatch-latency-bound PPO (SURVEY: no data-dependent Python
        control flow inside the hot loop).

        Returns None (caller falls back to the per-minibatch loop) when
        the sweep can't express the config faithfully: a mesh-sharded
        learner (the fused jit carries no shardings) or a batch that
        doesn't tile into minibatches (scan needs uniform sizes; silently
        dropping the remainder would diverge from the fallback)."""
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        n = next(iter(batch.values())).shape[0]
        if self.mesh is not None or n % minibatch_size:
            return None
        nmb = n // minibatch_size
        mb = minibatch_size
        key_shape = (n, nmb, mb, num_epochs)
        fused = self._fused_epochs.get(key_shape)
        if fused is None:
            fused = self._build_fused_epochs(n, nmb, mb, num_epochs)
            self._fused_epochs[key_shape] = fused
        self.params, self.opt_state, loss, aux = fused(
            self.params, self.opt_state, batch,
            jax.random.PRNGKey(seed))
        return self._finalize_metrics(loss, aux)

    def _build_fused_epochs(self, n, nmb, mb, num_epochs):
        step_fn = self._step_fn

        def one_minibatch(carry, idx):
            params, opt_state, batch = carry
            sl = jax.tree_util.tree_map(lambda x: jnp.take(x, idx, axis=0),
                                        batch)
            params, opt_state, loss, aux = step_fn(params, opt_state, sl)
            return (params, opt_state, batch), (loss, aux)

        def one_epoch(carry, key):
            perm = jax.random.permutation(key, n)[:nmb * mb]
            idxs = perm.reshape(nmb, mb)
            carry, (losses, auxs) = jax.lax.scan(one_minibatch, carry,
                                                 idxs)
            return carry, (losses, auxs)

        def fused(params, opt_state, batch, key):
            keys = jax.random.split(key, num_epochs)
            (params, opt_state, _b), (losses, auxs) = jax.lax.scan(
                one_epoch, (params, opt_state, batch), keys)
            last_aux = jax.tree_util.tree_map(lambda a: a[-1, -1], auxs)
            return params, opt_state, losses[-1, -1], last_aux

        # Same donation rationale as _update (eager tx.init, distinct
        # moment buffers): the sweep threads params/opt_state.
        return jax.jit(fused, donate_argnums=(0, 1))

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params):
        self.params = jax.device_put(params)


class _CollectiveLearner(Learner):
    """Learner actor for multi-learner groups: averages gradients across the
    group with a host-collective allreduce before applying (parity: the DDP
    allreduce between torch learner workers)."""

    def __init__(self, rank: int, world: int, group: str, module, loss_fn,
                 **kw):
        from ray_tpu.util import collective
        self.rank, self.world, self.group = rank, world, group
        collective.init_collective_group(world, rank, group_name=group)
        super().__init__(module, loss_fn, fused=False, **kw)
        # Split update: grads computed jitted, allreduced host-side, applied.
        loss_cfg = dict(kw.get("loss_cfg") or {})
        self._grad_fn = jax.jit(
            lambda p, b: jax.value_and_grad(loss_fn, has_aux=True)(
                p, b, **loss_cfg))
        # params/opt_state threaded and replaced by the caller: donate
        # (same rationale as Learner._update).
        self._apply_fn = jax.jit(
            lambda p, s, g: self._apply(p, s, g), donate_argnums=(0, 1))

    def _apply(self, params, opt_state, grads):
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def update(self, batch: dict) -> dict:
        from ray_tpu.util import collective
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        (loss, aux), grads = self._grad_fn(self.params, batch)
        flat, tree = jax.tree_util.tree_flatten(grads)
        # Use the RETURN value: np views of jax arrays are read-only, so the
        # in-place writeback inside allreduce is skipped for them.
        host = [collective.allreduce(np.asarray(g), group_name=self.group)
                / self.world
                for g in flat]
        grads = jax.tree_util.tree_unflatten(tree, host)
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads)
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in aux.items()})
        return out

    def ping(self):
        return "ok"


class LearnerGroup:
    """num_learners == 0: one in-process learner (default; the mesh gives it
    every local device). num_learners > 0: learner actors + collective
    allreduce (multi-host shape, parity: learner_group.py:72)."""

    def __init__(self, module, loss_fn, *, num_learners: int = 0,
                 config: dict | None = None, mesh=None):
        cfg = dict(config or {})
        if num_learners == 0:
            self.local = Learner(module, loss_fn, mesh=mesh, **cfg)
            self.remotes = []
        else:
            self.local = None
            group = f"learners-{id(self)}"
            cls = ray_tpu.remote(num_cpus=1)(_CollectiveLearner)
            self.remotes = [
                cls.remote(i, num_learners, group, module, loss_fn, **cfg)
                for i in range(num_learners)]
            ray_tpu.get([r.ping.remote() for r in self.remotes], timeout=120)

    def update_epochs(self, batch: dict, *, num_epochs: int,
                      minibatch_size: int, seed: int = 0) -> dict | None:
        """Fused multi-epoch sweep on the local learner (one accelerator
        dispatch); None for actor groups — callers fall back to the
        per-minibatch loop there."""
        if self.local is not None:
            return self.local.update_epochs(
                batch, num_epochs=num_epochs,
                minibatch_size=minibatch_size, seed=seed)
        return None

    def update(self, batch: dict) -> dict:
        if self.local is not None:
            return self.local.update(batch)
        n = len(self.remotes)
        B = next(iter(batch.values())).shape[0]
        if B < n:
            # Every learner must participate in the allreduce; an empty
            # shard would feed NaN gradients into the whole group.
            raise ValueError(
                f"batch of {B} rows cannot be sharded across {n} learners")
        bounds = np.linspace(0, B, n + 1, dtype=int)
        refs = []
        for i, r in enumerate(self.remotes):
            sl = {k: v[bounds[i]:bounds[i + 1]] for k, v in batch.items()}
            refs.append(r.update.remote(sl))
        results = ray_tpu.get(refs, timeout=300)
        return {k: float(np.mean([m[k] for m in results]))
                for k in results[0]}

    def get_weights(self):
        if self.local is not None:
            return self.local.get_weights()
        return ray_tpu.get(self.remotes[0].get_weights.remote(), timeout=120)

    def stop(self):
        for r in self.remotes:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass


def __graphcheck__(gc):
    """graphcheck hook (tools/graphcheck): the PPO learner update through
    the REAL Learner jit (donation included), at a tiny module. Pins:
    params + adam moments donated (the graphcheck finding that motivated
    donate_argnums above), no host callbacks in the update, and the
    flops/bytes fingerprint of loss+grad+apply."""

    def build(mesh):
        import functools  # noqa: F401 — loss_cfg carries the statics
        from ray_tpu.rllib.algorithms.ppo import ppo_loss
        from ray_tpu.rllib.core.rl_module import ActorCriticModule

        module = ActorCriticModule(obs_dim=8, num_actions=4)
        lr = Learner(module, ppo_loss,
                     loss_cfg=dict(module=module, clip=0.2, vf_coef=0.5,
                                   ent_coef=0.01))
        n = 64
        batch = {
            "obs": jax.ShapeDtypeStruct((n, 8), jnp.float32),
            "actions": jax.ShapeDtypeStruct((n,), jnp.int32),
            "logp": jax.ShapeDtypeStruct((n,), jnp.float32),
            "advantages": jax.ShapeDtypeStruct((n,), jnp.float32),
            "returns": jax.ShapeDtypeStruct((n,), jnp.float32),
        }
        params = jax.eval_shape(module.init, jax.random.PRNGKey(0))
        opt_state = jax.eval_shape(lr.tx.init, params)
        return gc.GraphSpec(
            name="rl.ppo_learner", fn=lr._step_fn,
            args=(params, opt_state, batch), jit_fn=lr._update,
            donate_argnums=(0, 1), min_donate_bytes=8192,
            arg_names=("params", "opt_state", "batch"))

    gc.register("rl.ppo_learner", build)
