"""Real-ALE Atari support (when ale-py is installed).

Parity: the reference's Atari benchmark path (rllib tuned examples wrap
ALE envs with the deepmind preprocessing stack). ale-py is not in this
image, so this module is a gated integration point: `register_atari`
registers a preprocessed, frame-stacked variant of an ALE env under a
stable id the env runners can `gym.make_vec`. The MinAtar-style suite
(`minatar.py`) is the always-available stand-in at test scale.
"""

from __future__ import annotations


def ale_available() -> bool:
    try:
        import ale_py  # noqa: F401
        return True
    except ImportError:
        return False


def register_atari(game: str = "Breakout", *, frame_stack: int = 4,
                   screen_size: int = 84) -> str:
    """Register `<game>NoFrameskip-v4` wrapped in the deepmind stack
    (grayscale, resize, frame-skip 4, max-pool, stacked frames — via
    gymnasium's AtariPreprocessing + FrameStackObservation) and return the
    registered id. Raises with a clear message when ale-py is missing."""
    if not ale_available():
        raise RuntimeError(
            "Atari environments need ale-py (pip install "
            "'gymnasium[atari]'); at test scale use the built-in "
            "MinAtarBreakout-v0 / MinAtarSpaceInvaders-v0 instead")
    import ale_py
    import gymnasium as gym
    gym.register_envs(ale_py)
    env_id = f"{game}Deepmind-v0"
    if env_id in gym.registry:
        return env_id

    def make(render_mode=None, **kw):
        import numpy as np
        from gymnasium.wrappers import (
            AtariPreprocessing,
            FrameStackObservation,
            TransformObservation,
        )
        env = gym.make(f"{game}NoFrameskip-v4", render_mode=render_mode,
                       **kw)
        env = AtariPreprocessing(env, screen_size=screen_size,
                                 grayscale_obs=True, scale_obs=True)
        env = FrameStackObservation(env, stack_size=frame_stack)
        # [stack, H, W] -> [H, W, stack]: channel-last for the conv module.
        space = gym.spaces.Box(0.0, 1.0,
                               (screen_size, screen_size, frame_stack),
                               np.float32)
        return TransformObservation(
            env, lambda obs: np.moveaxis(obs, 0, -1).astype(np.float32),
            observation_space=space)

    gym.register(id=env_id, entry_point=make)
    return env_id
