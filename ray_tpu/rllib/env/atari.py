"""Atari-class envs: real ALE when ale-py exists, plus a self-contained
ALE-COMPATIBLE fallback that needs no ROMs.

Parity: the reference's Atari benchmark path (rllib tuned examples wrap
ALE envs with the deepmind preprocessing stack). ale-py is not in this
image, so two paths:

- `register_atari`: the real thing when ale-py is importable.
- `register_atari_class` (always available): `AtariClass<Game>-v0` wraps
  each built-in MinAtar game and renders its state into the deepmind
  observation contract — 84x84x4 float32 frame stacks — so policy
  networks, learner compute, and rollout bandwidth match the ALE
  benchmark shape exactly while the dynamics stay ROM-free. This is the
  path the TPU RL benchmarks run (BASELINE north star: "RLlib PPO-Atari
  matching torch-GPU throughput").
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover - gymnasium is baked in
    gym = None
    spaces = None

_EnvBase = gym.Env if gym is not None else object


class AtariClassEnv(_EnvBase):
    """Deepmind-preprocessed view of a MinAtar core: the 10x10xC state
    renders into an 84x84 grayscale frame (8x nearest-neighbour upscale,
    channels weighted into intensities), stacked over the last 4 frames
    -> obs [84, 84, 4] float32 in [0, 1]."""

    metadata = {"render_modes": []}
    SCREEN = 84

    def __init__(self, core_cls, render_mode=None, **kw):
        self.core = core_cls(**kw)
        s = self.SCREEN
        self.observation_space = spaces.Box(0.0, 1.0, (s, s, 4),
                                            np.float32)
        self.action_space = self.core.action_space
        self._frames = np.zeros((s, s, 4), np.float32)

    def _render(self, obs10) -> np.ndarray:
        # channel weights spread entity types across gray levels
        weights = np.linspace(1.0, 0.4, obs10.shape[-1],
                              dtype=np.float32)
        gray = np.max(obs10 * weights, axis=-1)   # [10, 10]
        up = np.kron(gray, np.ones((8, 8), np.float32))  # [80, 80]
        frame = np.zeros((self.SCREEN, self.SCREEN), np.float32)
        frame[2:82, 2:82] = up
        return frame

    def reset(self, *, seed=None, options=None):
        obs, info = self.core.reset(seed=seed, options=options)
        frame = self._render(obs)
        self._frames = np.repeat(frame[:, :, None], 4, axis=2)
        return self._frames.copy(), info

    def step(self, action):
        obs, rew, term, trunc, info = self.core.step(action)
        self._frames = np.concatenate(
            [self._frames[:, :, 1:], self._render(obs)[:, :, None]],
            axis=2)
        return self._frames.copy(), rew, term, trunc, info


_CLASS_REGISTERED = False


def register_atari_class():
    """Register AtariClass{Breakout,SpaceInvaders,Asterix,Freeway,
    Seaquest}-v0 (idempotent)."""
    global _CLASS_REGISTERED
    if _CLASS_REGISTERED or gym is None:
        return
    _CLASS_REGISTERED = True
    from ray_tpu.rllib.env import minatar as m
    for game, cls in (("Breakout", m.MinAtarBreakout),
                      ("SpaceInvaders", m.MinAtarSpaceInvaders),
                      ("Asterix", m.MinAtarAsterix),
                      ("Freeway", m.MinAtarFreeway),
                      ("Seaquest", m.MinAtarSeaquest)):
        env_id = f"AtariClass{game}-v0"
        if env_id not in gym.registry:
            gym.register(
                id=env_id,
                entry_point=("ray_tpu.rllib.env.atari:AtariClassEnv"),
                kwargs={"core_cls": cls})


def ale_available() -> bool:
    try:
        import ale_py  # noqa: F401
        return True
    except ImportError:
        return False


def register_atari(game: str = "Breakout", *, frame_stack: int = 4,
                   screen_size: int = 84) -> str:
    """Register `<game>NoFrameskip-v4` wrapped in the deepmind stack
    (grayscale, resize, frame-skip 4, max-pool, stacked frames — via
    gymnasium's AtariPreprocessing + FrameStackObservation) and return the
    registered id. Raises with a clear message when ale-py is missing."""
    if not ale_available():
        raise RuntimeError(
            "Atari environments need ale-py (pip install "
            "'gymnasium[atari]'); at test scale use the built-in "
            "MinAtarBreakout-v0 / MinAtarSpaceInvaders-v0 instead")
    import ale_py
    import gymnasium as gym
    gym.register_envs(ale_py)
    env_id = f"{game}Deepmind-v0"
    if env_id in gym.registry:
        return env_id

    def make(render_mode=None, **kw):
        import numpy as np
        from gymnasium.wrappers import (
            AtariPreprocessing,
            FrameStackObservation,
            TransformObservation,
        )
        env = gym.make(f"{game}NoFrameskip-v4", render_mode=render_mode,
                       **kw)
        env = AtariPreprocessing(env, screen_size=screen_size,
                                 grayscale_obs=True, scale_obs=True)
        env = FrameStackObservation(env, stack_size=frame_stack)
        # [stack, H, W] -> [H, W, stack]: channel-last for the conv module.
        space = gym.spaces.Box(0.0, 1.0,
                               (screen_size, screen_size, frame_stack),
                               np.float32)
        return TransformObservation(
            env, lambda obs: np.moveaxis(obs, 0, -1).astype(np.float32),
            observation_space=space)

    gym.register(id=env_id, entry_point=make)
    return env_id
