"""Native MinAtar-style arcade environments (Atari-class env path).

Parity/role: the reference's RLlib benchmarks lean on ALE Atari
(gymnasium[atari] + ale-py), which is not installable here. These are
from-scratch 10x10 multi-channel reimplementations in the spirit of the
MinAtar suite (Young & Tian 2019): binary-channel grids, the same action
semantics, episodic reward — small enough to step fast on CPU env runners
while exercising the conv-module path end to end
(`rl_module.CNNActorCriticModule`). For real ALE frames see
`ray_tpu/rllib/env/atari.py`.

Registered gymnasium ids (via `register_builtin_envs()`):
  MinAtarBreakout-v0, MinAtarSpaceInvaders-v0
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover - gymnasium is baked into the image
    gym = None


class MinAtarBreakout(gym.Env):
    """10x10 Breakout: paddle row at the bottom, three brick rows at the
    top, a diagonally bouncing ball. Channels: 0=paddle, 1=ball, 2=trail,
    3=brick. Actions: 0=noop, 1=left, 2=right. Reward 1 per brick; the
    wall regenerates when cleared; episode ends when the ball drops."""

    metadata = {"render_modes": []}
    SIZE = 10

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(3)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.SIZE
        self.paddle = n // 2
        self.bricks = np.zeros((n, n), np.bool_)
        self.bricks[1:4, :] = True
        self.ball_y = 3
        self.ball_x = int(self._rng.integers(0, n))
        self.dy = 1
        self.dx = 1 if self._rng.random() < 0.5 else -1
        self.last_y, self.last_x = self.ball_y, self.ball_x
        self.steps = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[n - 1, self.paddle, 0] = 1.0
        o[self.ball_y, self.ball_x, 1] = 1.0
        o[self.last_y, self.last_x, 2] = 1.0
        o[:, :, 3] = self.bricks
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        if action == 1:
            self.paddle = max(0, self.paddle - 1)
        elif action == 2:
            self.paddle = min(n - 1, self.paddle + 1)
        self.last_y, self.last_x = self.ball_y, self.ball_x
        ny, nx = self.ball_y + self.dy, self.ball_x + self.dx
        reward = 0.0
        terminated = False
        if nx < 0 or nx >= n:  # side wall
            self.dx = -self.dx
            nx = self.ball_x + self.dx
        if ny < 0:  # ceiling
            self.dy = 1
            ny = self.ball_y + self.dy
        if 0 <= ny < n and self.bricks[ny, nx]:
            self.bricks[ny, nx] = False
            reward = 1.0
            self.dy = -self.dy
            ny = self.ball_y + self.dy
            if not self.bricks.any():  # wall cleared: regenerate
                self.bricks[1:4, :] = True
        if ny == n - 1:  # paddle row
            if nx == self.paddle:
                self.dy = -1
                ny = self.ball_y + self.dy
                # English: moving into the paddle edge mirrors dx.
                if action == 1:
                    self.dx = -1
                elif action == 2:
                    self.dx = 1
            else:
                terminated = True
        self.ball_y = int(np.clip(ny, 0, n - 1))
        self.ball_x = int(np.clip(nx, 0, n - 1))
        truncated = self.steps >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


class MinAtarSpaceInvaders(gym.Env):
    """10x10 Space Invaders: a 4x6 alien block marching side-to-side and
    down, a cannon on the bottom row. Channels: 0=cannon, 1=alien,
    2=alien bullet, 3=friendly bullet. Actions: 0=noop, 1=left, 2=right,
    3=fire. Reward 1 per alien; new wave on clear; episode ends when a
    bullet hits the cannon or aliens reach the bottom row."""

    metadata = {"render_modes": []}
    SIZE = 10

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(4)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def _spawn_wave(self):
        self.aliens = np.zeros((self.SIZE, self.SIZE), np.bool_)
        self.aliens[1:5, 2:8] = True
        self.adx = 1
        self.move_timer = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.cannon = self.SIZE // 2
        self._spawn_wave()
        self.enemy_shots: list[list[int]] = []  # [y, x]
        self.my_shot = None  # [y, x] — one in flight at a time
        self.steps = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[n - 1, self.cannon, 0] = 1.0
        o[:, :, 1] = self.aliens
        for y, x in self.enemy_shots:
            o[y, x, 2] = 1.0
        if self.my_shot is not None:
            o[self.my_shot[0], self.my_shot[1], 3] = 1.0
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        reward = 0.0
        terminated = False
        if action == 1:
            self.cannon = max(0, self.cannon - 1)
        elif action == 2:
            self.cannon = min(n - 1, self.cannon + 1)
        elif action == 3 and self.my_shot is None:
            self.my_shot = [n - 2, self.cannon]

        # Friendly bullet rises; hit removes an alien.
        if self.my_shot is not None:
            self.my_shot[0] -= 1
            y, x = self.my_shot
            if y < 0:
                self.my_shot = None
            elif self.aliens[y, x]:
                self.aliens[y, x] = False
                reward = 1.0
                self.my_shot = None
                if not self.aliens.any():
                    self._spawn_wave()

        # Alien block marches every other step; edge -> drop a row.
        self.move_timer += 1
        if self.move_timer % 2 == 0 and self.aliens.any():
            cols = np.flatnonzero(self.aliens.any(axis=0))
            if (self.adx > 0 and cols[-1] == n - 1) or \
               (self.adx < 0 and cols[0] == 0):
                self.aliens = np.roll(self.aliens, 1, axis=0)
                self.adx = -self.adx
                if self.aliens[n - 1].any():
                    terminated = True  # invasion
            else:
                self.aliens = np.roll(self.aliens, self.adx, axis=1)

        # Random alien fire from a bottom-most alien.
        if self.aliens.any() and self._rng.random() < 0.2:
            col = int(self._rng.choice(np.flatnonzero(
                self.aliens.any(axis=0))))
            row = int(np.flatnonzero(self.aliens[:, col])[-1])
            self.enemy_shots.append([row + 1, col])

        nxt = []
        for y, x in self.enemy_shots:
            y += 1
            if y == n - 1 and x == self.cannon:
                terminated = True
            elif y < n:
                nxt.append([y, x])
        self.enemy_shots = nxt

        truncated = self.steps >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


_REGISTERED = False


def register_builtin_envs():
    """Idempotently register the built-in envs with gymnasium (called by
    the env runner in every actor process before gym.make_vec)."""
    global _REGISTERED
    if _REGISTERED or gym is None:
        return
    _REGISTERED = True
    for name, ep in (
            ("MinAtarBreakout-v0",
             "ray_tpu.rllib.env.minatar:MinAtarBreakout"),
            ("MinAtarSpaceInvaders-v0",
             "ray_tpu.rllib.env.minatar:MinAtarSpaceInvaders")):
        if name not in gym.registry:
            gym.register(id=name, entry_point=ep)
