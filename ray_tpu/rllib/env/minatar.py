"""Native MinAtar-style arcade environments (Atari-class env path).

Parity/role: the reference's RLlib benchmarks lean on ALE Atari
(gymnasium[atari] + ale-py), which is not installable here. These are
from-scratch 10x10 multi-channel reimplementations in the spirit of the
MinAtar suite (Young & Tian 2019): binary-channel grids, the same action
semantics, episodic reward — small enough to step fast on CPU env runners
while exercising the conv-module path end to end
(`rl_module.CNNActorCriticModule`). For real ALE frames see
`ray_tpu/rllib/env/atari.py`.

Registered gymnasium ids (via `register_builtin_envs()`):
  MinAtarBreakout-v0, MinAtarSpaceInvaders-v0
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover - gymnasium is baked into the image
    gym = None
    spaces = None

_EnvBase = gym.Env if gym is not None else object


class MinAtarBreakout(_EnvBase):
    """10x10 Breakout: paddle row at the bottom, three brick rows at the
    top, a diagonally bouncing ball. Channels: 0=paddle, 1=ball, 2=trail,
    3=brick. Actions: 0=noop, 1=left, 2=right. Reward 1 per brick; the
    wall regenerates when cleared; episode ends when the ball drops."""

    metadata = {"render_modes": []}
    SIZE = 10

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(3)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.SIZE
        self.paddle = n // 2
        self.bricks = np.zeros((n, n), np.bool_)
        self.bricks[1:4, :] = True
        self.ball_y = 3
        self.ball_x = int(self._rng.integers(0, n))
        self.dy = 1
        self.dx = 1 if self._rng.random() < 0.5 else -1
        self.last_y, self.last_x = self.ball_y, self.ball_x
        self.steps = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[n - 1, self.paddle, 0] = 1.0
        o[self.ball_y, self.ball_x, 1] = 1.0
        o[self.last_y, self.last_x, 2] = 1.0
        o[:, :, 3] = self.bricks
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        if action == 1:
            self.paddle = max(0, self.paddle - 1)
        elif action == 2:
            self.paddle = min(n - 1, self.paddle + 1)
        self.last_y, self.last_x = self.ball_y, self.ball_x
        ny, nx = self.ball_y + self.dy, self.ball_x + self.dx
        reward = 0.0
        terminated = False
        if nx < 0 or nx >= n:  # side wall
            self.dx = -self.dx
            nx = self.ball_x + self.dx
        if ny < 0:  # ceiling
            self.dy = 1
            ny = self.ball_y + self.dy
        if 0 <= ny < n and self.bricks[ny, nx]:
            self.bricks[ny, nx] = False
            reward = 1.0
            self.dy = -self.dy
            ny = self.ball_y + self.dy
            if not self.bricks.any():  # wall cleared: regenerate
                self.bricks[1:4, :] = True
        if ny == n - 1:  # paddle row
            if nx == self.paddle:
                self.dy = -1
                ny = self.ball_y + self.dy
                # English: moving into the paddle edge mirrors dx.
                if action == 1:
                    self.dx = -1
                elif action == 2:
                    self.dx = 1
            else:
                terminated = True
        self.ball_y = int(np.clip(ny, 0, n - 1))
        self.ball_x = int(np.clip(nx, 0, n - 1))
        truncated = self.steps >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


class MinAtarSpaceInvaders(_EnvBase):
    """10x10 Space Invaders: a 4x6 alien block marching side-to-side and
    down, a cannon on the bottom row. Channels: 0=cannon, 1=alien,
    2=alien bullet, 3=friendly bullet. Actions: 0=noop, 1=left, 2=right,
    3=fire. Reward 1 per alien; new wave on clear; episode ends when a
    bullet hits the cannon or aliens reach the bottom row."""

    metadata = {"render_modes": []}
    SIZE = 10

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(4)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def _spawn_wave(self):
        self.aliens = np.zeros((self.SIZE, self.SIZE), np.bool_)
        self.aliens[1:5, 2:8] = True
        self.adx = 1
        self.move_timer = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.cannon = self.SIZE // 2
        self._spawn_wave()
        self.enemy_shots: list[list[int]] = []  # [y, x]
        self.my_shot = None  # [y, x] — one in flight at a time
        self.steps = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[n - 1, self.cannon, 0] = 1.0
        o[:, :, 1] = self.aliens
        for y, x in self.enemy_shots:
            o[y, x, 2] = 1.0
        if self.my_shot is not None:
            o[self.my_shot[0], self.my_shot[1], 3] = 1.0
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        reward = 0.0
        terminated = False
        if action == 1:
            self.cannon = max(0, self.cannon - 1)
        elif action == 2:
            self.cannon = min(n - 1, self.cannon + 1)
        elif action == 3 and self.my_shot is None:
            self.my_shot = [n - 2, self.cannon]

        # Friendly bullet rises; hit removes an alien.
        if self.my_shot is not None:
            self.my_shot[0] -= 1
            y, x = self.my_shot
            if y < 0:
                self.my_shot = None
            elif self.aliens[y, x]:
                self.aliens[y, x] = False
                reward = 1.0
                self.my_shot = None
                if not self.aliens.any():
                    self._spawn_wave()

        # Alien block marches every other step; edge -> drop a row.
        self.move_timer += 1
        if self.move_timer % 2 == 0 and self.aliens.any():
            cols = np.flatnonzero(self.aliens.any(axis=0))
            if (self.adx > 0 and cols[-1] == n - 1) or \
               (self.adx < 0 and cols[0] == 0):
                self.aliens = np.roll(self.aliens, 1, axis=0)
                self.adx = -self.adx
                if self.aliens[n - 1].any():
                    terminated = True  # invasion
            else:
                self.aliens = np.roll(self.aliens, self.adx, axis=1)

        # Random alien fire from a bottom-most alien.
        if self.aliens.any() and self._rng.random() < 0.2:
            col = int(self._rng.choice(np.flatnonzero(
                self.aliens.any(axis=0))))
            row = int(np.flatnonzero(self.aliens[:, col])[-1])
            self.enemy_shots.append([row + 1, col])

        nxt = []
        for y, x in self.enemy_shots:
            y += 1
            if y == n - 1 and x == self.cannon:
                terminated = True
            elif y < n:
                nxt.append([y, x])
        self.enemy_shots = nxt

        truncated = self.steps >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


class MinAtarAsterix(_EnvBase):
    """10x10 Asterix: the hero moves in four directions; enemies and
    treasure slide horizontally across rows 1..8, spawning at a fixed
    cadence. Channels: 0=hero, 1=treasure, 2=enemy, 3=motion trail.
    Actions: 0=noop, 1=left, 2=right, 3=up, 4=down. Reward 1 per
    treasure; touching an enemy ends the episode."""

    metadata = {"render_modes": []}
    SIZE = 10

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(5)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.SIZE
        self.hero = [n // 2, n // 2]
        self.entities: list[list] = []  # [y, x, dx, is_gold]
        self.steps = 0
        self.spawn_timer = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[self.hero[0], self.hero[1], 0] = 1.0
        for y, x, dx, gold in self.entities:
            o[y, x, 1 if gold else 2] = 1.0
            tx = x - dx
            if 0 <= tx < n:
                o[y, tx, 3] = 1.0
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        dy, dx = [(0, 0), (0, -1), (0, 1), (-1, 0), (1, 0)][int(action)]
        self.hero[0] = int(np.clip(self.hero[0] + dy, 1, n - 2))
        self.hero[1] = int(np.clip(self.hero[1] + dx, 0, n - 1))
        reward = 0.0
        terminated = False
        self.spawn_timer += 1
        if self.spawn_timer >= 3 and len(self.entities) < 8:
            self.spawn_timer = 0
            row = int(self._rng.integers(1, n - 1))
            if not any(e[0] == row for e in self.entities):
                going_right = bool(self._rng.random() < 0.5)
                self.entities.append(
                    [row, 0 if going_right else n - 1,
                     1 if going_right else -1,
                     bool(self._rng.random() < 1 / 3)])
        nxt = []
        for y, x, edx, gold in self.entities:
            x += edx
            if x < 0 or x >= n:
                continue  # slid off
            if [y, x] == self.hero:
                if gold:
                    reward += 1.0
                    continue
                terminated = True
            nxt.append([y, x, edx, gold])
        self.entities = nxt
        truncated = self.steps >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


class MinAtarFreeway(_EnvBase):
    """10x10 Freeway: the chicken climbs from the bottom row to the top
    across 8 traffic lanes; cars wrap around at lane-specific speeds and
    directions. Channels: 0=chicken, 1=car, 2=fast-car marker,
    3=direction marker. Actions: 0=noop, 1=up, 2=down. Reward 1 per
    crossing (chicken restarts at the bottom); a collision knocks it
    back to the start. Episodes are time-limited only."""

    metadata = {"render_modes": []}
    SIZE = 10

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(3)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.SIZE
        self.chicken = n - 1
        # lanes 1..8: (x, dir, period); speed = move every `period` steps
        self.cars = []
        for lane in range(1, n - 1):
            direction = 1 if lane % 2 else -1
            period = int(self._rng.integers(1, 4))
            self.cars.append([int(self._rng.integers(0, n)), direction,
                              period])
        self.steps = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[self.chicken, n // 2, 0] = 1.0
        for lane, (x, d, period) in enumerate(self.cars, start=1):
            o[lane, x, 1] = 1.0
            if period == 1:
                o[lane, x, 2] = 1.0
            if d > 0:
                o[lane, x, 3] = 1.0
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        if action == 1:
            self.chicken = max(0, self.chicken - 1)
        elif action == 2:
            self.chicken = min(n - 1, self.chicken + 1)
        for car in self.cars:
            if self.steps % car[2] == 0:
                car[0] = (car[0] + car[1]) % n
        reward = 0.0
        if self.chicken == 0:
            reward = 1.0
            self.chicken = n - 1
        elif 1 <= self.chicken <= n - 2:
            car = self.cars[self.chicken - 1]
            if car[0] == n // 2:  # chicken column is fixed at center
                self.chicken = n - 1
        truncated = self.steps >= self.max_steps
        return self._obs(), reward, False, truncated, {}


class MinAtarSeaquest(_EnvBase):
    """10x10 Seaquest: a submarine with an oxygen budget hunts fish with
    torpedoes and must surface (row 0) to refill. Channels: 0=sub,
    1=fish, 2=torpedo, 3=oxygen gauge (bottom row fill). Actions:
    0=noop, 1=left, 2=right, 3=up, 4=down, 5=fire. Reward 1 per fish;
    running out of oxygen or touching a fish ends the episode."""

    metadata = {"render_modes": []}
    SIZE = 10
    MAX_O2 = 60

    def __init__(self, render_mode=None, max_steps: int = 1000):
        n = self.SIZE
        self.observation_space = spaces.Box(0.0, 1.0, (n, n, 4),
                                            np.float32)
        self.action_space = spaces.Discrete(6)
        self.max_steps = max_steps
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.SIZE
        self.sub = [n // 2, n // 2]
        self.fish: list[list] = []       # [y, x, dx]
        self.torps: list[list] = []      # [y, x, dx]
        self.o2 = self.MAX_O2
        self.facing = 1
        self.steps = 0
        return self._obs(), {}

    def _obs(self):
        n = self.SIZE
        o = np.zeros((n, n, 4), np.float32)
        o[self.sub[0], self.sub[1], 0] = 1.0
        for y, x, _d in self.fish:
            o[y, x, 1] = 1.0
        for y, x, _d in self.torps:
            o[y, x, 2] = 1.0
        fill = int(round(self.o2 / self.MAX_O2 * (n - 1)))
        o[n - 1, :fill + 1, 3] = 1.0
        return o

    def step(self, action):
        n = self.SIZE
        self.steps += 1
        reward = 0.0
        terminated = False
        a = int(action)
        if a == 1:
            self.sub[1] = max(0, self.sub[1] - 1)
            self.facing = -1
        elif a == 2:
            self.sub[1] = min(n - 1, self.sub[1] + 1)
            self.facing = 1
        elif a == 3:
            self.sub[0] = max(0, self.sub[0] - 1)
        elif a == 4:
            self.sub[0] = min(n - 2, self.sub[0] + 1)  # row n-1 = gauge
        elif a == 5 and len(self.torps) < 3:
            self.torps.append([self.sub[0], self.sub[1], self.facing])
        # oxygen: refill on the surface row, deplete below it
        if self.sub[0] == 0:
            self.o2 = self.MAX_O2
        else:
            self.o2 -= 1
            if self.o2 <= 0:
                terminated = True
        if self.steps % 4 == 0 and len(self.fish) < 6:
            row = int(self._rng.integers(1, n - 2))
            going_right = bool(self._rng.random() < 0.5)
            self.fish.append([row, 0 if going_right else n - 1,
                              1 if going_right else -1])
        nxt_t = []
        for y, x, d in self.torps:
            x += d
            if not 0 <= x < n:
                continue
            hit = [f for f in self.fish if f[0] == y and f[1] == x]
            if hit:
                self.fish = [f for f in self.fish if f not in hit]
                reward += float(len(hit))
                continue
            nxt_t.append([y, x, d])
        self.torps = nxt_t
        nxt_f = []
        for y, x, d in self.fish:
            if self.steps % 2 == 0:
                x += d
            if not 0 <= x < n:
                continue
            if [y, x] == self.sub:
                terminated = True
            hit = [t for t in self.torps if t[0] == y and t[1] == x]
            if hit:
                self.torps = [t for t in self.torps if t not in hit]
                reward += 1.0
                continue
            nxt_f.append([y, x, d])
        self.fish = nxt_f
        truncated = self.steps >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


_REGISTERED = False

MINATAR_SUITE = ("MinAtarBreakout-v0", "MinAtarSpaceInvaders-v0",
                 "MinAtarAsterix-v0", "MinAtarFreeway-v0",
                 "MinAtarSeaquest-v0")


def register_builtin_envs():
    """Idempotently register the built-in envs with gymnasium (called by
    the env runner in every actor process before gym.make_vec)."""
    global _REGISTERED
    if _REGISTERED or gym is None:
        return
    _REGISTERED = True
    for name, ep in (
            ("MinAtarBreakout-v0",
             "ray_tpu.rllib.env.minatar:MinAtarBreakout"),
            ("MinAtarSpaceInvaders-v0",
             "ray_tpu.rllib.env.minatar:MinAtarSpaceInvaders"),
            ("MinAtarAsterix-v0",
             "ray_tpu.rllib.env.minatar:MinAtarAsterix"),
            ("MinAtarFreeway-v0",
             "ray_tpu.rllib.env.minatar:MinAtarFreeway"),
            ("MinAtarSeaquest-v0",
             "ray_tpu.rllib.env.minatar:MinAtarSeaquest")):
        if name not in gym.registry:
            gym.register(id=name, entry_point=ep)
    from ray_tpu.rllib.env.atari import register_atari_class
    register_atari_class()
