"""On-device vectorized environments: MinAtar-class dynamics as pure jax.

Parity target: the reference's PPO-Atari benchmark path
(`rllib/algorithms/ppo/ppo.py:388` sampling + learner update, torch-GPU).
TPU-native redesign rather than translation: instead of stepping numpy
envs on the host and shipping [T, B, 84, 84, 4] observation tensors to
the accelerator every iteration (round 4's path — host env stepping plus
a CPU policy forward per step capped PPO at ~300 env-steps/s, and the
obs upload dominated `learner_update_ms`), the env dynamics themselves
are pure jax functions batched with `vmap` and rolled out under one
`lax.scan` — policy forward, env step, frame rendering, GAE, and the
minibatch-epoch update all execute in a single compiled program on the
TPU. Observations never cross the host boundary. This is the public
gymnax/Brax pattern (see PAPERS.md) applied to the MinAtar/AtariClass
games this repo already ships in numpy form (`env/minatar.py`,
`env/atari.py` — those remain the gym-compatible path and the score-gate
reference).

Env API (functional, single-env; the wrapper vmaps):
  reset1(key) -> state
  step1(state, action, key) -> (state, reward, terminated)
  obs1(state) -> observation
Auto-reset: `JaxVecEnv.step` resets finished episodes in-place (standard
for on-device rollouts) and accumulates episode-return statistics on
device.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_I = jnp.int32
_F = jnp.float32


class BreakoutState(NamedTuple):
    paddle: jnp.ndarray   # [] int32
    by: jnp.ndarray       # ball y
    bx: jnp.ndarray       # ball x
    dy: jnp.ndarray
    dx: jnp.ndarray
    ly: jnp.ndarray       # trail (last ball position)
    lx: jnp.ndarray
    bricks: jnp.ndarray   # [10, 10] bool
    steps: jnp.ndarray    # [] int32


class JaxBreakout:
    """MinAtar Breakout (env/minatar.py:30) as pure jax: paddle row at the
    bottom, three brick rows, diagonally bouncing ball; reward 1 per
    brick; wall regenerates when cleared; episode ends when the ball
    drops. Channels: 0=paddle, 1=ball, 2=trail, 3=brick."""

    SIZE = 10
    num_actions = 3
    obs_shape = (10, 10, 4)
    max_steps = 1000

    def reset1(self, key) -> BreakoutState:
        n = self.SIZE
        kx, kd = jax.random.split(key)
        bricks = jnp.zeros((n, n), bool).at[1:4, :].set(True)
        bx = jax.random.randint(kx, (), 0, n)
        dx = jnp.where(jax.random.uniform(kd) < 0.5, 1, -1).astype(_I)
        return BreakoutState(
            paddle=jnp.asarray(n // 2, _I), by=jnp.asarray(3, _I),
            bx=bx.astype(_I), dy=jnp.asarray(1, _I), dx=dx,
            ly=jnp.asarray(3, _I), lx=bx.astype(_I), bricks=bricks,
            steps=jnp.asarray(0, _I))

    def obs1(self, s: BreakoutState):
        n = self.SIZE
        o = jnp.zeros((n, n, 4), _F)
        o = o.at[n - 1, s.paddle, 0].set(1.0)
        o = o.at[s.by, s.bx, 1].set(1.0)
        o = o.at[s.ly, s.lx, 2].set(1.0)
        o = o.at[:, :, 3].set(s.bricks.astype(_F))
        return o

    def step1(self, s: BreakoutState, action, key):
        """Mirrors the numpy step's where-chain order exactly (side wall,
        ceiling, brick bounce + wall regen, paddle/english, drop)."""
        n = self.SIZE
        action = action.astype(_I)
        paddle = jnp.clip(
            s.paddle + (action == 2).astype(_I) - (action == 1).astype(_I),
            0, n - 1)
        ly, lx = s.by, s.bx
        dy, dx = s.dy, s.dx
        ny, nx = s.by + dy, s.bx + dx
        # side walls
        hit_side = (nx < 0) | (nx >= n)
        dx = jnp.where(hit_side, -dx, dx)
        nx = jnp.where(hit_side, s.bx + dx, nx)
        # ceiling
        hit_ceil = ny < 0
        dy = jnp.where(hit_ceil, 1, dy)
        ny = jnp.where(hit_ceil, s.by + dy, ny)
        # brick
        cy, cx = jnp.clip(ny, 0, n - 1), jnp.clip(nx, 0, n - 1)
        brick_hit = (ny >= 0) & (ny < n) & s.bricks[cy, cx]
        reward = brick_hit.astype(_F)
        bricks = s.bricks.at[cy, cx].set(
            jnp.where(brick_hit, False, s.bricks[cy, cx]))
        dy = jnp.where(brick_hit, -dy, dy)
        ny = jnp.where(brick_hit, s.by + dy, ny)
        # wall cleared: regenerate
        fresh = jnp.zeros((n, n), bool).at[1:4, :].set(True)
        bricks = jnp.where(bricks.any(), bricks, fresh)
        # paddle row
        at_row = ny == n - 1
        on_paddle = at_row & (nx == paddle)
        dy = jnp.where(on_paddle, -1, dy)
        ny = jnp.where(on_paddle, s.by + dy, ny)
        # english: moving into the paddle mirrors dx
        dx = jnp.where(on_paddle & (action == 1), -1,
                       jnp.where(on_paddle & (action == 2), 1, dx))
        terminated = at_row & ~on_paddle
        steps = s.steps + 1
        truncated = steps >= self.max_steps
        s2 = BreakoutState(
            paddle=paddle, by=jnp.clip(ny, 0, n - 1).astype(_I),
            bx=jnp.clip(nx, 0, n - 1).astype(_I), dy=dy.astype(_I),
            dx=dx.astype(_I), ly=ly, lx=lx, bricks=bricks, steps=steps)
        return s2, reward, terminated | truncated


class JaxAtariClass:
    """Deepmind-preprocessed view of a jax MinAtar core (the on-device
    twin of env/atari.py AtariClassEnv): the 10x10xC state renders into
    an 84x84 grayscale frame (8x nearest-neighbour upscale, channel
    weights spread entity types across gray levels), stacked over the
    last 4 frames -> obs [84, 84, 4] float32 in [0, 1]. Same frame shape,
    same nature-CNN, same rollout bandwidth as the ALE benchmark — but
    rendered by the TPU inside the rollout scan."""

    SCREEN = 84

    def __init__(self, core=None):
        self.core = core or JaxBreakout()
        self.num_actions = self.core.num_actions
        self.obs_shape = (self.SCREEN, self.SCREEN, 4)

    def _frame(self, core_obs):
        c = core_obs.shape[-1]
        weights = jnp.linspace(1.0, 0.4, c, dtype=_F)
        gray = jnp.max(core_obs * weights, axis=-1)          # [10, 10]
        up = jnp.repeat(jnp.repeat(gray, 8, 0), 8, 1)        # [80, 80]
        return jnp.pad(up, ((2, 2), (2, 2)))                 # [84, 84]

    def reset1(self, key):
        cs = self.core.reset1(key)
        frame = self._frame(self.core.obs1(cs))
        frames = jnp.repeat(frame[:, :, None], 4, axis=2)
        return (cs, frames)

    def obs1(self, s):
        return s[1]

    def step1(self, s, action, key):
        cs, frames = s
        cs2, reward, done = self.core.step1(cs, action, key)
        frame = self._frame(self.core.obs1(cs2))
        frames = jnp.concatenate([frames[:, :, 1:], frame[:, :, None]], 2)
        return (cs2, frames), reward, done


class VecState(NamedTuple):
    env: object          # vmapped env-state pytree
    ep_ret: jnp.ndarray  # [B] running episode return
    ep_len: jnp.ndarray  # [B]
    done_ret_sum: jnp.ndarray  # [] sum of completed-episode returns
    done_len_sum: jnp.ndarray
    done_count: jnp.ndarray


class JaxVecEnv:
    """Batched auto-resetting wrapper: `vmap` over the functional env +
    on-device episode statistics (the host only ever fetches three
    scalars)."""

    def __init__(self, env, num_envs: int):
        self.env = env
        self.num_envs = num_envs
        self.num_actions = env.num_actions
        self.obs_shape = env.obs_shape

    def reset(self, key) -> VecState:
        keys = jax.random.split(key, self.num_envs)
        es = jax.vmap(self.env.reset1)(keys)
        z = jnp.zeros((self.num_envs,), _F)
        zero = jnp.asarray(0.0, _F)
        return VecState(env=es, ep_ret=z, ep_len=jnp.zeros_like(z),
                        done_ret_sum=zero, done_len_sum=zero,
                        done_count=zero)

    def observe(self, vs: VecState):
        return jax.vmap(self.env.obs1)(vs.env)

    def step(self, vs: VecState, actions, key) -> tuple:
        """-> (VecState, rewards [B], dones [B]); finished episodes are
        reset in place (their stats banked first)."""
        k1, k2 = jax.random.split(key)
        skeys = jax.random.split(k1, self.num_envs)
        es, rew, done = jax.vmap(self.env.step1)(vs.env, actions, skeys)
        ep_ret = vs.ep_ret + rew
        ep_len = vs.ep_len + 1.0
        d = done.astype(_F)
        banked = VecState(
            env=es,
            ep_ret=ep_ret * (1.0 - d), ep_len=ep_len * (1.0 - d),
            done_ret_sum=vs.done_ret_sum + (ep_ret * d).sum(),
            done_len_sum=vs.done_len_sum + (ep_len * d).sum(),
            done_count=vs.done_count + d.sum())
        # Auto-reset the finished envs.
        rkeys = jax.random.split(k2, self.num_envs)
        fresh = jax.vmap(self.env.reset1)(rkeys)
        es = jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                done.reshape((-1,) + (1,) * (old.ndim - 1)), new, old),
            fresh, banked.env)
        return banked._replace(env=es), rew, done


def build_rollout(vec_env: JaxVecEnv, module, T: int):
    """A T-step on-device rollout as one scan: policy forward, env step,
    auto-reset, trajectory collection. Returns a pure function suitable
    for jit (and for fusing with GAE + the learner update into a single
    compiled training iteration — see PPO.training_step's on-device
    path)."""

    def rollout(params, vs: VecState, key):
        def step_fn(carry, _):
            vs, key = carry
            key, akey, skey = jax.random.split(key, 3)
            obs = vec_env.observe(vs)
            action, logp, value = module.forward_exploration(
                params, obs, akey)
            vs2, rew, done = vec_env.step(vs, action, skey)
            return (vs2, key), (obs, action, logp, value, rew,
                                done.astype(_F))
        (vs, key), (obs, act, logp, val, rew, done) = jax.lax.scan(
            step_fn, (vs, key), None, length=T)
        last_obs = vec_env.observe(vs)
        _, last_val = module.forward_train(params, last_obs)
        traj = {"obs": obs, "actions": act, "logp": logp, "values": val,
                "rewards": rew, "dones": done, "last_values": last_val}
        return vs, key, traj
    return rollout


_REGISTRY = {}


def make_jax_env(name: str, num_envs: int) -> JaxVecEnv:
    """Names mirror the numpy registry with a `Jax` prefix:
    JaxMinAtarBreakout-v0, JaxAtariClassBreakout-v0."""
    base = name[3:] if name.startswith("Jax") else name
    base = base.split("-")[0]
    if base == "MinAtarBreakout":
        env = JaxBreakout()
    elif base == "AtariClassBreakout":
        env = JaxAtariClass(JaxBreakout())
    else:
        raise ValueError(
            f"no jax-native env {name!r} (have: JaxMinAtarBreakout-v0, "
            f"JaxAtariClassBreakout-v0)")
    return JaxVecEnv(env, num_envs)


def is_jax_env(name: str) -> bool:
    return isinstance(name, str) and name.startswith("Jax")
