"""Multi-agent environments + the multi-agent env runner.

Parity: reference `rllib/env/multi_agent_env.py` (dict-keyed observations/
actions/rewards with an "__all__" done flag) and the multi-agent half of
`rllib/env/multi_agent_env_runner.py`. TPU-split kept: env stepping is CPU
actor work; per-policy batches go to jit-compiled learners.

Scope note vs the reference: every agent in `possible_agents` is assumed
present at every step (no mid-episode agent churn); the reference's
episode slicing for appearing/disappearing agents is not replicated.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.env.env_runner import RunnerGroupBase


class MultiAgentEnv:
    """Dict-keyed multi-agent env interface (parity: multi_agent_env.py).

    Subclasses define:
      possible_agents: list[str]
      observation_spaces / action_spaces: {agent_id: gymnasium space}
      reset(seed) -> (obs_dict, info_dict)
      step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)
        where terminateds/truncateds carry an "__all__" key.
    """

    possible_agents: list[str] = []
    observation_spaces: dict = {}
    action_spaces: dict = {}

    def reset(self, *, seed=None, options=None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def close(self):
        pass


class MultiAgentEnvRunner:
    """Steps one MultiAgentEnv, batching policy forwards per policy id.

    `modules` maps policy_id -> RLModule spec; `policy_mapping_fn`
    (agent_id -> policy_id) routes agents onto policies — several agents
    may share one policy (parameter sharing), matching the reference's
    config.multi_agent(policies=..., policy_mapping_fn=...).
    """

    def __init__(self, env_maker, modules: dict, policy_mapping_fn,
                 seed: int = 0, env_config: dict | None = None):
        import jax

        self.env = env_maker(**(env_config or {}))
        self.agents = list(self.env.possible_agents)
        self.modules = modules
        self.mapping = {aid: policy_mapping_fn(aid) for aid in self.agents}
        # policy id -> its agents, in stable order
        self.policy_agents: dict[str, list[str]] = {}
        for aid in self.agents:
            self.policy_agents.setdefault(self.mapping[aid], []).append(aid)
        unknown = set(self.mapping.values()) - set(modules)
        if unknown:
            raise ValueError(f"policy_mapping_fn routed to unknown "
                             f"policies {sorted(unknown)}")
        self._explore = {pid: jax.jit(m.forward_exploration)
                         for pid, m in modules.items()}
        self._key = jax.random.PRNGKey(seed)
        obs, _ = self.env.reset(seed=seed)
        self._obs = obs
        self._ep_ret = 0.0
        self._ep_len = 0
        self.completed_returns: list[float] = []
        self.completed_lengths: list[int] = []

    def _stack(self, pid: str) -> np.ndarray:
        return np.stack([np.asarray(self._obs[a], np.float32).ravel()
                         for a in self.policy_agents[pid]])

    def sample(self, params: dict, num_steps: int) -> dict:
        """Collect per-policy [T, n_agents, ...] fragments.

        Returns {policy_id: fragment} with the same keys PPO's GAE expects
        (obs/actions/logp/values/rewards/dones/last_values).
        """
        import jax

        T = num_steps
        bufs = {}
        for pid, agents in self.policy_agents.items():
            n = len(agents)
            d = self._stack(pid).shape[-1]
            bufs[pid] = {
                "obs": np.empty((T, n, d), np.float32),
                "actions": np.empty((T, n), np.int64),
                "logp": np.empty((T, n), np.float32),
                "values": np.empty((T, n), np.float32),
                "rewards": np.empty((T, n), np.float32),
                "dones": np.empty((T, n), np.float32),
                # Value of the post-step state at episode boundaries: zero
                # for terminations, V(final next obs) for truncations — the
                # GAE bootstrap (a truncated episode must not be value-cut
                # to zero as if it had ended).
                "bootstrap": np.zeros((T, n), np.float32),
            }
        for t in range(T):
            action_dict = {}
            for pid, agents in self.policy_agents.items():
                obs_b = self._stack(pid)
                self._key, sub = jax.random.split(self._key)
                act, logp, val = self._explore[pid](params[pid], obs_b, sub)
                act = np.asarray(act)
                b = bufs[pid]
                b["obs"][t] = obs_b
                b["actions"][t] = act
                b["logp"][t] = np.asarray(logp)
                b["values"][t] = np.asarray(val)
                for i, aid in enumerate(agents):
                    action_dict[aid] = act[i]
            nxt, rew, term, trunc, _ = self.env.step(action_dict)
            done_all = bool(term.get("__all__")) or bool(trunc.get("__all__"))
            term_all = bool(term.get("__all__"))
            for pid, agents in self.policy_agents.items():
                b = bufs[pid]
                for i, aid in enumerate(agents):
                    b["rewards"][t, i] = rew.get(aid, 0.0)
                    b["dones"][t, i] = float(done_all)
            self._ep_ret += sum(rew.values())
            self._ep_len += 1
            if done_all:
                if not term_all:
                    # Truncated, not terminated: bootstrap with the value of
                    # the final next obs (evaluated before the reset wipes
                    # it).
                    self._obs = nxt
                    for pid, agents in self.policy_agents.items():
                        self._key, sub = jax.random.split(self._key)
                        _, _, bval = self._explore[pid](
                            params[pid], self._stack(pid), sub)
                        bufs[pid]["bootstrap"][t] = np.asarray(bval)
                self.completed_returns.append(self._ep_ret)
                self.completed_lengths.append(self._ep_len)
                self._ep_ret, self._ep_len = 0.0, 0
                nxt, _ = self.env.reset()
            self._obs = nxt
        out = {}
        for pid, agents in self.policy_agents.items():
            self._key, sub = jax.random.split(self._key)
            _, _, last_val = self._explore[pid](
                params[pid], self._stack(pid), sub)
            b = bufs[pid]
            b["last_values"] = np.asarray(last_val)
            out[pid] = b
        return out

    def get_metrics(self) -> dict:
        return {
            "episode_return_mean": (
                float(np.mean(self.completed_returns[-100:]))
                if self.completed_returns else float("nan")),
            "episode_len_mean": (
                float(np.mean(self.completed_lengths[-100:]))
                if self.completed_lengths else float("nan")),
            "num_episodes": len(self.completed_returns),
        }

    def ping(self):
        return "ok"


class MultiAgentEnvRunnerGroup(RunnerGroupBase):
    """Local (num_env_runners == 0) or remote multi-agent runners; dispatch,
    fault replacement, metric aggregation and stop come from the shared
    RunnerGroupBase."""

    runner_cls = MultiAgentEnvRunner

    def __init__(self, env_maker, modules, policy_mapping_fn, *,
                 num_env_runners: int = 0, seed: int = 0,
                 env_config: dict | None = None,
                 restart_failed: bool = True):
        self._init_runners(
            (env_maker, modules, policy_mapping_fn),
            dict(env_config=env_config),
            num_env_runners=num_env_runners, seed=seed,
            restart_failed=restart_failed)
