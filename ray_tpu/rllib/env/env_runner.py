"""EnvRunner: vectorized-environment sampling actor.

Parity: reference `rllib/env/single_agent_env_runner.py:68` (gymnasium
vector envs + ConnectorV2 pipelines) inside `EnvRunnerGroup`
(`env/env_runner_group.py:71`). TPU split kept from the reference: env
stepping is CPU-bound actor work; only the learner touches the accelerator.
The runner does batched policy inference with jitted module forwards on its
local (CPU) jax backend.
"""

from __future__ import annotations

import numpy as np

import ray_tpu


def _flat(obs):
    return np.asarray(obs, dtype=np.float32).reshape(len(obs), -1)


class SingleAgentEnvRunner:
    """Steps `num_envs` copies of a gymnasium env, collecting fixed-length
    rollout fragments (PPO/IMPALA) or transition batches (DQN)."""

    def __init__(self, env_name: str, module, num_envs: int = 1,
                 seed: int = 0, env_config: dict | None = None):
        import gymnasium as gym
        import jax

        from ray_tpu.rllib.env.minatar import register_builtin_envs
        register_builtin_envs()
        # SAME_STEP autoreset (gym<1.0 behavior): on done, step() returns
        # the reset obs. gymnasium 1.x's NEXT_STEP default would record a
        # phantom transition per episode boundary (terminal obs as the new
        # episode's first obs, ignored action, reward 0) in every fragment.
        try:
            self.env = gym.make_vec(
                env_name, num_envs=num_envs, vectorization_mode="sync",
                vector_kwargs={
                    "autoreset_mode": gym.vector.AutoresetMode.SAME_STEP},
                **(env_config or {}))
        except (AttributeError, TypeError):  # older gymnasium
            self.env = gym.make_vec(env_name, num_envs=num_envs,
                                    vectorization_mode="sync",
                                    **(env_config or {}))
        self.num_envs = num_envs
        self.module = module
        # Acting runs on the CPU backend even in-process: env stepping is
        # a per-step host round-trip, and paying an accelerator dispatch
        # per step (hundreds of microseconds, ~ms over a tunneled chip)
        # caps env-steps/s far below the CPU forward itself. The remote
        # runner actors get this for free (CPU-backend workers); this
        # makes local mode match. The learner keeps the accelerator.
        try:
            act_dev = jax.devices("cpu")[0]
        except RuntimeError:
            act_dev = None
        self._act_device = act_dev
        # Placement rides the committed inputs (params + key device_put to
        # CPU below; obs is numpy): jit compiles for the CPU backend with
        # no deprecated device= hint.
        self._explore = jax.jit(module.forward_exploration)
        self._infer = jax.jit(module.forward_inference)
        # The RNG key must live on the acting device too: a key on the
        # default accelerator makes every per-step split a device dispatch
        # (a full network round trip on tunneled chips).
        self._key = jax.random.PRNGKey(seed)
        if act_dev is not None:
            self._key = jax.device_put(self._key, act_dev)
        obs, _ = self.env.reset(seed=seed)
        self.obs = _flat(obs)
        # Per-env accumulators for completed-episode returns.
        self._ep_ret = np.zeros(num_envs, dtype=np.float64)
        self._ep_len = np.zeros(num_envs, dtype=np.int64)
        self.completed_returns: list[float] = []
        self.completed_lengths: list[int] = []

    def sample(self, params, num_steps: int, explore: bool = True) -> dict:
        """Collect a [T, B, ...] fragment. Returns numpy arrays (they ride
        the object plane zero-copy)."""
        import jax

        if self._act_device is not None:
            # One transfer up front; otherwise every per-step jit call
            # re-copies accelerator-resident params to the CPU backend.
            params = jax.device_put(params, self._act_device)
        T, B = num_steps, self.num_envs
        obs_buf = np.empty((T, B, self.obs.shape[-1]), np.float32)
        if getattr(self.module, "action_kind", "discrete") == "continuous":
            act_buf = np.empty((T, B, self.module.action_dim), np.float32)
        else:
            act_buf = np.empty((T, B), np.int64)
        logp_buf = np.empty((T, B), np.float32)
        val_buf = np.empty((T, B), np.float32)
        rew_buf = np.empty((T, B), np.float32)
        done_buf = np.empty((T, B), np.float32)
        term_buf = np.empty((T, B), np.float32)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            if explore:
                action, logp, value = self._explore(params, self.obs, sub)
            else:
                action = self._infer(params, self.obs)
                logp = value = np.zeros(B, np.float32)
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            nxt, rew, term, trunc, _ = self.env.step(action)
            done = np.logical_or(term, trunc)
            rew_buf[t] = rew
            done_buf[t] = done
            term_buf[t] = term  # truncation is NOT termination: TD targets
            # bootstrap through time limits (dones only cut episodes)
            self._ep_ret += rew
            self._ep_len += 1
            for i in np.nonzero(done)[0]:
                self.completed_returns.append(float(self._ep_ret[i]))
                self.completed_lengths.append(int(self._ep_len[i]))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
            self.obs = _flat(nxt)
        # Bootstrap value for the final obs (used by GAE/V-trace).
        self._key, sub = jax.random.split(self._key)
        _, _, last_val = self._explore(params, self.obs, sub)
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "terminateds": term_buf,
            "last_values": np.asarray(last_val),
            "final_obs": self.obs.copy(),  # next_obs tail for TD targets
        }

    def get_metrics(self) -> dict:
        out = {
            "episode_return_mean": (float(np.mean(self.completed_returns[-100:]))
                                    if self.completed_returns else float("nan")),
            "episode_len_mean": (float(np.mean(self.completed_lengths[-100:]))
                                 if self.completed_lengths else float("nan")),
            "num_episodes": len(self.completed_returns),
        }
        return out

    def ping(self):
        return "ok"


class RunnerGroupBase:
    """Shared local/remote dispatch + fault handling for runner groups
    (parity: env_runner_group.py:71 local-worker mode; fault-awareness per
    restart_failed_env_runners / FaultAwareApply, env_runner.py:32).

    Subclasses set `runner_cls` and call `_init_runners(args, kw, ...)`;
    dead remote runners are replaced on the next sample round."""

    runner_cls: type = None

    def _init_runners(self, args: tuple, kw: dict, *, num_env_runners: int,
                      seed: int, restart_failed: bool):
        self._args = args
        self._kw = kw
        self.restart_failed = restart_failed
        self.num_env_runners = num_env_runners
        self._seed = seed
        if num_env_runners == 0:
            self.local = self.runner_cls(*args, seed=seed, **kw)
            self.remotes = []
        else:
            self.local = None
            self._cls = ray_tpu.remote(num_cpus=1)(self.runner_cls)
            self.remotes = [
                self._cls.remote(*args, seed=seed + i, **kw)
                for i in range(num_env_runners)]

    def _replace(self, idx: int):
        self.remotes[idx] = self._cls.remote(
            *self._args, seed=self._seed + 1000 + idx, **self._kw)

    def sample(self, params, num_steps: int) -> list[dict]:
        if self.local is not None:
            return [self.local.sample(params, num_steps)]
        params_ref = ray_tpu.put(params)
        refs = [(i, r.sample.remote(params_ref, num_steps))
                for i, r in enumerate(self.remotes)]
        out = []
        for i, ref in refs:
            try:
                out.append(ray_tpu.get(ref, timeout=120))
            except ray_tpu.RayTpuError:
                if not self.restart_failed:
                    raise
                self._replace(i)
        return out

    def sample_async(self, params_ref, num_steps: int):
        """One in-flight sample request per runner (IMPALA-style)."""
        return [(i, r.sample.remote(params_ref, num_steps))
                for i, r in enumerate(self.remotes)]

    def aggregate_metrics(self) -> dict:
        if self.local is not None:
            return self.local.get_metrics()
        rets, lens, n = [], [], 0
        for i, r in enumerate(self.remotes):
            try:
                m = ray_tpu.get(r.get_metrics.remote(), timeout=60)
            except ray_tpu.RayTpuError:
                if self.restart_failed:
                    self._replace(i)
                continue
            if m["num_episodes"]:
                rets.append(m["episode_return_mean"])
                lens.append(m["episode_len_mean"])
                n += m["num_episodes"]
        return {
            "episode_return_mean": float(np.mean(rets)) if rets else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
            "num_episodes": n,
        }

    def stop(self):
        for r in self.remotes:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass


class EnvRunnerGroup(RunnerGroupBase):
    runner_cls = SingleAgentEnvRunner

    def __init__(self, env_name: str, module, *, num_env_runners: int = 0,
                 num_envs_per_env_runner: int = 1, seed: int = 0,
                 env_config: dict | None = None, restart_failed: bool = True):
        self._init_runners(
            (env_name, module),
            dict(num_envs=num_envs_per_env_runner, env_config=env_config),
            num_env_runners=num_env_runners, seed=seed,
            restart_failed=restart_failed)
