"""Offline RL data path: record rollouts to files, load them back.

Parity: reference `rllib/offline/` (offline data writers/readers feeding
BC/MARWIL/CQL — the reference records episodes to JSON/Parquet and reads
them through Ray Data; here transitions ride ray_tpu.data the same way).
"""

from __future__ import annotations

import glob as _glob
import os

import numpy as np


def record_transitions(env_name: str, module, params, *, num_steps: int,
                       path: str | None = None, fmt: str = "parquet",
                       seed: int = 0, env_config: dict | None = None,
                       explore: bool = True):
    """Roll `module` (with `params`) in `env_name` and record flat
    transitions {obs, actions, rewards, next_obs, dones}.

    Returns the row list; with `path`, also writes one parquet/json file
    per call (the reference's output writer shape).
    """
    from ray_tpu.rllib.algorithms.algorithm import Algorithm
    from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner

    runner = SingleAgentEnvRunner(env_name, module, seed=seed,
                                  env_config=env_config)
    frag = runner.sample(params, num_steps, explore=explore)
    actions_2d = getattr(module, "action_kind", "discrete") == "continuous"
    cols = Algorithm._replay_rows(frag, actions_2d=actions_2d)
    n = len(cols["obs"])
    rows = [{k: cols[k][i].tolist() if cols[k][i].ndim else cols[k][i].item()
             for k in cols} for i in range(n)]
    if path is not None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if fmt == "parquet":
            import pyarrow as pa
            import pyarrow.parquet as pq
            pq.write_table(pa.Table.from_pylist(rows), path)
        elif fmt == "json":
            import json
            with open(path, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
        else:
            raise ValueError(f"unknown offline format {fmt!r}")
    return rows


def load_offline(input_):
    """Normalize any offline input into a row list.

    Accepts: a list of dicts, a ray_tpu.data Dataset, or a path/glob to
    parquet/jsonl files (parity: the reference's `input_` config accepting
    dataset paths).
    """
    if input_ is None:
        return None
    if isinstance(input_, list):
        return input_
    if hasattr(input_, "take_all"):  # ray_tpu.data Dataset
        return input_.take_all()
    if isinstance(input_, str):
        paths = sorted(_glob.glob(input_)) or [input_]
        rows = []
        for p in paths:
            if p.endswith(".parquet"):
                import pyarrow.parquet as pq
                rows.extend(pq.read_table(p).to_pylist())
            else:
                import json
                with open(p) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            rows.append(json.loads(line))
        return rows
    raise TypeError(f"cannot load offline input of type {type(input_)}")


def rows_to_arrays(rows: list[dict], *, continuous: bool = False) -> dict:
    """Columnar numpy views of a row list for replay/minibatching."""
    out = {
        "obs": np.asarray([r["obs"] for r in rows], np.float32),
        "rewards": np.asarray([r.get("rewards", 0.0) for r in rows],
                              np.float32),
        "dones": np.asarray([r.get("dones", 0.0) for r in rows], np.float32),
    }
    acts = [r["actions"] for r in rows]
    out["actions"] = (np.asarray(acts, np.float32) if continuous
                      else np.asarray(acts, np.int64))
    if rows and "next_obs" in rows[0]:
        out["next_obs"] = np.asarray([r["next_obs"] for r in rows],
                                     np.float32)
    return out
