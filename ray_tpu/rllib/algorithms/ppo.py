"""PPO — clipped-surrogate policy optimization.

Parity: reference `rllib/algorithms/ppo/ppo.py:388` (new-stack
training_step: synchronous_parallel_sample -> GAE -> LearnerGroup.update
with minibatch epochs). TPU-native: GAE is a jitted `lax.scan` over the
time axis and the update is one jit-compiled loss+grad+apply; there is no
torch/tf policy twin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.lambda_ = 0.95

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, lambda_=None, **kw):
        super().training(**kw)
        if clip_param is not None:
            self.clip_param = clip_param
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if lambda_ is not None:
            self.lambda_ = lambda_
        return self


@functools.partial(jax.jit, static_argnames=("gamma", "lam"))
def _gae(rewards, values, dones, last_values, *, gamma, lam,
         bootstrap=None):
    """Generalized advantage estimation over [T, B] via lax.scan
    (time-reversed; no Python loop under jit).

    `dones` marks episode boundaries (terminated OR truncated): the lambda
    chain always cuts there. `bootstrap`, when given, holds the value of the
    post-step state at boundary rows — zero for true terminations, V(s_next)
    for truncations — so truncated episodes are bootstrapped instead of
    treated as if the return were zero."""
    if bootstrap is None:
        bootstrap = jnp.zeros_like(rewards)

    def step(carry, xs):
        r, v, d, v_next, bv = xs
        v_eff = (1.0 - d) * v_next + d * bv
        delta = r + gamma * v_eff - v
        adv = delta + gamma * lam * (1.0 - d) * carry
        return adv, adv

    v_next = jnp.concatenate([values[1:], last_values[None]], axis=0)
    _, advs = jax.lax.scan(
        step, jnp.zeros_like(last_values),
        (rewards, values, dones, v_next, bootstrap), reverse=True)
    return advs, advs + values


def ppo_loss(params, batch, *, module, clip, vf_coef, ent_coef):
    logits, value = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), -1)[..., 0]
    ratio = jnp.exp(logp - batch["logp"])
    adv = batch["advantages"]
    surr = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
    pi_loss = -surr.mean()
    vf_loss = jnp.square(value - batch["returns"]).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    total = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy,
                   "kl": (batch["logp"] - logp).mean()}


class PPO(Algorithm):
    supports_ondevice_env = True  # jax-native envs (env/jax_env.py)

    def _loss_fn(self):
        return functools.partial(ppo_loss, module=self.module)

    def _loss_cfg(self):
        c = self.config
        return {"clip": c.clip_param, "vf_coef": c.vf_loss_coeff,
                "ent_coef": c.entropy_coeff}

    def training_step(self) -> dict:
        if self._jax_vec_env is not None:
            return self._training_step_ondevice()
        import time as _time
        c = self.config
        _t0 = _time.perf_counter()
        params = self.learner_group.get_weights()
        batches = []
        steps = 0
        while steps < c.train_batch_size:
            frags = self.env_runner_group.sample(
                params, c.rollout_fragment_length)
            for f in frags:
                adv, ret = _gae(
                    jnp.asarray(f["rewards"]), jnp.asarray(f["values"]),
                    jnp.asarray(f["dones"]), jnp.asarray(f["last_values"]),
                    gamma=c.gamma, lam=c.lambda_)
                # One fetch for both outputs (two np.asarray calls = two
                # blocking device round trips).
                f["advantages"], f["returns"] = jax.device_get((adv, ret))
                steps += f["rewards"].size
            batches.extend(frags)
        self._timesteps += steps
        batch = self._concat_fragments(batches)
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        batch = {k: batch[k] for k in
                 ("obs", "actions", "logp", "advantages", "returns")}
        n = batch["obs"].shape[0]
        _sample_ms = (_time.perf_counter() - _t0) * 1e3
        _t0 = _time.perf_counter()
        # Local learner: the whole epochs x minibatches sweep is one jit
        # call (one dispatch + one metrics fetch per training step).
        metrics = self.learner_group.update_epochs(
            batch, num_epochs=c.num_epochs,
            minibatch_size=c.minibatch_size, seed=self.iteration)
        if metrics is not None:
            # sample vs learner split (the bench reports the learner step
            # time on the accelerator separately from host env stepping)
            metrics["sample_ms"] = round(_sample_ms, 1)
            metrics["learner_update_ms"] = round(
                (_time.perf_counter() - _t0) * 1e3, 1)
            return metrics
        metrics = {}
        rng = np.random.default_rng(self.iteration)
        for _ in range(c.num_epochs):
            perm = rng.permutation(n)
            for s in range(0, n, c.minibatch_size):
                idx = perm[s:s + c.minibatch_size]
                if len(idx) < 2:
                    continue
                metrics = self.learner_group.update(
                    {k: v[idx] for k, v in batch.items()})
        return metrics

    def _training_step_ondevice(self) -> dict:
        """Jax-native env: the ENTIRE iteration (rollout + GAE + epochs)
        is one compiled dispatch (core/ondevice.py) — obs never touch the
        host, which on a tunneled chip is the difference between ~300 and
        tens of thousands of env-steps/s at the Atari frame shape."""
        import time as _time

        c = self.config
        learner = self.learner_group.local
        if learner is None:
            raise ValueError("on-device PPO uses a local learner "
                             "(num_learners=0)")
        if self._ondev_iter is None:
            from ray_tpu.rllib.core.ondevice import build_ppo_train_iter
            B = self._jax_vec_env.num_envs
            T = max(1, c.train_batch_size // B)
            self._ondev_iter = build_ppo_train_iter(
                self._jax_vec_env, self.module, T=T,
                num_epochs=c.num_epochs,
                minibatch_size=min(c.minibatch_size, T * B),
                gamma=c.gamma, lam=c.lambda_, clip=c.clip_param,
                vf_coef=c.vf_loss_coeff, ent_coef=c.entropy_coeff,
                tx=learner.tx)
            self._ondev_T = T
            import jax as _jax
            self._ondev_vs = self._jax_vec_env.reset(
                _jax.random.PRNGKey(c.seed or 0))
            self._ondev_key = _jax.random.PRNGKey((c.seed or 0) + 1)
        _t0 = _time.perf_counter()
        (learner.params, learner.opt_state, self._ondev_vs,
         self._ondev_key, m) = self._ondev_iter(
            learner.params, learner.opt_state, self._ondev_vs,
            self._ondev_key)
        import jax as _jax
        m = {k: float(v)
             for k, v in _jax.device_get(m).items()}  # ONE device fetch
        dt_ms = (_time.perf_counter() - _t0) * 1e3
        steps = self._ondev_T * self._jax_vec_env.num_envs
        self._timesteps += steps
        self.env_runner_group.record(
            m.pop("ep_ret_sum"), m.pop("ep_len_sum"), m.pop("ep_count"))
        m["learner_update_ms"] = round(dt_ms, 1)
        m["sample_ms"] = 0.0  # sampling IS the update dispatch
        return m
