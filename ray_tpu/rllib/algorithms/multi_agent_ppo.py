"""Multi-agent PPO: per-policy modules + learners over a MultiAgentEnv.

Parity: reference multi-agent training — `rllib/env/multi_agent_env.py`
routed through `config.multi_agent(policies=..., policy_mapping_fn=...)`
with one RLModule per policy in a MultiRLModule
(`core/rl_module/multi_rl_module.py`) and per-module losses in the learner.
TPU-native: each policy's update is its own jit-compiled loss+grad+apply;
policies with shared parameters simply map multiple agents onto one module.
"""

from __future__ import annotations

import functools
import time

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import _gae, ppo_loss
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import ActorCriticModule
from ray_tpu.rllib.env.multi_agent import MultiAgentEnvRunnerGroup


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=MultiAgentPPO)
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.lambda_ = 0.95
        self.policies: list[str] | None = None
        self.policy_mapping_fn = None

    def multi_agent(self, *, policies=None, policy_mapping_fn=None):
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, lambda_=None, **kw):
        super().training(**kw)
        for k, v in (("clip_param", clip_param),
                     ("vf_loss_coeff", vf_loss_coeff),
                     ("entropy_coeff", entropy_coeff),
                     ("lambda_", lambda_)):
            if v is not None:
                setattr(self, k, v)
        return self


class MultiAgentPPO:
    """Trainable over {policy_id: module/learner}; config.env is a
    MultiAgentEnv class or factory callable."""

    def __init__(self, config: MultiAgentPPOConfig):
        c = self.config = config
        if c.env is None or not callable(c.env):
            raise ValueError("config.environment(env=...) must be a "
                             "MultiAgentEnv class/factory for MultiAgentPPO")
        probe = c.env(**c.env_config)
        mapping = c.policy_mapping_fn or (lambda aid: aid)
        policies = c.policies or sorted(
            {mapping(a) for a in probe.possible_agents})
        self.policies = policies
        hidden = tuple(c.model.get("hidden", (64, 64)))
        self.modules = {}
        for pid in policies:
            # module shapes come from any agent mapped onto this policy
            aid = next((a for a in probe.possible_agents
                        if mapping(a) == pid), None)
            if aid is None:
                raise ValueError(
                    f"policy {pid!r} is listed in config.policies but "
                    f"policy_mapping_fn routes no agent to it "
                    f"(agents: {probe.possible_agents})")
            obs_dim = int(np.prod(probe.observation_spaces[aid].shape))
            n_act = int(probe.action_spaces[aid].n)
            self.modules[pid] = ActorCriticModule(obs_dim, n_act, hidden)
        probe.close()
        loss_cfg = {"clip": c.clip_param, "vf_coef": c.vf_loss_coeff,
                    "ent_coef": c.entropy_coeff}
        self.learners = {
            pid: Learner(m, functools.partial(ppo_loss, module=m),
                         lr=c.lr, grad_clip=c.grad_clip,
                         seed=c.seed + i, loss_cfg=loss_cfg)
            for i, (pid, m) in enumerate(self.modules.items())}
        self.env_runner_group = MultiAgentEnvRunnerGroup(
            c.env, self.modules, mapping,
            num_env_runners=c.num_env_runners, seed=c.seed,
            env_config=c.env_config,
            restart_failed=c.restart_failed_env_runners)
        self.iteration = 0
        self._timesteps = 0

    def get_weights(self) -> dict:
        return {pid: ln.get_weights() for pid, ln in self.learners.items()}

    def training_step(self) -> dict:
        c = self.config
        params = self.get_weights()
        frag_lists = []
        for _attempt in range(10):
            # A round can come back empty when every remote runner died and
            # was replaced (fault path) — retry against the fresh runners.
            frag_lists = self.env_runner_group.sample(
                params, c.rollout_fragment_length)
            if frag_lists:
                break
        if not frag_lists:
            raise RuntimeError(
                "multi-agent sample returned no fragments after 10 rounds "
                "of env-runner replacement")
        metrics = {}
        rng = np.random.default_rng(self.iteration)
        for pid in self.policies:
            frags = [fl[pid] for fl in frag_lists]
            parts = []
            for f in frags:
                adv, ret = _gae(
                    jnp.asarray(f["rewards"]), jnp.asarray(f["values"]),
                    jnp.asarray(f["dones"]), jnp.asarray(f["last_values"]),
                    gamma=c.gamma, lam=c.lambda_,
                    bootstrap=jnp.asarray(f["bootstrap"]))
                f["advantages"] = np.asarray(adv)
                f["returns"] = np.asarray(ret)
                parts.append(f)
                self._timesteps += f["rewards"].size
            batch = {}
            for k in ("obs", "actions", "logp", "advantages", "returns"):
                batch[k] = np.concatenate(
                    [p[k].reshape(-1, *p[k].shape[2:]) for p in parts])
            adv = batch["advantages"]
            batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
            n = batch["obs"].shape[0]
            for _ in range(c.num_epochs):
                perm = rng.permutation(n)
                for s in range(0, n, c.minibatch_size):
                    idx = perm[s:s + c.minibatch_size]
                    if len(idx) < 2:
                        continue
                    m = self.learners[pid].update(
                        {k: v[idx] for k, v in batch.items()})
                    metrics.update({f"{pid}/{k}": v for k, v in m.items()})
        return metrics

    def train(self) -> dict:
        t0 = time.perf_counter()
        self.iteration += 1
        result = self.training_step()
        result.update(self.env_runner_group.aggregate_metrics())
        result.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
        })
        return result

    def save_to_path(self, path: str):
        import os
        import pickle
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({"weights": self.get_weights(),
                         "iteration": self.iteration,
                         "timesteps": self._timesteps}, f)
        return path

    def restore_from_path(self, path: str):
        import os
        import pickle
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        for pid, w in state["weights"].items():
            self.learners[pid].set_weights(w)
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    def stop(self):
        self.env_runner_group.stop()
