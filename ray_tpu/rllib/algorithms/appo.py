"""APPO — asynchronous PPO (IMPALA's actor-learner loop + clipped loss).

Parity: reference `rllib/algorithms/appo/appo.py` (async sampling with
V-trace off-policy correction and the PPO clipped surrogate on the
corrected advantages).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.2

    def training(self, *, clip_param=None, **kw):
        super().training(**kw)
        if clip_param is not None:
            self.clip_param = clip_param
        return self


def appo_loss(params, batch, *, module, clip, vf_coef, ent_coef):
    logits, value = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), -1)[..., 0]
    ratio = jnp.exp(logp - batch["behavior_logp"])
    adv = batch["pg_advantages"]
    surr = jnp.minimum(ratio * adv,
                       jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
    pi_loss = -surr.mean()
    vf_loss = jnp.square(value - batch["vs"]).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    total = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


class APPO(IMPALA):
    """IMPALA's async machinery; the learner applies the clipped surrogate
    against the behavior policy's log-probs."""

    def _loss_fn(self):
        return functools.partial(appo_loss, module=self.module)

    def _loss_cfg(self):
        c = self.config
        return {"clip": c.clip_param, "vf_coef": c.vf_loss_coeff,
                "ent_coef": c.entropy_coeff}

    def _make_batch(self, f, vs, pg_adv):
        import numpy as np
        T, B = f["rewards"].shape
        return {
            "obs": f["obs"].reshape(T * B, -1),
            "actions": f["actions"].reshape(-1),
            "behavior_logp": f["logp"].reshape(-1),
            "vs": np.asarray(vs).reshape(-1),
            "pg_advantages": np.asarray(pg_adv).reshape(-1),
        }
