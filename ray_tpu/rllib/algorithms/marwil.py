"""MARWIL — monotonic advantage re-weighted imitation learning.

Parity: reference `rllib/algorithms/marwil/marwil.py` (offline RL between
BC and RL: clone actions weighted by exp(beta * advantage), advantage =
observed return minus the learned value baseline; beta=0 reduces to BC).
Shares BC's offline-data plumbing; rows additionally carry "returns"
(rewards-to-go).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.bc import BC, BCConfig


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.beta = 1.0
        self.vf_coeff = 1.0

    def training(self, *, beta=None, vf_coeff=None, **kw):
        super().training(**kw)
        if beta is not None:
            self.beta = beta
        if vf_coeff is not None:
            self.vf_coeff = vf_coeff
        return self


def marwil_loss(params, batch, *, module, beta, vf_coeff):
    logits, value = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), -1)[..., 0]
    adv = batch["returns"] - value
    # Scale-normalize before exponentiating (parity: the reference divides
    # by a running sqrt(E[adv^2]) — raw returns in the hundreds would
    # overflow float32 exp and NaN the whole tree); the clip bounds the
    # symmetric underflow (all-w=0 -> silent zero gradient).
    adv_sg = jax.lax.stop_gradient(adv)
    rms = jnp.sqrt(jnp.mean(jnp.square(adv_sg)) + 1e-8)
    w = jnp.exp(jnp.clip(beta * adv_sg / rms, -10.0, 10.0))
    w = w / jnp.maximum(w.mean(), 1e-8)
    pi_loss = -(w * logp).mean()
    vf_loss = jnp.square(adv).mean()
    return pi_loss + vf_coeff * vf_loss, {
        "policy_loss": pi_loss, "vf_loss": vf_loss,
        "mean_advantage": adv.mean()}


class MARWIL(BC):
    def __init__(self, config):
        super().__init__(config)
        # self._rows is the ONE materialization done by BC — re-running a
        # lazy Dataset here could reorder rows and misalign returns.
        if "returns" not in self._rows[0]:
            self.stop()  # groups already exist: don't leak their actors
            raise ValueError(
                "MARWIL offline rows need 'returns' (rewards-to-go)")
        self._returns = np.asarray([r["returns"] for r in self._rows],
                                   np.float32)

    def _loss_fn(self):
        return functools.partial(
            marwil_loss, module=self.module, beta=self.config.beta,
            vf_coeff=self.config.vf_coeff)

    def _batch(self, sel) -> dict:
        return {"obs": self._obs[sel], "actions": self._actions[sel],
                "returns": self._returns[sel]}
