"""Algorithm: the trainable driver of the RL stack.

Parity: reference `rllib/algorithms/algorithm.py:198` (a Tune Trainable
whose `train()` runs one `training_step` over EnvRunnerGroup +
LearnerGroup, per §3.6 of the survey). Checkpointing follows the
reference's Checkpointable shape: weights + config dict.
"""

from __future__ import annotations

import pickle
import time

import gymnasium as gym
import numpy as np

from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import module_for_env
from ray_tpu.rllib.env.env_runner import EnvRunnerGroup


class Algorithm:
    """Subclasses define `loss_fn`, `module_kind`, `training_step()`."""

    module_kind = "actor_critic"

    def __init__(self, config):
        self.config = config
        if config.env is None:
            raise ValueError("config.environment(env=...) is required")
        from ray_tpu.rllib.env.jax_env import is_jax_env, make_jax_env
        self._jax_vec_env = None
        self._ondev_iter = None  # built lazily by the on-device path
        if is_jax_env(config.env):
            # On-device env: dynamics are jax, the training iteration can
            # compile end-to-end (env/jax_env.py + core/ondevice.py); no
            # gym probe, no host env runners.
            from ray_tpu.rllib.core.ondevice import OnDeviceSamplerGroup
            from ray_tpu.rllib.core.rl_module import (
                MINATAR_FILTERS, NATURE_FILTERS, CNNActorCriticModule)
            venv = make_jax_env(config.env,
                                config.num_envs_per_env_runner)
            if not getattr(self, "supports_ondevice_env", False):
                raise ValueError(
                    "jax-native envs need an algorithm with an on-device "
                    f"training path (PPO); {type(self).__name__} uses "
                    "the gym env path")
            filters, dense = ((NATURE_FILTERS, 512)
                              if venv.obs_shape[0] >= 64
                              else (MINATAR_FILTERS, 128))
            self.module = CNNActorCriticModule(
                venv.obs_shape, venv.num_actions, filters=filters,
                dense=dense)
            self._jax_vec_env = venv
            self.env_runner_group = OnDeviceSamplerGroup()
        else:
            from ray_tpu.rllib.env.minatar import register_builtin_envs
            register_builtin_envs()
            probe = gym.make(config.env, **config.env_config)
            self.module = module_for_env(
                probe, hidden=tuple(config.model.get("hidden", (64, 64))),
                kind=self.module_kind)
            probe.close()
            self.env_runner_group = EnvRunnerGroup(
                config.env, self.module,
                num_env_runners=config.num_env_runners,
                num_envs_per_env_runner=config.num_envs_per_env_runner,
                seed=config.seed, env_config=config.env_config,
                restart_failed=config.restart_failed_env_runners)
        self.learner_group = LearnerGroup(
            self.module, self._loss_fn(),
            num_learners=config.num_learners,
            config={"lr": config.lr, "grad_clip": config.grad_clip,
                    "seed": config.seed, "loss_cfg": self._loss_cfg()})
        self.iteration = 0
        self._timesteps = 0

    # ---- subclass hooks ----

    def _loss_fn(self):
        raise NotImplementedError

    def _loss_cfg(self) -> dict:
        return {}

    def training_step(self) -> dict:
        raise NotImplementedError

    # ---- Trainable surface ----

    def train(self) -> dict:
        t0 = time.perf_counter()
        self.iteration += 1
        result = self.training_step()
        metrics = self.env_runner_group.aggregate_metrics()
        result.update(metrics)
        result.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
        })
        return result

    def get_weights(self):
        return self.learner_group.get_weights()

    def _extra_state(self) -> dict:
        """Algorithm-specific checkpoint payload (SAC: target nets, alpha,
        optimizer states). Base: nothing."""
        return {}

    def _load_extra_state(self, extra: dict, weights):
        pass

    def save_to_path(self, path: str):
        import os
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({"weights": self.get_weights(),
                         "iteration": self.iteration,
                         "timesteps": self._timesteps,
                         "extra": self._extra_state(),
                         "config": self.config.to_dict()}, f)
        return path

    def restore_from_path(self, path: str):
        import os
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        if self.learner_group.local is not None:
            self.learner_group.local.set_weights(state["weights"])
        else:
            import ray_tpu
            ray_tpu.get([r.set_weights.remote(state["weights"])
                         for r in self.learner_group.remotes], timeout=120)
        self._load_extra_state(state.get("extra", {}), state["weights"])
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    @staticmethod
    def _replay_rows(f, *, actions_2d: bool) -> dict:
        """Fragment -> flat replay transitions, bootstrapping through time
        limits: truncated-not-terminated rows are dropped (their next_obs
        is the auto-reset observation) and dones carry terminateds only."""
        import numpy as np
        T, B = f["rewards"].shape
        next_obs = np.concatenate([f["obs"][1:], f["final_obs"][None]],
                                  axis=0)
        dones = f["dones"].reshape(-1)
        terms = f["terminateds"].reshape(-1)
        keep = ~((dones > 0) & (terms == 0))
        actions = (f["actions"].reshape(T * B, -1) if actions_2d
                   else f["actions"].reshape(-1))
        return {
            "obs": f["obs"].reshape(T * B, -1)[keep],
            "actions": actions[keep],
            "rewards": f["rewards"].reshape(-1).astype(np.float32)[keep],
            "dones": terms.astype(np.float32)[keep],
            "next_obs": next_obs.reshape(T * B, -1)[keep],
        }

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()

    # ---- shared helpers ----

    def _concat_fragments(self, fragments: list[dict]) -> dict:
        """[T,B,...] fragments from every runner -> flat [N,...] batch,
        after per-fragment advantage computation by the subclass."""
        out = {}
        for k in fragments[0]:
            if k == "last_values":
                continue
            out[k] = np.concatenate(
                [f[k].reshape(-1, *f[k].shape[2:]) for f in fragments])
        return out
