"""SAC — soft actor-critic for continuous control.

Parity: reference `rllib/algorithms/sac/sac.py` (off-policy maximum-entropy
RL: twin Q critics with a soft TD target, reparameterized actor, and
auto-tuned temperature). TPU-native: the three updates (critic, actor,
alpha) fuse into ONE jit over the online/target trees — the module is the
squashed-Gaussian spec in rl_module.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 500
        self.tau = 0.005               # polyak target update
        self.initial_alpha = 0.2
        self.target_entropy = None     # None -> -action_dim
        self.lr = 3e-4
        self.train_batch_size = 64
        self.num_updates_per_iter = 32
        self.rollout_fragment_length = 16

    def training(self, *, replay_buffer_capacity=None, tau=None,
                 initial_alpha=None, target_entropy=None,
                 num_steps_sampled_before_learning_starts=None,
                 num_updates_per_iter=None, **kw):
        super().training(**kw)
        for k, v in (("replay_buffer_capacity", replay_buffer_capacity),
                     ("tau", tau), ("initial_alpha", initial_alpha),
                     ("target_entropy", target_entropy),
                     ("num_steps_sampled_before_learning_starts",
                      num_steps_sampled_before_learning_starts),
                     ("num_updates_per_iter", num_updates_per_iter)):
            if v is not None:
                setattr(self, k, v)
        return self


class SAC(Algorithm):
    """Owns its own fused update (critic+actor+alpha in one jit) instead of
    the generic LearnerGroup single-loss path."""

    module_kind = "sac"

    def __init__(self, config):
        config.num_learners = 0  # the fused update IS the learner
        super().__init__(config)
        c = config
        m = self.module
        if getattr(m, "action_kind", "discrete") != "continuous":
            raise ValueError("SAC needs a continuous (Box) action space")
        self.buffer = ReplayBuffer(c.replay_buffer_capacity, seed=c.seed)
        self.params = self.learner_group.get_weights()
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.log_alpha = jnp.asarray(np.log(c.initial_alpha), jnp.float32)
        self.target_entropy = (c.target_entropy
                               if c.target_entropy is not None
                               else -float(m.action_dim))
        self.tx = optax.adam(c.lr)
        self.opt_state = self.tx.init(self.params)
        self.alpha_tx = optax.adam(c.lr)
        self.alpha_opt_state = self.alpha_tx.init(self.log_alpha)
        self._key = jax.random.PRNGKey(c.seed + 7)

        gamma, tau, tgt_ent = c.gamma, c.tau, self.target_entropy

        def update(params, target_params, opt_state, log_alpha,
                   alpha_opt_state, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(log_alpha)

            # soft TD target from the target critics
            next_a, next_logp = m.sample(params, batch["next_obs"], k1)
            tq1, tq2 = m.q_values(target_params, batch["next_obs"], next_a)
            tq = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * tq
            target = jax.lax.stop_gradient(target)

            def critic_loss(p):
                q1, q2 = m.q_values(p, batch["obs"], batch["actions"])
                return (jnp.square(q1 - target).mean()
                        + jnp.square(q2 - target).mean())

            def actor_loss(p):
                a, logp = m.sample(p, batch["obs"], k2)
                q1, q2 = m.q_values(jax.lax.stop_gradient(p), batch["obs"],
                                    a)
                return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

            closs, cgrads = jax.value_and_grad(critic_loss)(params)
            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(params)
            # Critic grads touch q*, actor grads touch pi*: sum is safe.
            grads = jax.tree_util.tree_map(lambda a_, b_: a_ + b_,
                                           cgrads, agrads)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            def alpha_loss(la):
                return (-jnp.exp(la)
                        * (jax.lax.stop_gradient(logp) + tgt_ent)).mean()

            al, agrad = jax.value_and_grad(alpha_loss)(log_alpha)
            aupd, alpha_opt_state = self.alpha_tx.update(
                agrad, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, aupd)

            target_params = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, target_params, params)
            aux = {"critic_loss": closs, "actor_loss": aloss,
                   "alpha": jnp.exp(log_alpha),
                   "entropy": -logp.mean()}
            return (params, target_params, opt_state, log_alpha,
                    alpha_opt_state, aux)

        self._update = jax.jit(update)

    def _loss_fn(self):
        # The generic learner is only a parameter container for SAC.
        return lambda params, batch: (jnp.float32(0.0), {})

    def training_step(self) -> dict:
        c = self.config
        frags = self.env_runner_group.sample(self.params,
                                             c.rollout_fragment_length)
        for f in frags:
            self.buffer.add_batch(self._replay_rows(f, actions_2d=True))
            self._timesteps += f["rewards"].size
        metrics = {}
        if self._timesteps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iter):
                batch = {k: jnp.asarray(v)
                         for k, v in self.buffer.sample(
                             c.train_batch_size).items()}
                self._key, sub = jax.random.split(self._key)
                (self.params, self.target_params, self.opt_state,
                 self.log_alpha, self.alpha_opt_state, aux) = self._update(
                    self.params, self.target_params, self.opt_state,
                    self.log_alpha, self.alpha_opt_state, batch, sub)
                metrics = {k: float(v) for k, v in aux.items()}
        return metrics

    def get_weights(self):
        return jax.device_get(self.params)

    def _extra_state(self) -> dict:
        return {
            "target_params": jax.device_get(self.target_params),
            "log_alpha": float(self.log_alpha),
            "opt_state": jax.device_get(self.opt_state),
            "alpha_opt_state": jax.device_get(self.alpha_opt_state),
            "key": jax.device_get(self._key),
        }

    def _load_extra_state(self, extra: dict, weights):
        # SAC trains from self.params, not the learner group — apply the
        # checkpointed weights here or restore would be a no-op.
        self.params = jax.device_put(weights)
        if extra:
            self.target_params = jax.device_put(extra["target_params"])
            self.log_alpha = jnp.asarray(extra["log_alpha"], jnp.float32)
            self.opt_state = jax.device_put(extra["opt_state"])
            self.alpha_opt_state = jax.device_put(extra["alpha_opt_state"])
            self._key = jnp.asarray(extra["key"])