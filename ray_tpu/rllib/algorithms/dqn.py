"""DQN — double/dueling deep Q-learning with a replay buffer.

Parity: reference `rllib/algorithms/dqn/dqn.py` (new stack: sample ->
replay buffer -> TD update -> periodic target sync). TPU-native: the TD
loss + double-Q target is one jit-compiled function over the online and
target param trees; exploration is Boltzmann over Q (see QModule) instead
of a stateful epsilon connector.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500  # env steps
        self.double_q = True
        self.lr = 1e-3
        self.train_batch_size = 32
        self.num_updates_per_iter = 32

    def training(self, *, replay_buffer_capacity=None,
                 num_steps_sampled_before_learning_starts=None,
                 target_network_update_freq=None, double_q=None,
                 num_updates_per_iter=None, **kw):
        super().training(**kw)
        for k, v in (("replay_buffer_capacity", replay_buffer_capacity),
                     ("num_steps_sampled_before_learning_starts",
                      num_steps_sampled_before_learning_starts),
                     ("target_network_update_freq",
                      target_network_update_freq),
                     ("double_q", double_q),
                     ("num_updates_per_iter", num_updates_per_iter)):
            if v is not None:
                setattr(self, k, v)
        return self


def dqn_loss(params, batch, *, module, gamma, double_q):
    """TD loss; batch carries the target tree under 'target_params' --
    it rides the batch so the jitted signature stays (params, batch)."""
    q = module.forward_train(params, batch["obs"])
    q_a = jnp.take_along_axis(
        q, batch["actions"][..., None].astype(jnp.int32), -1)[..., 0]
    q_next_target = module.forward_train(batch["target_params"],
                                         batch["next_obs"])
    if double_q:
        q_next_online = module.forward_train(params, batch["next_obs"])
        best = jnp.argmax(q_next_online, axis=-1)
        q_next = jnp.take_along_axis(
            q_next_target, best[..., None], -1)[..., 0]
    else:
        q_next = q_next_target.max(axis=-1)
    target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * q_next
    td = q_a - jax.lax.stop_gradient(target)
    loss = jnp.square(td).mean()
    return loss, {"td_error_mean": jnp.abs(td).mean(),
                  "q_mean": q_a.mean()}


class DQN(Algorithm):
    module_kind = "q"

    def __init__(self, config):
        if config.num_learners:
            raise ValueError(
                "DQN runs a single (device-mesh) learner: the target tree "
                "rides the batch and cannot be row-sharded across learner "
                "actors")
        super().__init__(config)
        self.buffer = ReplayBuffer(config.replay_buffer_capacity,
                                   seed=config.seed)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.learner_group.get_weights())
        self._last_target_sync = 0

    def _loss_fn(self):
        return functools.partial(dqn_loss, module=self.module)

    def _loss_cfg(self):
        return {"gamma": self.config.gamma,
                "double_q": self.config.double_q}

    def training_step(self) -> dict:
        c = self.config
        params = self.learner_group.get_weights()
        frags = self.env_runner_group.sample(params,
                                             c.rollout_fragment_length)
        for f in frags:
            self.buffer.add_batch(self._replay_rows(f, actions_2d=False))
            self._timesteps += f["rewards"].size
        metrics = {}
        if self._timesteps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iter):
                batch = self.buffer.sample(c.train_batch_size)
                batch["target_params"] = self.target_params
                metrics = self.learner_group.update(batch)
        if (self._timesteps - self._last_target_sync
                >= c.target_network_update_freq):
            self.target_params = self.learner_group.get_weights()
            self._last_target_sync = self._timesteps
        return metrics
