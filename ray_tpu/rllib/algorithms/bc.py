"""BC — behavior cloning from offline (obs, action) data.

Parity: reference `rllib/algorithms/bc/bc.py` (offline RL entry point:
supervised policy learning over recorded episodes, the base of MARWIL).
Offline data arrives as a ray_tpu.data Dataset (or a list of dicts) with
"obs" and "actions" columns — the same shape the reference reads from its
offline JSON/Parquet episode files.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=BC)
        self.input_ = None  # Dataset | list[dict] with obs/actions

    def offline_data(self, *, input_=None, **_compat):
        if input_ is not None:
            self.input_ = input_
        return self


def bc_loss(params, batch, *, module):
    logits, _ = module.forward_train(params, batch["obs"])
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(
        logp, batch["actions"][..., None].astype(jnp.int32), -1)[..., 0]
    loss = -ll.mean()
    return loss, {"neg_logp": loss}


class BC(Algorithm):
    """Supervised: no env sampling; evaluation uses a local env runner."""

    def __init__(self, config):
        if config.input_ is None:
            raise ValueError("BCConfig.offline_data(input_=...) is required")
        config.num_env_runners = 0  # evaluation-only local runner
        super().__init__(config)
        from ray_tpu.rllib.offline import load_offline
        rows = load_offline(config.input_)  # Dataset | rows | path/glob
        if not rows:
            self.stop()  # groups already exist: don't leak their actors
            raise ValueError("offline input is empty")
        self._rows = rows  # materialized ONCE; subclasses read from here
        self._obs = np.asarray([r["obs"] for r in rows], np.float32)
        self._actions = np.asarray([r["actions"] for r in rows], np.int64)
        self._rng = np.random.default_rng(config.seed)

    def _loss_fn(self):
        return functools.partial(bc_loss, module=self.module)

    def _batch(self, sel) -> dict:
        """Minibatch for the learner; subclasses (MARWIL) add columns."""
        return {"obs": self._obs[sel], "actions": self._actions[sel]}

    def training_step(self) -> dict:
        c = self.config
        n = len(self._obs)
        metrics = {}
        for _ in range(c.num_epochs):
            idx = self._rng.permutation(n)
            floor = max(2, c.num_learners or 1)  # every learner needs rows
            for s in range(0, n, c.minibatch_size):
                sel = idx[s:s + c.minibatch_size]
                if len(sel) < floor:
                    continue
                metrics = self.learner_group.update(self._batch(sel))
        self._timesteps += n * c.num_epochs
        return metrics

    def evaluate(self, num_steps: int = 500) -> dict:
        """Roll the cloned policy greedily for a return estimate."""
        self.env_runner_group.sample(self.learner_group.get_weights(),
                                     num_steps)
        return self.env_runner_group.aggregate_metrics()
