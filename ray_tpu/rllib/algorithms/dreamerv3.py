"""DreamerV3 — model-based RL: learn a latent world model, act in dreams.

Parity: reference `rllib/algorithms/dreamerv3/` (RSSM world model +
imagination-trained actor-critic, Hafner et al. 2023). TPU-native
redesign: the whole algorithm is three pure functions — `observe` (RSSM
posterior scan over a replayed fragment + ELBO losses), `imagine` (prior
rollout scan driven by the actor), and one fused jit `update` (world-model
+ actor + critic grads in a single compiled step) — no torch modules, no
per-component training loops. The reference's scale knobs (two-hot symlog
critic, percentile return normalization, KL balancing with free bits,
straight-through categorical latents) are kept; sizes default small
enough to learn toy control on a CPU test box.

Scope vs reference: vector observations use an MLP encoder/decoder (image
encoders ride the same code path via flattening at toy scale); collection
runs a local vectorized gym env inside the algorithm process — the
recurrent acting state (h, z) lives with the env, which the stateless
EnvRunner fragment interface cannot carry.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _mlp_init(key, sizes, scale=None):
    params = []
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        s = scale if scale is not None else 1.0 / np.sqrt(sizes[i])
        params.append({
            "w": jax.random.uniform(k, (sizes[i], sizes[i + 1]),
                                    jnp.float32, -s, s),
            "b": jnp.zeros((sizes[i + 1],)),
        })
    return params


def _mlp(params, x, act=jax.nn.silu, final_act=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or final_act:
            x = act(x)
    return x


@dataclasses.dataclass(frozen=True)
class RSSMSpec:
    """Sizes of the recurrent state-space model."""

    obs_dim: int
    num_actions: int
    deter: int = 256
    classes: int = 16   # categorical latents: `groups` x `classes`
    groups: int = 16
    hidden: int = 256
    discrete_actions: bool = True

    @property
    def stoch(self) -> int:
        return self.classes * self.groups

    @property
    def feat(self) -> int:
        return self.deter + self.stoch

    def init_params(self, key) -> dict:
        ks = jax.random.split(key, 12)
        d, s, hdn = self.deter, self.stoch, self.hidden
        return {
            "encoder": _mlp_init(ks[0], (self.obs_dim, hdn, hdn)),
            # GRU over [z, a] -> deter: one fused 3-gate matmul per input.
            "gru_in": _mlp_init(ks[1], (s + self.num_actions, 3 * d))[0],
            "gru_h": _mlp_init(ks[2], (d, 3 * d))[0],
            "prior": _mlp_init(ks[4], (d, hdn, s)),
            "post": _mlp_init(ks[5], (d + hdn, hdn, s)),
            "decoder": _mlp_init(ks[6], (self.feat, hdn, hdn,
                                         self.obs_dim)),
            "reward": _mlp_init(ks[7], (self.feat, hdn, 1), scale=1e-4),
            "cont": _mlp_init(ks[8], (self.feat, hdn, 1)),
            "actor": _mlp_init(ks[9], (self.feat, hdn, self.num_actions),
                               scale=0.01),
            "critic": _mlp_init(ks[10], (self.feat, hdn, 1), scale=1e-4),
        }

    # ---- RSSM cells ----

    def _gru(self, p, h, x):
        gi = x @ p["gru_in"]["w"] + p["gru_in"]["b"]
        gh = h @ p["gru_h"]["w"] + p["gru_h"]["b"]
        r = jax.nn.sigmoid(gi[..., :self.deter] + gh[..., :self.deter])
        u = jax.nn.sigmoid(
            gi[..., self.deter:2 * self.deter]
            + gh[..., self.deter:2 * self.deter])
        cand = jnp.tanh(gi[..., 2 * self.deter:]
                        + r * gh[..., 2 * self.deter:])
        return u * cand + (1 - u) * h

    def _unimix(self, logits):
        """1% uniform-mixed grouped log-probs (the DreamerV3 trick that
        prevents deterministic collapse). Sampling AND the KL terms both
        use this distribution — training the KL on the raw logits would
        let them saturate while the sampled distribution differs."""
        shp = logits.shape[:-1] + (self.groups, self.classes)
        probs = 0.99 * jax.nn.softmax(logits.reshape(shp)) \
            + 0.01 / self.classes
        return jnp.log(probs)

    def _sample_latent(self, mixed_lg, key):
        """Straight-through one-hot categorical from mixed log-probs
        [.., groups, classes]."""
        idx = jax.random.categorical(key, mixed_lg)
        one = jax.nn.one_hot(idx, self.classes, dtype=mixed_lg.dtype)
        probs = jnp.exp(mixed_lg)
        one = one + probs - jax.lax.stop_gradient(probs)  # straight-through
        return one.reshape(mixed_lg.shape[:-2] + (self.stoch,))

    def obs_step(self, p, h, z, a, embed, is_first, key):
        """One posterior step. All of [B, ...]. Returns unimixed grouped
        log-probs for both distributions (KL-ready)."""
        mask = 1.0 - is_first[..., None]
        h = h * mask
        z = z * mask
        a = a * mask
        x = jnp.concatenate([z, a], -1)
        h = self._gru(p, h, x)
        prior_lg = self._unimix(_mlp(p["prior"], h))
        post_in = jnp.concatenate([h, embed], -1)
        post_lg = self._unimix(_mlp(p["post"], post_in))
        z = self._sample_latent(post_lg, key)
        return h, z, prior_lg, post_lg

    def img_step(self, p, h, z, a, key):
        x = jnp.concatenate([z, a], -1)
        h = self._gru(p, h, x)
        prior_lg = self._unimix(_mlp(p["prior"], h))
        z = self._sample_latent(prior_lg, key)
        return h, z

    def _kl(self, lhs_lg, rhs_lg):
        """KL(lhs || rhs) over grouped-categorical log-probs, summed."""
        return (jnp.exp(lhs_lg) * (lhs_lg - rhs_lg)).sum(-1).sum(-1)


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DreamerV3)
        self.batch_size_B = 8          # replayed fragments per update
        self.batch_length_T = 32       # fragment length
        self.horizon_H = 10            # imagination horizon
        self.model_size = {"deter": 256, "hidden": 256,
                           "classes": 16, "groups": 16}
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.entropy_scale = 3e-3
        self.free_bits = 1.0
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.critic_ema_decay = 0.98
        self.replay_capacity = 500     # fragments
        self.num_updates_per_iter = 8
        self.num_envs = 8
        self.lr = 4e-4
        self.actor_critic_lr = 1e-4

    def training(self, *, batch_size_B=None, batch_length_T=None,
                 horizon_H=None, model_size=None, entropy_scale=None,
                 num_updates_per_iter=None, replay_capacity=None,
                 actor_critic_lr=None, **kw):
        super().training(**kw)
        for k, v in (("batch_size_B", batch_size_B),
                     ("batch_length_T", batch_length_T),
                     ("horizon_H", horizon_H), ("model_size", model_size),
                     ("entropy_scale", entropy_scale),
                     ("num_updates_per_iter", num_updates_per_iter),
                     ("replay_capacity", replay_capacity),
                     ("actor_critic_lr", actor_critic_lr)):
            if v is not None:
                setattr(self, k, v)
        return self


class DreamerV3:
    """Self-contained: owns the vector env (recurrent acting state rides
    with it), the fragment replay, and one fused jit update."""

    def __init__(self, config: DreamerV3Config):
        import gymnasium as gym

        from ray_tpu.rllib.env.minatar import register_builtin_envs
        register_builtin_envs()
        self.config = config
        c = config
        # SAME_STEP autoreset: on done, step() returns the RESET obs (the
        # gym<1.0 behavior). The default NEXT_STEP mode inserts a phantom
        # transition at every episode boundary (terminal obs recorded as
        # the new episode's first obs, with an ignored action and reward
        # 0) — which would corrupt every boundary in the replay.
        try:
            vector_kwargs = {
                "autoreset_mode": gym.vector.AutoresetMode.SAME_STEP}
            self.env = gym.make_vec(c.env, num_envs=c.num_envs,
                                    vectorization_mode="sync",
                                    vector_kwargs=vector_kwargs,
                                    **(c.env_config or {}))
        except (AttributeError, TypeError):  # older gymnasium
            self.env = gym.make_vec(c.env, num_envs=c.num_envs,
                                    vectorization_mode="sync",
                                    **(c.env_config or {}))
        obs_dim = int(np.prod(self.env.single_observation_space.shape))
        num_actions = int(self.env.single_action_space.n)
        ms = c.model_size
        self.spec = RSSMSpec(obs_dim=obs_dim, num_actions=num_actions,
                             deter=ms["deter"], hidden=ms["hidden"],
                             classes=ms["classes"], groups=ms["groups"])
        self._key = jax.random.PRNGKey(c.seed)
        self._key, k = jax.random.split(self._key)
        self.params = self.spec.init_params(k)
        self.critic_ema = jax.tree_util.tree_map(
            lambda x: x, self.params["critic"])
        clip = optax.clip_by_global_norm(c.grad_clip or 100.0)
        self.wm_tx = optax.chain(clip, optax.adamw(c.lr))
        self.ac_tx = optax.chain(clip, optax.adamw(c.actor_critic_lr))
        wm_params = {k: v for k, v in self.params.items()
                     if k not in ("actor", "critic")}
        self.wm_opt = self.wm_tx.init(wm_params)
        self.ac_opt = self.ac_tx.init({"actor": self.params["actor"],
                                       "critic": self.params["critic"]})
        # Return-normalization EMA of the 5th..95th percentile range.
        self.retnorm = jnp.ones(())

        obs, _ = self.env.reset(seed=c.seed)
        self._obs = self._flat(obs)
        E = c.num_envs
        self._h = np.zeros((E, self.spec.deter), np.float32)
        self._z = np.zeros((E, self.spec.stoch), np.float32)
        self._a = np.zeros((E, num_actions), np.float32)
        self._is_first = np.ones((E,), np.float32)
        self._ep_ret = np.zeros(E)
        self.completed_returns: list[float] = []
        self.buffer: list[dict] = []
        self.iteration = 0
        self._timesteps = 0

        self._act = jax.jit(self._act_fn)
        self._update = jax.jit(self._update_fn)

    # ---------------- acting ----------------

    @staticmethod
    def _flat(obs):
        return np.asarray(obs, np.float32).reshape(len(obs), -1)

    def _act_fn(self, params, h, z, a, obs, is_first, key):
        k1, k2 = jax.random.split(key)
        embed = _mlp(params["encoder"], symlog(obs), final_act=True)
        h, z, _, _ = self.spec.obs_step(params, h, z, a, embed,
                                        is_first, k1)
        feat = jnp.concatenate([h, z], -1)
        logits = _mlp(params["actor"], feat)
        action = jax.random.categorical(k2, logits)
        return h, z, action

    def _collect(self, steps: int) -> dict:
        """Step the vector env `steps` times; returns the fragment
        [T, E, ...] and pushes per-env fragments into the replay."""
        c = self.config
        E = c.num_envs
        T = steps
        frag = {
            "obs": np.empty((T, E, self.spec.obs_dim), np.float32),
            "action": np.empty((T, E), np.int64),
            "reward": np.zeros((T, E), np.float32),
            "cont": np.ones((T, E), np.float32),
            "is_first": np.zeros((T, E), np.float32),
        }
        for t in range(T):
            self._key, k = jax.random.split(self._key)
            h, z, action = self._act(self.params, self._h, self._z,
                                     self._a, self._obs, self._is_first, k)
            action = np.asarray(action)
            frag["obs"][t] = self._obs
            frag["is_first"][t] = self._is_first
            frag["action"][t] = action
            obs, rew, term, trunc, _ = self.env.step(action)
            done = np.logical_or(term, trunc)
            frag["reward"][t] = rew
            frag["cont"][t] = 1.0 - np.asarray(term, np.float32)
            self._ep_ret += rew
            for i in np.flatnonzero(done):
                self.completed_returns.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._h, self._z = np.asarray(h), np.asarray(z)
            self._a = np.eye(self.spec.num_actions,
                             dtype=np.float32)[action]
            self._is_first = np.asarray(done, np.float32)
            self._obs = self._flat(obs)
            self._timesteps += E
        for e in range(E):
            self.buffer.append({k: v[:, e] for k, v in frag.items()})
        if len(self.buffer) > c.replay_capacity:
            del self.buffer[:len(self.buffer) - c.replay_capacity]
        return frag

    # ---------------- the fused update ----------------

    def _observe(self, params, batch, key):
        """RSSM posterior scan over [B, T, ...]; returns losses + feats."""
        spec, c = self.spec, self.config
        B, T = batch["obs"].shape[:2]
        embed = _mlp(params["encoder"], symlog(batch["obs"]),
                     final_act=True)
        a_onehot = jax.nn.one_hot(batch["action"], spec.num_actions)
        # Previous action enters each step (shifted by one).
        a_prev = jnp.concatenate(
            [jnp.zeros_like(a_onehot[:, :1]), a_onehot[:, :-1]], 1)

        def step(carry, xs):
            h, z, key = carry
            emb_t, a_t, first_t = xs
            key, k = jax.random.split(key)
            h, z, prior_lg, post_lg = spec.obs_step(
                params, h, z, a_t, emb_t, first_t, k)
            return (h, z, key), (h, z, prior_lg, post_lg)

        init = (jnp.zeros((B, spec.deter)), jnp.zeros((B, spec.stoch)),
                key)
        xs = (embed.transpose(1, 0, 2), a_prev.transpose(1, 0, 2),
              batch["is_first"].transpose(1, 0))
        _, (hs, zs, prior_lg, post_lg) = jax.lax.scan(step, init, xs)
        # [T, B, ...] -> [B, T, ...]
        hs, zs = hs.transpose(1, 0, 2), zs.transpose(1, 0, 2)
        prior_lg = prior_lg.transpose(1, 0, 2, 3)  # [B, T, groups, classes]
        post_lg = post_lg.transpose(1, 0, 2, 3)
        feat = jnp.concatenate([hs, zs], -1)

        recon = _mlp(params["decoder"], feat)
        rew_pred = _mlp(params["reward"], feat)[..., 0]
        cont_pred = _mlp(params["cont"], feat)[..., 0]
        recon_loss = jnp.square(recon - symlog(batch["obs"])).sum(-1)
        rew_loss = jnp.square(rew_pred - symlog(batch["reward"]))
        cont_loss = optax.sigmoid_binary_cross_entropy(
            cont_pred, batch["cont"])
        dyn = jnp.maximum(c.free_bits, spec._kl(
            jax.lax.stop_gradient(post_lg), prior_lg))
        rep = jnp.maximum(c.free_bits, spec._kl(
            post_lg, jax.lax.stop_gradient(prior_lg)))
        wm_loss = (recon_loss + rew_loss + cont_loss
                   + c.kl_dyn_scale * dyn + c.kl_rep_scale * rep).mean()
        metrics = {"recon_loss": recon_loss.mean(),
                   "reward_loss": rew_loss.mean(),
                   "continue_loss": cont_loss.mean(),
                   "kl": dyn.mean()}
        return wm_loss, (feat, metrics)

    def _imagine(self, params, start_feat, key):
        """Actor-driven prior rollout from (flattened) posterior states."""
        spec, c = self.spec, self.config
        N = start_feat.shape[0]
        h = start_feat[:, :spec.deter]
        z = start_feat[:, spec.deter:]

        def step(carry, _):
            h, z, key = carry
            key, ka, kz = jax.random.split(key, 3)
            feat = jnp.concatenate([h, z], -1)
            logits = _mlp(params["actor"], feat)
            a = jax.random.categorical(ka, logits)
            logp = jax.nn.log_softmax(logits)
            ent = -(jnp.exp(logp) * logp).sum(-1)
            logp_a = jnp.take_along_axis(logp, a[:, None], -1)[:, 0]
            a1 = jax.nn.one_hot(a, spec.num_actions)
            h, z = spec.img_step(params, h, z, a1, kz)
            return (h, z, key), (feat, logp_a, ent)

        (_h, _z, _k), (feats, logp, ent) = jax.lax.scan(
            step, (h, z, key), None, length=c.horizon_H)
        last = jnp.concatenate([_h, _z], -1)
        return feats, logp, ent, last  # feats [H, N, F]

    def _update_fn(self, params, critic_ema, wm_opt, ac_opt, retnorm,
                   batch, key):
        spec, c = self.spec, self.config
        k_wm, k_img = jax.random.split(key)

        # ---- world model ----
        def wm_loss_fn(wm_params):
            full = {**wm_params, "actor": params["actor"],
                    "critic": params["critic"]}
            return self._observe(full, batch, k_wm)

        wm_params = {k: v for k, v in params.items()
                     if k not in ("actor", "critic")}
        (wm_loss, (feat, wm_metrics)), wm_grads = jax.value_and_grad(
            wm_loss_fn, has_aux=True)(wm_params)
        upd, wm_opt = self.wm_tx.update(wm_grads, wm_opt, wm_params)
        wm_params = optax.apply_updates(wm_params, upd)
        params = {**wm_params, "actor": params["actor"],
                  "critic": params["critic"]}

        # ---- imagination rollout (world model frozen) ----
        start = jax.lax.stop_gradient(
            feat.reshape(-1, spec.feat))

        def ac_loss_fn(ac):
            full = {**wm_params, **ac}
            feats, logp, ent, last = self._imagine(full, start, k_img)
            rew = symexp(_mlp(full["reward"], feats)[..., 0])
            cont = jax.nn.sigmoid(_mlp(full["cont"], feats)[..., 0])
            disc = c.gamma * cont
            # The critic PREDICTS in symlog space; everything downstream
            # (bootstrap, advantage) works in raw-return space.
            value_sym = _mlp(full["critic"], feats)[..., 0]
            value = symexp(value_sym)
            last_v = symexp(_mlp(full["critic"], last)[..., 0])
            values = jnp.concatenate([value, last_v[None]], 0)
            # lambda-returns (time-reversed scan).
            def lam_step(nxt, xs):
                r, d, v_next = xs
                ret = r + d * ((1 - c.gae_lambda) * v_next
                               + c.gae_lambda * nxt)
                return ret, ret
            _, rets = jax.lax.scan(
                lam_step, values[-1],
                (rew, disc, values[1:]), reverse=True)
            rets = jax.lax.stop_gradient(rets)
            # Percentile return normalization (EMA of the 5-95 range).
            lo, hi = jnp.percentile(rets, 5.0), jnp.percentile(rets, 95.0)
            scale = jnp.maximum(1.0, hi - lo)
            adv = (rets - value) / jax.lax.stop_gradient(
                jnp.maximum(retnorm, scale))
            weight = jnp.cumprod(
                jnp.concatenate([jnp.ones_like(disc[:1]), disc[:-1]], 0),
                0)
            actor_loss = -(weight * (
                logp * jax.lax.stop_gradient(adv)
                + c.entropy_scale * ent)).mean()
            v_ema_sym = _mlp(critic_ema, feats)[..., 0]
            critic_loss = (weight * (
                jnp.square(value_sym - symlog(rets))
                + 0.3 * jnp.square(
                    value_sym - jax.lax.stop_gradient(v_ema_sym))
            )).mean()
            aux = {"actor_loss": actor_loss, "critic_loss": critic_loss,
                   "actor_entropy": ent.mean(), "scale": scale,
                   "imagined_return": rets.mean()}
            return actor_loss + critic_loss, aux

        ac = {"actor": params["actor"], "critic": params["critic"]}
        (ac_loss, aux), ac_grads = jax.value_and_grad(
            ac_loss_fn, has_aux=True)(ac)
        upd, ac_opt = self.ac_tx.update(ac_grads, ac_opt, ac)
        ac = optax.apply_updates(ac, upd)
        params = {**wm_params, **ac}
        critic_ema = jax.tree_util.tree_map(
            lambda e, p: c.critic_ema_decay * e
            + (1 - c.critic_ema_decay) * p, critic_ema, ac["critic"])
        retnorm = 0.99 * retnorm + 0.01 * aux.pop("scale")
        metrics = {**wm_metrics, **aux, "world_model_loss": wm_loss}
        return params, critic_ema, wm_opt, ac_opt, retnorm, metrics

    # ---------------- driver API ----------------

    def training_step(self) -> dict:
        c = self.config
        self._collect(c.batch_length_T)
        metrics = {}
        rng = np.random.default_rng(c.seed + self.iteration)
        for _ in range(c.num_updates_per_iter):
            if len(self.buffer) < c.batch_size_B:
                break
            self._key, ku = jax.random.split(self._key)
            idx = rng.integers(0, len(self.buffer), c.batch_size_B)
            # One host->device transfer per key (per-fragment jnp.stack
            # would do B tiny transfers each).
            batch = {
                k: jnp.asarray(np.stack([self.buffer[i][k] for i in idx]))
                for k in ("obs", "action", "reward", "cont", "is_first")}
            (self.params, self.critic_ema, self.wm_opt, self.ac_opt,
             self.retnorm, metrics) = self._update(
                self.params, self.critic_ema, self.wm_opt, self.ac_opt,
                self.retnorm, batch, ku)
        return {k: float(v) for k, v in metrics.items()}

    def train(self) -> dict:
        t0 = time.perf_counter()
        self.iteration += 1
        result = self.training_step()
        rets = self.completed_returns[-50:]
        if rets:
            result["episode_return_mean"] = float(np.mean(rets))
        result.update({
            "training_iteration": self.iteration,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
        })
        return result

    def get_weights(self):
        return self.params

    def stop(self):
        self.env.close()
