"""CQL — conservative Q-learning for offline continuous control.

Parity: reference `rllib/algorithms/cql/cql.py` (SAC trained purely from a
recorded dataset, with the CQL(H) conservative regularizer pushing Q down
on out-of-distribution actions so the policy cannot exploit extrapolation
error). TPU-native like SAC: the whole update — conservative critic, actor,
temperature, polyak — is ONE jit; the OOD action sampling (uniform +
current-policy) happens inside the same compiled program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.offline import load_offline, rows_to_arrays


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CQL
        self.input_ = None            # rows | Dataset | path/glob
        self.cql_alpha = 1.0          # conservative penalty weight
        self.num_ood_actions = 4      # sampled actions per state
        self.bc_iters = 0             # optional BC warmup iterations

    def offline_data(self, *, input_=None, **_compat):
        if input_ is not None:
            self.input_ = input_
        return self

    def training(self, *, cql_alpha=None, num_ood_actions=None,
                 bc_iters=None, **kw):
        super().training(**kw)
        if cql_alpha is not None:
            self.cql_alpha = cql_alpha
        if num_ood_actions is not None:
            self.num_ood_actions = num_ood_actions
        if bc_iters is not None:
            self.bc_iters = bc_iters
        return self


class CQL(SAC):
    """SAC machinery + conservative critic, trained from offline data only
    (no env sampling; the env is used for evaluation rollouts)."""

    def __init__(self, config):
        if config.input_ is None:
            raise ValueError("CQLConfig.offline_data(input_=...) is required")
        super().__init__(config)
        rows = load_offline(config.input_)
        if not rows:
            self.stop()
            raise ValueError("offline input is empty")
        self._data = rows_to_arrays(rows, continuous=True)
        if "next_obs" not in self._data:
            self.stop()
            raise ValueError("CQL needs next_obs in the offline data")
        self._rebuild_update()

    def _rebuild_update(self):
        """Replace SAC's fused update with the conservative variant."""
        c = self.config
        m = self.module
        gamma, tau, tgt_ent = c.gamma, c.tau, self.target_entropy
        n_ood = int(c.num_ood_actions)
        cql_alpha = float(c.cql_alpha)
        low = jnp.asarray(m.low)
        high = jnp.asarray(m.high)

        def update(params, target_params, opt_state, log_alpha,
                   alpha_opt_state, batch, key, *, bc_mode=False):
            k1, k2, k3, k4 = jax.random.split(key, 4)
            alpha = jnp.exp(log_alpha)
            B = batch["obs"].shape[0]

            next_a, next_logp = m.sample(params, batch["next_obs"], k1)
            tq1, tq2 = m.q_values(target_params, batch["next_obs"], next_a)
            tq = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * tq
            target = jax.lax.stop_gradient(target)

            def critic_loss(p):
                q1, q2 = m.q_values(p, batch["obs"], batch["actions"])
                bellman = (jnp.square(q1 - target).mean()
                           + jnp.square(q2 - target).mean())
                # CQL(H): push down logsumexp Q over sampled actions, push
                # up Q on dataset actions. Samples: uniform-random actions
                # (importance weight = volume) + current-policy actions.
                ks = jax.random.split(k3, n_ood)
                rand_a = jax.random.uniform(
                    k2, (n_ood, B, m.action_dim),
                    minval=low, maxval=high)
                pol = [m.sample(jax.lax.stop_gradient(p), batch["obs"], kk)
                       for kk in ks]
                pol_a = jnp.stack([a for a, _ in pol])
                pol_logp = jnp.stack([lp for _, lp in pol])

                def q_on(p_, acts):
                    qa1, qa2 = jax.vmap(
                        lambda a_: m.q_values(p_, batch["obs"], a_))(acts)
                    return qa1, qa2

                r1, r2 = q_on(p, rand_a)
                p1, p2 = q_on(p, pol_a)
                log_vol = jnp.log(high - low).sum()
                cat1 = jnp.concatenate([r1 + log_vol, p1 - pol_logp], 0)
                cat2 = jnp.concatenate([r2 + log_vol, p2 - pol_logp], 0)
                cql1 = (jax.nn.logsumexp(cat1, axis=0) - q1).mean()
                cql2 = (jax.nn.logsumexp(cat2, axis=0) - q2).mean()
                return bellman + cql_alpha * (cql1 + cql2), bellman

            def actor_loss(p):
                a, logp = m.sample(p, batch["obs"], k4)
                if bc_mode:
                    # BC warmup (bc_iters): clone dataset actions before
                    # trusting the conservative critic.
                    lp_data = m.log_prob(p, batch["obs"], batch["actions"])
                    return (alpha * logp - lp_data).mean(), logp
                q1, q2 = m.q_values(jax.lax.stop_gradient(p),
                                    batch["obs"], a)
                return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

            (closs, bellman), cgrads = jax.value_and_grad(
                critic_loss, has_aux=True)(params)
            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(params)
            grads = jax.tree_util.tree_map(lambda a_, b_: a_ + b_,
                                           cgrads, agrads)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            def alpha_loss(la):
                return (-jnp.exp(la)
                        * (jax.lax.stop_gradient(logp) + tgt_ent)).mean()

            al, agrad = jax.value_and_grad(alpha_loss)(log_alpha)
            aupd, alpha_opt_state = self.alpha_tx.update(
                agrad, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, aupd)

            target_params = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, target_params, params)
            aux = {"critic_loss": closs, "bellman_loss": bellman,
                   "actor_loss": aloss, "alpha": jnp.exp(log_alpha),
                   "entropy": -logp.mean()}
            return (params, target_params, opt_state, log_alpha,
                    alpha_opt_state, aux)

        import functools
        self._update = jax.jit(functools.partial(update, bc_mode=False))
        self._update_bc = (jax.jit(functools.partial(update, bc_mode=True))
                           if c.bc_iters else None)

    def training_step(self) -> dict:
        c = self.config
        n = len(self._data["obs"])
        rng = np.random.default_rng(c.seed + self.iteration)
        metrics = {}
        # train() bumps iteration before calling us: 1-based
        step = (self._update_bc
                if self._update_bc is not None and self.iteration <= c.bc_iters
                else self._update)
        for _ in range(c.num_updates_per_iter):
            sel = rng.integers(0, n, size=c.train_batch_size)
            batch = {k: jnp.asarray(v[sel]) for k, v in self._data.items()}
            self._key, sub = jax.random.split(self._key)
            (self.params, self.target_params, self.opt_state,
             self.log_alpha, self.alpha_opt_state, aux) = step(
                self.params, self.target_params, self.opt_state,
                self.log_alpha, self.alpha_opt_state, batch, sub)
            metrics = {k: float(v) for k, v in aux.items()}
        self._timesteps += c.num_updates_per_iter * c.train_batch_size
        return metrics

    def evaluate(self, num_steps: int = 500) -> dict:
        self.env_runner_group.sample(self.params, num_steps)
        return self.env_runner_group.aggregate_metrics()
