"""IMPALA — asynchronous actor-learner with V-trace off-policy correction.

Parity: reference `rllib/algorithms/impala/impala.py:599` (async sample
queues feeding GPU learners). TPU-native: each env runner keeps exactly one
sample request in flight (the queue is the object plane itself — refs are
futures); the learner consumes fragments as they land and V-trace
(importance-weighted value targets, Espeholt et al. 2018) is a jitted
`lax.scan` like PPO's GAE.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_pg_rho_threshold = 1.0
        self.num_env_runners = 2  # async needs remote runners
        self.broadcast_interval = 1  # updates between weight broadcasts

    def training(self, *, vf_loss_coeff=None, entropy_coeff=None,
                 clip_rho_threshold=None, clip_pg_rho_threshold=None,
                 broadcast_interval=None, **kw):
        super().training(**kw)
        for k, v in (("vf_loss_coeff", vf_loss_coeff),
                     ("entropy_coeff", entropy_coeff),
                     ("clip_rho_threshold", clip_rho_threshold),
                     ("clip_pg_rho_threshold", clip_pg_rho_threshold),
                     ("broadcast_interval", broadcast_interval)):
            if v is not None:
                setattr(self, k, v)
        return self


def _vtrace_core(behavior_logp, target_logp, rewards, values, dones,
                 last_values, *, gamma, rho_bar=1.0, c_bar=1.0):
    """V-trace targets/advantages over [T, B] (lax.scan, time-reversed).
    Pure (traceable inside other jits — the on-device Anakin path)."""
    rho = jnp.exp(target_logp - behavior_logp)
    rho_c = jnp.minimum(rho_bar, rho)
    c = jnp.minimum(c_bar, rho)
    v_next = jnp.concatenate([values[1:], last_values[None]], axis=0)
    deltas = rho_c * (rewards + gamma * v_next * (1.0 - dones) - values)

    def step(carry, xs):
        delta, c_t, d = xs
        acc = delta + gamma * (1.0 - d) * c_t * carry
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step, jnp.zeros_like(last_values), (deltas, c, dones), reverse=True)
    vs = vs_minus_v + values
    vs_next = jnp.concatenate([vs[1:], last_values[None]], axis=0)
    pg_adv = rho_c * (rewards + gamma * vs_next * (1.0 - dones) - values)
    return vs, pg_adv


_vtrace = jax.jit(_vtrace_core,
                  static_argnames=("gamma", "rho_bar", "c_bar"))


def impala_loss(params, batch, *, module, vf_coef, ent_coef):
    logits, value = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), -1)[..., 0]
    pi_loss = -(batch["pg_advantages"] * logp).mean()
    vf_loss = jnp.square(value - batch["vs"]).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    total = pi_loss + vf_coef * vf_loss - ent_coef * entropy
    return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


class IMPALA(Algorithm):
    supports_ondevice_env = True  # Anakin-style (core/ondevice.py)

    def __init__(self, config):
        from ray_tpu.rllib.env.jax_env import is_jax_env
        if config.num_env_runners < 1 and not is_jax_env(config.env):
            raise ValueError("IMPALA needs remote env runners (async) "
                             "or a jax-native env (on-device Anakin)")
        super().__init__(config)
        self._inflight: dict = {}  # ref -> runner index
        self._target_logp = jax.jit(
            lambda p, obs, act: jnp.take_along_axis(
                jax.nn.log_softmax(self.module.forward(p, obs)[0]),
                act[..., None].astype(jnp.int32), -1)[..., 0])
        self._updates_since_broadcast = 0
        self._params_ref = None
        self._behavior_params = None  # on-device path: stale actor tree

    def _loss_fn(self):
        return functools.partial(impala_loss, module=self.module)

    def _loss_cfg(self):
        c = self.config
        return {"vf_coef": c.vf_loss_coeff, "ent_coef": c.entropy_coeff}

    def _make_batch(self, f, vs, pg_adv) -> dict:
        T, B = f["rewards"].shape
        return {
            "obs": f["obs"].reshape(T * B, -1),
            "actions": f["actions"].reshape(-1),
            "vs": np.asarray(vs).reshape(-1),
            "pg_advantages": np.asarray(pg_adv).reshape(-1),
        }

    def _broadcast(self):
        self._params_ref = ray_tpu.put(self.learner_group.get_weights())

    def _launch(self, idx: int):
        runner = self.env_runner_group.remotes[idx]
        ref = runner.sample.remote(self._params_ref,
                                   self.config.rollout_fragment_length)
        self._inflight[ref] = idx

    def _training_step_ondevice(self) -> dict:
        """Anakin/Podracer IMPALA: on-device envs act with a behavior
        tree the host refreshes every broadcast_interval iterations;
        rollout + learner forward + V-trace + the minibatch pass compile
        into one dispatch (core/ondevice.py build_impala_train_iter)."""
        import time as _time

        import jax as _jax

        c = self.config
        learner = self.learner_group.local
        if learner is None:
            raise ValueError("on-device IMPALA uses a local learner "
                             "(num_learners=0)")
        if self._ondev_iter is None:
            from ray_tpu.rllib.core.ondevice import build_impala_train_iter
            B = self._jax_vec_env.num_envs
            T = max(1, c.train_batch_size // B)
            self._ondev_iter = build_impala_train_iter(
                self._jax_vec_env, self.module, T=T,
                minibatch_size=min(c.minibatch_size, T * B),
                gamma=c.gamma, rho_bar=c.clip_rho_threshold,
                c_bar=c.clip_pg_rho_threshold, vf_coef=c.vf_loss_coeff,
                ent_coef=c.entropy_coeff, tx=learner.tx)
            self._ondev_T = T
            self._ondev_vs = self._jax_vec_env.reset(
                _jax.random.PRNGKey(c.seed or 0))
            self._ondev_key = _jax.random.PRNGKey((c.seed or 0) + 1)
            self._behavior_params = learner.params
        _t0 = _time.perf_counter()
        (learner.params, learner.opt_state, self._ondev_vs,
         self._ondev_key, m) = self._ondev_iter(
            learner.params, self._behavior_params, learner.opt_state,
            self._ondev_vs, self._ondev_key)
        self._updates_since_broadcast += 1
        if self._updates_since_broadcast >= c.broadcast_interval:
            self._behavior_params = learner.params
            self._updates_since_broadcast = 0
        m = {k: float(v) for k, v in _jax.device_get(m).items()}
        dt_ms = (_time.perf_counter() - _t0) * 1e3
        steps = self._ondev_T * self._jax_vec_env.num_envs
        self._timesteps += steps
        self.env_runner_group.record(
            m.pop("ep_ret_sum"), m.pop("ep_len_sum"), m.pop("ep_count"))
        m["learner_update_ms"] = round(dt_ms, 1)
        m["sample_ms"] = 0.0
        return m

    def training_step(self) -> dict:
        if self._jax_vec_env is not None:
            return self._training_step_ondevice()
        c = self.config
        if self._params_ref is None:
            self._broadcast()
            for i in range(len(self.env_runner_group.remotes)):
                self._launch(i)
        metrics = {}

        def live_params():
            # Target-logp wants the freshest params; with a local learner
            # use its device tree directly (no device->host round trip —
            # get_weights() would copy the full tree per fragment).
            if self.learner_group.local is not None:
                return self.learner_group.local.params
            return self.learner_group.get_weights()

        params = live_params()
        steps = 0
        while steps < c.train_batch_size:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=120)
            if not ready:
                raise TimeoutError("no sample fragment arrived in 120s")
            ref = ready[0]
            idx = self._inflight.pop(ref)
            try:
                f = ray_tpu.get(ref, timeout=60)
            except ray_tpu.RayTpuError:
                self.env_runner_group._replace(idx)
                self._launch(idx)
                continue
            # Relaunch immediately: the runner never waits on the learner.
            self._launch(idx)
            target_logp = self._target_logp(
                params, jnp.asarray(f["obs"]), jnp.asarray(f["actions"]))
            vs, pg_adv = _vtrace(
                jnp.asarray(f["logp"]), target_logp,
                jnp.asarray(f["rewards"]), jnp.asarray(f["values"]),
                jnp.asarray(f["dones"]), jnp.asarray(f["last_values"]),
                gamma=c.gamma, rho_bar=c.clip_rho_threshold,
                c_bar=c.clip_pg_rho_threshold)
            T, B = f["rewards"].shape
            batch = self._make_batch(f, vs, pg_adv)
            metrics = self.learner_group.update(batch)
            params = live_params()
            steps += T * B
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= c.broadcast_interval:
                self._broadcast()
                self._updates_since_broadcast = 0
        self._timesteps += steps
        return metrics
