"""AlgorithmConfig: fluent builder for RL algorithms.

Parity: reference `rllib/algorithms/algorithm_config.py` (the
`.environment().env_runners().training().learners()` chain). Only the
jax framework exists here — there is no `.framework()` switch; learners are
jit-compiled JAX (the reference's torch/tf2 twin stacks collapse into one).
"""

from __future__ import annotations

import copy


class AlgorithmConfig:
    def __init__(self, algo_class=None):
        self.algo_class = algo_class
        # environment
        self.env: str | None = None
        self.env_config: dict = {}
        # env runners
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 128
        self.restart_failed_env_runners: bool = True
        # training (common)
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 512
        self.minibatch_size: int = 128
        self.num_epochs: int = 4
        self.grad_clip: float | None = 0.5
        self.model: dict = {"hidden": (64, 64)}
        # learners
        self.num_learners: int = 0
        # debugging
        self.seed: int = 0
        # algo-specific keys land via .training(**kwargs)
        self._extra: dict = {}

    # ---- fluent sections (each returns self) ----

    def environment(self, env=None, *, env_config=None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None,
                    restart_failed_env_runners=None, **_compat):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if restart_failed_env_runners is not None:
            self.restart_failed_env_runners = restart_failed_env_runners
        return self

    def training(self, *, lr=None, gamma=None, train_batch_size=None,
                 minibatch_size=None, num_epochs=None, grad_clip=None,
                 model=None, **algo_specific):
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if minibatch_size is not None:
            self.minibatch_size = minibatch_size
        if num_epochs is not None:
            self.num_epochs = num_epochs
        if grad_clip is not None:
            self.grad_clip = grad_clip
        if model is not None:
            self.model.update(model)
        self._extra.update(algo_specific)
        return self

    def learners(self, *, num_learners=None, **_compat):
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def debugging(self, *, seed=None, **_compat):
        if seed is not None:
            self.seed = seed
        return self

    def framework(self, *_a, **_k):  # parity shim: jax-only stack
        return self

    def resources(self, **_compat):  # parity shim
        return self

    def __getattr__(self, name):
        extra = self.__dict__.get("_extra")
        if extra is not None and name in extra:
            return extra[name]
        raise AttributeError(name)

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items()
             if k not in ("algo_class", "_extra")}
        d.update(self._extra)
        return d

    def build_algo(self):
        if self.algo_class is None:
            raise ValueError("config has no algo_class bound")
        return self.algo_class(self)

    build = build_algo  # parity alias
