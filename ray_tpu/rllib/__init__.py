"""ray_tpu.rllib: reinforcement learning at scale, jax-first.

Parity map to the reference's `rllib/` (new API stack only — the old
policy/rollout-worker stack is intentionally not reproduced):
- RLModule (core/rl_module.py)  <- rllib/core/rl_module/rl_module.py:260
- Learner/LearnerGroup (core/learner.py) <- rllib/core/learner/
- EnvRunner/Group (env/env_runner.py) <- rllib/env/single_agent_env_runner.py:68
- AlgorithmConfig/Algorithm (algorithms/) <- rllib/algorithms/
- PPO, DQN, IMPALA <- rllib/algorithms/{ppo,dqn,impala}/
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "APPO", "APPOConfig",
    "BC", "BCConfig", "DQN", "DQNConfig", "IMPALA", "IMPALAConfig",
    "MARWIL", "MARWILConfig", "SAC", "SACConfig",
]
