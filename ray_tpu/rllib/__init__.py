"""ray_tpu.rllib: reinforcement learning at scale, jax-first.

Parity map to the reference's `rllib/` (new API stack only — the old
policy/rollout-worker stack is intentionally not reproduced):
- RLModule (core/rl_module.py)  <- rllib/core/rl_module/rl_module.py:260
- Learner/LearnerGroup (core/learner.py) <- rllib/core/learner/
- EnvRunner/Group (env/env_runner.py) <- rllib/env/single_agent_env_runner.py:68
- AlgorithmConfig/Algorithm (algorithms/) <- rllib/algorithms/
- PPO, DQN, IMPALA, SAC, CQL, BC/MARWIL <- rllib/algorithms/
- MultiAgentEnv + multi-agent PPO (env/multi_agent.py) <- rllib/env/multi_agent_env.py
- offline record/load (offline.py) <- rllib/offline/
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.multi_agent_ppo import (
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.env.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
)

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "APPO", "APPOConfig",
    "BC", "BCConfig", "DQN", "DQNConfig", "IMPALA", "IMPALAConfig",
    "MARWIL", "MARWILConfig", "SAC", "SACConfig", "CQL", "CQLConfig",
    "DreamerV3", "DreamerV3Config",
    "MultiAgentPPO", "MultiAgentPPOConfig", "MultiAgentEnv",
    "MultiAgentEnvRunner",
]
