"""Uniform transition replay buffer (host-side numpy ring).

Parity: reference `rllib/utils/replay_buffers/` (EpisodeReplayBuffer et al,
simplified to uniform transition sampling — the shape DQN needs). Storage
stays in host RAM; only sampled minibatches cross to the accelerator.
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._store: dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._size

    def add_batch(self, batch: dict):
        n = len(next(iter(batch.values())))
        if not self._store:
            for k, v in batch.items():
                self._store[k] = np.empty((self.capacity, *v.shape[1:]),
                                          v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}
