/* ray_tpu dashboard SPA (parity role: dashboard/client React app).
   Hash-routed views over the JSON API; vanilla DOM, no build step.
   Charts: single-series line + area small multiples fed by /api/history,
   with a crosshair + tooltip hover layer. */

"use strict";

const VIEWS = [
  ["overview", "Overview"],
  ["timeline", "Timeline"],
  ["nodes", "Nodes"],
  ["workers", "Workers"],
  ["actors", "Actors"],
  ["tasks", "Tasks"],
  ["objects", "Objects"],
  ["placement_groups", "Placement groups"],
  ["jobs", "Jobs"],
  ["train", "Train"],
  ["serve", "Serve"],
  ["logs", "Logs"],
];

const $ = (sel) => document.querySelector(sel);
const esc = (s) => String(s)
  .replace(/&/g, "&amp;").replace(/</g, "&lt;")
  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");

async function getJSON(path) {
  const resp = await fetch(path);
  if (!resp.ok) throw new Error(path + ": " + resp.status);
  return resp.json();
}

function currentView() {
  const h = location.hash.replace(/^#\/?/, "").split("?")[0];
  return VIEWS.some(([v]) => v === h) ? h : "overview";
}

function renderNav() {
  $("#nav").innerHTML = VIEWS.map(([v, label]) =>
    `<a href="#/${v}" class="${v === currentView() ? "active" : ""}">` +
    `${label}</a>`).join("");
}

/* ---------------- tables with filter + sort ---------------- */

const tableState = {};  // view -> {filter, sortCol, asc}

function badge(value) {
  const v = String(value).toUpperCase();
  let cls = "";
  if (["ALIVE", "RUNNING", "FINISHED", "SUCCEEDED", "READY", "CREATED",
       "IDLE", "BUSY", "TRUE"].includes(v)) cls = "good";
  else if (["PENDING", "RESTARTING", "SCHEDULED", "SPILLED",
            "STOPPED"].includes(v)) cls = "warning";
  else if (["DEAD", "FAILED", "LOST", "REMOVED", "FALSE"].includes(v))
    cls = "critical";
  else return esc(value);
  return `<span class="badge ${cls}">${esc(value)}</span>`;
}

const STATE_COLS = new Set(["state", "status", "alive", "job_status"]);

/* Drill-down linkification: id columns navigate per-node -> per-worker
   -> per-task detail views (the reference frontend's entity pages). */
const LINK_COLS = {
  node_id: (v) => `#/node?id=${encodeURIComponent(v)}`,
  worker_id: (v) => `#/worker?id=${encodeURIComponent(v)}`,
  task_id: (v) => `#/task?id=${encodeURIComponent(v)}`,
  job_id: (v) => `#/job?id=${encodeURIComponent(v)}`,
  job: (v) => `#/job?id=${encodeURIComponent(v)}`,
};

function cellHTML(c, v) {
  if (STATE_COLS.has(c)) return badge(v);
  if (LINK_COLS[c] && typeof v === "string" && v)
    return `<a class="drill" href="${LINK_COLS[c](v)}">${esc(v)}</a>`;
  return esc(JSON.stringify(v));
}

function renderTable(view, rows) {
  const st = tableState[view] ||= { filter: "", sortCol: null, asc: false };
  let cols = rows.length ? Object.keys(rows[0]) : [];
  let shown = rows;
  if (st.filter) {
    const f = st.filter.toLowerCase();
    shown = rows.filter((r) =>
      cols.some((c) => String(r[c]).toLowerCase().includes(f)));
  }
  if (st.sortCol) {
    const c = st.sortCol;
    shown = [...shown].sort((a, b) => {
      const x = a[c], y = b[c];
      const cmp = (typeof x === "number" && typeof y === "number")
        ? x - y : String(x).localeCompare(String(y));
      return st.asc ? cmp : -cmp;
    });
  }
  const head = cols.map((c) =>
    `<th data-col="${esc(c)}" class="${st.sortCol === c ?
      "sorted" + (st.asc ? " asc" : "") : ""}">${esc(c)}</th>`).join("");
  const body = shown.length ? shown.map((r) =>
    `<tr>${cols.map((c) => `<td title="${esc(JSON.stringify(r[c]))}">` +
      cellHTML(c, r[c]) + "</td>").join("")}</tr>`).join("")
    : `<tr><td class="empty">(empty)</td></tr>`;
  return `
    <div class="toolbar">
      <input id="filter" placeholder="filter…" value="${esc(st.filter)}">
      <span class="count">${shown.length}/${rows.length} rows</span>
    </div>
    <table><thead><tr>${head}</tr></thead><tbody>${body}</tbody></table>`;
}

function wireTable(view, rerender) {
  const inp = $("#filter");
  if (inp) inp.addEventListener("input", () => {
    tableState[view].filter = inp.value;
    rerender();
    const again = $("#filter");
    again.focus();
    again.setSelectionRange(again.value.length, again.value.length);
  });
  document.querySelectorAll("th[data-col]").forEach((th) =>
    th.addEventListener("click", () => {
      const st = tableState[view];
      if (st.sortCol === th.dataset.col) st.asc = !st.asc;
      else { st.sortCol = th.dataset.col; st.asc = false; }
      rerender();
    }));
}

/* ---------------- charts ---------------- */

function lineChart(id, title, points, fmt) {
  // Single series: titled tile, no legend needed; thin 2px line over a
  // soft area, recessive grid, crosshair tooltip on hover.
  const W = 300, H = 90, PADL = 34, PADB = 12, PADT = 6;
  if (!points.length) {
    return `<div class="chart"><h3>${esc(title)}</h3>` +
      `<svg viewBox="0 0 ${W} ${H}"><text class="axis" x="8" y="45">` +
      `no samples yet</text></svg></div>`;
  }
  const xs = points.map((p) => p[0]), ys = points.map((p) => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
  const yMax = Math.max(...ys, 1e-9) * 1.1;
  const X = (x) => PADL + (x - x0) / Math.max(x1 - x0, 1e-9)
    * (W - PADL - 4);
  const Y = (y) => PADT + (1 - y / yMax) * (H - PADT - PADB);
  const path = points.map((p, i) =>
    `${i ? "L" : "M"}${X(p[0]).toFixed(1)},${Y(p[1]).toFixed(1)}`).join("");
  const area = path + `L${X(x1).toFixed(1)},${Y(0).toFixed(1)}` +
    `L${X(x0).toFixed(1)},${Y(0).toFixed(1)}Z`;
  const gridYs = [0.5, 1.0].map((f) => yMax * f / 1.1);
  const grid = gridYs.map((g) =>
    `<line class="gridline" x1="${PADL}" x2="${W - 4}" ` +
    `y1="${Y(g).toFixed(1)}" y2="${Y(g).toFixed(1)}"/>` +
    `<text class="axis" x="2" y="${(Y(g) + 3).toFixed(1)}">` +
    `${fmt(g)}</text>`).join("");
  return `<div class="chart" data-chart="${id}"><h3>${esc(title)}</h3>
    <svg viewBox="0 0 ${W} ${H}" preserveAspectRatio="none">
      ${grid}
      <path class="area" d="${area}"/>
      <path class="line" d="${path}"/>
      <line class="cursor" y1="${PADT}" y2="${H - PADB}" x1="-10" x2="-10"/>
      <circle class="dot" r="3" cx="-10" cy="-10"/>
    </svg></div>`;
}

const chartData = {};  // id -> {points, fmt, title}

function wireCharts() {
  document.querySelectorAll("[data-chart]").forEach((el) => {
    const svg = el.querySelector("svg");
    const id = el.dataset.chart;
    svg.addEventListener("mousemove", (ev) => {
      const { points, fmt, title } = chartData[id] || {};
      if (!points || !points.length) return;
      const rect = svg.getBoundingClientRect();
      const W = 300, PADL = 34;
      const fx = (ev.clientX - rect.left) / rect.width * W;
      const xs = points.map((p) => p[0]);
      const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
      const t = x0 + (fx - PADL) / (W - PADL - 4) * (x1 - x0);
      let best = 0;
      points.forEach((p, i) => {
        if (Math.abs(p[0] - t) < Math.abs(points[best][0] - t)) best = i;
      });
      const p = points[best];
      const yMax = Math.max(...points.map((q) => q[1]), 1e-9) * 1.1;
      const X = PADL + (p[0] - x0) / Math.max(x1 - x0, 1e-9)
        * (W - PADL - 4);
      const Y = 6 + (1 - p[1] / yMax) * (90 - 6 - 12);
      svg.querySelector(".cursor").setAttribute("x1", X);
      svg.querySelector(".cursor").setAttribute("x2", X);
      const dot = svg.querySelector(".dot");
      dot.setAttribute("cx", X);
      dot.setAttribute("cy", Y);
      const tip = $("#tooltip");
      tip.style.display = "block";
      tip.style.left = (ev.clientX + 12) + "px";
      tip.style.top = (ev.clientY - 10) + "px";
      tip.innerHTML = `<b>${fmt(p[1])}</b> <span>${esc(title)} · ` +
        `${new Date(p[0] * 1000).toLocaleTimeString()}</span>`;
    });
    svg.addEventListener("mouseleave", () => {
      $("#tooltip").style.display = "none";
      svg.querySelector(".cursor").setAttribute("x1", -10);
      svg.querySelector(".dot").setAttribute("cx", -10);
    });
  });
}

/* Multi-series state-over-time chart: one line per state with a legend
   (the task/actor state timelines of the reference's frontend). */
const STATE_PALETTE = ["#4c9f70", "#d9a441", "#c75c5c", "#5b8dd9",
                      "#9a6fb8", "#5bb8b0", "#8a8a8a"];

function multiChart(title, hist, field) {
  const W = 620, H = 130, PADL = 34, PADB = 14, PADT = 6;
  const states = [...new Set(hist.flatMap(
    (h) => Object.keys(h[field] || {})))].sort();
  if (!hist.length || !states.length) {
    return `<div class="chart wide"><h3>${esc(title)}</h3>` +
      `<svg viewBox="0 0 ${W} ${H}"><text class="axis" x="8" y="60">` +
      `no samples yet</text></svg></div>`;
  }
  const xs = hist.map((h) => h.ts);
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
  const yMax = Math.max(1, ...hist.flatMap(
    (h) => states.map((s) => (h[field] || {})[s] || 0))) * 1.1;
  const X = (x) => PADL + (x - x0) / Math.max(x1 - x0, 1e-9)
    * (W - PADL - 4);
  const Y = (y) => PADT + (1 - y / yMax) * (H - PADT - PADB);
  const paths = states.map((s, i) => {
    const d = hist.map((h, j) =>
      `${j ? "L" : "M"}${X(h.ts).toFixed(1)},` +
      `${Y((h[field] || {})[s] || 0).toFixed(1)}`).join("");
    return `<path class="line" style="stroke:` +
      `${STATE_PALETTE[i % STATE_PALETTE.length]}" d="${d}"/>`;
  }).join("");
  const grid = [0.5, 1.0].map((f) => {
    const g = yMax * f / 1.1;
    return `<line class="gridline" x1="${PADL}" x2="${W - 4}" ` +
      `y1="${Y(g).toFixed(1)}" y2="${Y(g).toFixed(1)}"/>` +
      `<text class="axis" x="2" y="${(Y(g) + 3).toFixed(1)}">` +
      `${Math.round(g)}</text>`;
  }).join("");
  const legend = states.map((s, i) =>
    `<span class="legend-item"><i style="background:` +
    `${STATE_PALETTE[i % STATE_PALETTE.length]}"></i>${esc(s)}</span>`)
    .join("");
  return `<div class="chart wide"><h3>${esc(title)}</h3>
    <svg viewBox="0 0 ${W} ${H}" preserveAspectRatio="none">
      ${grid}${paths}</svg>
    <div class="legend">${legend}</div></div>`;
}

async function viewTimeline() {
  const hist = await getJSON("/api/history");
  $("#main").innerHTML = `<div class="charts">` +
    multiChart("Tasks by state over time", hist, "tasks_by_state") +
    multiChart("Actors by state over time", hist, "actors_by_state") +
    `</div>`;
}

async function viewTrain() {
  const runs = await getJSON("/api/train");
  const rows = runs.map((r) => ({
    name: r.name, state: r.state, workers: r.num_workers,
    iterations: r.iterations,
    started: new Date(r.started * 1000).toLocaleTimeString(),
    latest_metrics: r.latest_metrics,
  }));
  const rerender = () => {
    $("#main").innerHTML =
      `<p class="footer">training runs driven from this head ` +
      `process</p>` + renderTable("train", rows);
    wireTable("train", rerender);
  };
  rerender();
}

async function viewServe() {
  const apps = await getJSON("/api/serve");
  const rows = [];
  Object.entries(apps).forEach(([app, a]) =>
    Object.entries(a.deployments || {}).forEach(([dep, d]) =>
      rows.push({
        app, status: a.status, route: a.route_prefix, deployment: dep,
        dep_status: d.status, replicas:
          `${d.running_replicas}/${d.target_num_replicas}`,
        version: d.version,
      })));
  const rerender = () => {
    $("#main").innerHTML = renderTable("serve", rows);
    wireTable("serve", rerender);
  };
  rerender();
}

/* ---------------- views ---------------- */

async function viewOverview() {
  const [s, hist] = await Promise.all([
    getJSON("/api/cluster_status"), getJSON("/api/history")]);
  const used = (k) =>
    (s.resources.total[k] || 0) - (s.resources.available[k] || 0);
  const fmtInt = (v) => String(Math.round(v));
  const fmtMiB = (v) => Math.round(v) + "M";
  const cards = `
    <div class="card"><b>${s.nodes.alive}</b><span>nodes alive</span></div>
    <div class="card"><b>${used("CPU")}/${s.resources.total.CPU || 0}</b>
      <span>CPUs used</span></div>
    <div class="card"><b>${used("TPU")}/${s.resources.total.TPU || 0}</b>
      <span>TPUs used</span></div>
    <div class="card"><b>${s.pending_tasks}</b>
      <span>pending tasks</span></div>
    <div class="card"><b>${s.store.num_objects || 0}</b>
      <span>objects · ${Math.round((s.store.allocated || 0) / 1048576)}
      MiB</span></div>`;
  const series = [
    ["cpu", "CPU in use", hist.map((h) => [h.ts, h.cpu_used]), fmtInt],
    ["tpu", "TPU in use", hist.map((h) => [h.ts, h.tpu_used]), fmtInt],
    ["pending", "Pending tasks", hist.map((h) => [h.ts, h.pending]),
     fmtInt],
    ["tasks", "Tasks finished /s", hist.map((h) => [h.ts, h.tasks_per_s]),
     fmtInt],
    ["store", "Object store MiB", hist.map((h) => [h.ts, h.store_mib]),
     fmtMiB],
    ["workers", "Workers", hist.map((h) => [h.ts, h.workers]), fmtInt],
  ];
  series.forEach(([id, title, points, fmt]) =>
    chartData[id] = { points, fmt, title });
  $("#main").innerHTML =
    `<div class="cards">${cards}</div><div class="charts">` +
    series.map(([id, title, points, fmt]) =>
      lineChart(id, title, points, fmt)).join("") +
    `</div><p class="footer">raw: ` +
    ["cluster_status", "nodes", "actors", "tasks", "objects", "workers",
     "placement_groups", "jobs", "history"].map((r) =>
      `<a href="/api/${r}">/api/${r}</a>`).join(" ") +
    ` <a href="/metrics">/metrics</a></p>`;
  wireCharts();
}

async function viewTable(view) {
  const rows = await getJSON("/api/" + view);
  const rerender = () => {
    $("#main").innerHTML = renderTable(view, rows);
    wireTable(view, rerender);
    if (view === "workers") wireProfileButtons();
  };
  rerender();
}

function wireProfileButtons() {
  // Augment the workers table with per-row stack sampling.
  document.querySelectorAll("tbody tr").forEach((tr) => {
    const idCell = tr.querySelector("td");
    if (!idCell) return;
    const wid = JSON.parse(idCell.title || '""');
    const td = document.createElement("td");
    td.innerHTML = `<button>profile 1s</button>`;
    td.querySelector("button").addEventListener("click", async () => {
      const text = await (await fetch(
        `/api/profile?worker=${wid}&duration=1&format=text`)).text();
      $("#main").insertAdjacentHTML("beforeend",
        `<h3>stacks: ${esc(wid)}</h3><pre class="logview">` +
        `${esc(text)}</pre>`);
    });
    tr.appendChild(td);
  });
  const headRow = document.querySelector("thead tr");
  if (headRow) headRow.insertAdjacentHTML("beforeend", "<th></th>");
}

async function viewJobs() {
  const rows = await getJSON("/api/jobs");
  const rerender = () => {
    $("#main").innerHTML = renderTable("jobs", rows);
    wireTable("jobs", rerender);
  };
  rerender();
}

async function viewLogs() {
  const files = await getJSON("/api/logs");
  const sel = location.hash.split("?file=")[1] || "";
  let html = `<div class="toolbar"><select id="logfile">` +
    `<option value="">— pick a log file —</option>` +
    files.map((f) => `<option ${f === decodeURIComponent(sel) ?
      "selected" : ""}>${esc(f)}</option>`).join("") +
    `</select></div>`;
  if (sel) {
    const text = await (await fetch(
      "/api/logs?file=" + sel + "&tail=500")).text();
    html += `<pre class="logview">${esc(text)}</pre>`;
  }
  $("#main").innerHTML = html;
  $("#logfile").addEventListener("change", (ev) => {
    location.hash = "#/logs?file=" + encodeURIComponent(ev.target.value);
  });
}

/* ---------------- drill-down detail views ----------------
   #/node?id=…  -> the node's header + its workers + recent tasks
   #/worker?id=… -> exec history of one worker + its log tail
   #/task?id=…  -> one task's timeline phases + fn rollup + log tail
   All derived from the existing /api/timeline, /api/task_summary,
   /api/nodes, /api/workers and /api/logs endpoints. */

function hashParam(name) {
  const m = location.hash.match(new RegExp("[?&]" + name + "=([^&]*)"));
  return m ? decodeURIComponent(m[1]) : "";
}

function backLink(view, label) {
  return `<p class="footer"><a href="#/${view}">&larr; ${label}</a></p>`;
}

/* Pair B/E trace events per (pid, tid, name) stack; X events pass
   through. Returns [{name, pid, tid, ts, dur, args}] (us). */
function traceSlices(trace) {
  const out = [], open = {};
  trace.forEach((ev) => {
    if (ev.ph === "X") out.push(ev);
    else if (ev.ph === "B") {
      (open[`${ev.pid}|${ev.tid}|${ev.name}`] ||= []).push(ev);
    } else if (ev.ph === "E") {
      const stack = open[`${ev.pid}|${ev.tid}|${ev.name}`];
      const b = stack && stack.pop();
      if (b) out.push({ ...b, ph: "X", dur: ev.ts - b.ts });
    }
  });
  return out;
}

function phaseBars(slices) {
  // Minimal horizontal phase chart: offset/duration bars over the task's
  // whole span (the chrome-trace view, inlined for one task).
  if (!slices.length) return "<p>(no timeline phases recorded)</p>";
  const t0 = Math.min(...slices.map((s) => s.ts));
  const t1 = Math.max(...slices.map((s) => s.ts + (s.dur || 0)), t0 + 1);
  const rows = slices.map((s) => {
    const left = ((s.ts - t0) / (t1 - t0) * 100).toFixed(2);
    const width = Math.max(0.5, (s.dur || 0) / (t1 - t0) * 100).toFixed(2);
    const ms = ((s.dur || 0) / 1000).toFixed(3);
    return `<div class="phase-row" data-phase="${esc(s.name)}">
      <span class="phase-label">${esc(s.name)}
        <i class="muted">${esc(String(s.tid || ""))}</i></span>
      <span class="phase-track"><span class="phase-bar"
        style="left:${left}%;width:${width}%"></span></span>
      <span class="phase-ms">${ms} ms</span></div>`;
  }).join("");
  return `<div class="phases">${rows}</div>`;
}

async function logTailHTML(fileName, lines) {
  // Worker logs live on the head node; agent-node worker logs are not
  // served from here — degrade to a note instead of an error page.
  try {
    const resp = await fetch(
      `/api/logs?file=${encodeURIComponent(fileName)}&tail=${lines}`);
    if (!resp.ok) throw new Error(String(resp.status));
    const text = await resp.text();
    return `<h3>log tail: ${esc(fileName)}</h3>` +
      `<pre class="logview" id="tasklog">${esc(text)}</pre>`;
  } catch (e) {
    return `<p class="muted">no log file ${esc(fileName)} on the head ` +
      `node (agent-node workers log locally)</p>`;
  }
}

async function viewNodeDetail() {
  const id = hashParam("id");
  const [nodes, workers, trace] = await Promise.all([
    getJSON("/api/nodes"), getJSON("/api/workers"),
    getJSON("/api/timeline")]);
  const node = nodes.find((n) => n.node_id === id);
  const mine = workers.filter((w) => w.node_id === id);
  // Tasks recently seen on this node's rows (lease/exec/spill slices).
  const seen = new Map();
  traceSlices(trace).forEach((ev) => {
    const a = ev.args || {};
    if (ev.pid === `node:${id}` && a.task_id)
      seen.set(a.task_id, {
        task_id: a.task_id, job: a.job || "", what: ev.name,
        state: a.state || "",
        ms: ((ev.dur || 0) / 1000).toFixed(3),
      });
  });
  $("#main").innerHTML =
    `<h2 class="drill-title">node ${esc(id)}</h2>` +
    (node ? `<div class="cards">
      <div class="card"><b>${badge(node.alive)}</b><span>alive</span></div>
      <div class="card"><b>${esc(node.hostname || "?")}</b>
        <span>host</span></div>
      <div class="card"><b>${esc(JSON.stringify(node.resources))}</b>
        <span>resources</span></div></div>`
      : `<p>(unknown node)</p>`) +
    `<h3>workers (${mine.length})</h3>` + renderTable("node_workers", mine) +
    `<h3>recent tasks on this node</h3>` +
    renderTable("node_tasks", [...seen.values()]) +
    backLink("nodes", "all nodes");
}

async function viewWorkerDetail() {
  const id = hashParam("id");
  const trace = await getJSON("/api/timeline");
  const rows = traceSlices(trace)
    .filter((ev) => ev.tid === `worker:${id}`
            && (ev.args || {}).task_id)
    .map((ev) => ({
      task_id: ev.args.task_id, job: ev.args.job || "", phase: ev.name,
      state: ev.args.state || "", attempt: ev.args.attempt,
      start: new Date(ev.ts / 1000).toLocaleTimeString(),
      ms: +((ev.dur || 0) / 1000).toFixed(3),
    }));
  $("#main").innerHTML =
    `<h2 class="drill-title">worker ${esc(id)}</h2>` +
    `<h3>executed tasks</h3>` + renderTable("worker_tasks", rows) +
    await logTailHTML(`worker-${id.slice(0, 8)}.out`, 100) +
    backLink("workers", "all workers");
}

async function viewTaskDetail() {
  const id = hashParam("id");
  const [trace, summary] = await Promise.all([
    getJSON("/api/timeline"), getJSON("/api/task_summary")]);
  // Sub-spans (deserialize_args/execute/store_outputs) carry no task
  // args; keep only ones nested inside this task's exec windows.
  const mine = traceSlices(trace).filter(
    (ev) => (ev.args || {}).task_id === id);
  const windows = mine.map((ev) => [ev.ts, ev.ts + (ev.dur || 0), ev.tid]);
  const subs = traceSlices(trace).filter((ev) => !(ev.args || {}).task_id
    && windows.some(([a, b, tid]) => ev.tid === tid && ev.ts >= a
      && ev.ts + (ev.dur || 0) <= b + 1));
  const all = [...mine, ...subs].sort((a, b) => a.ts - b.ts);
  const exec = mine.find((ev) => String(ev.name).startsWith("exec:"));
  const fn = mine.length
    ? String(mine[0].name).replace(/^[a-z_]+:/, "") : "";
  const roll = ((summary || {}).tasks || {})[fn];
  const wid = exec ? String(exec.tid).replace(/^worker:/, "") : "";
  $("#main").innerHTML =
    `<h2 class="drill-title">task ${esc(id)}</h2>` +
    `<div class="cards">
      <div class="card"><b>${esc(fn || "?")}</b><span>function</span></div>
      <div class="card"><b>${mine.length ? badge(
        (mine[0].args || {}).state || "?") : "?"}</b><span>state</span>
      </div>
      ${roll ? `<div class="card"><b>${roll.mean_exec_ms ?? "?"}</b>
        <span>fn mean exec ms</span></div>
      <div class="card"><b>${roll.mean_queue_ms ?? "?"}</b>
        <span>fn mean queue ms</span></div>` : ""}
    </div>` +
    `<h3>timeline phases</h3>` + phaseBars(all) +
    (wid ? `<p>executed on <a class="drill" href="#/worker?id=` +
      `${encodeURIComponent(wid)}">worker ${esc(wid)}</a></p>` +
      await logTailHTML(`worker-${wid.slice(0, 8)}.out`, 60) : "") +
    backLink("tasks", "all tasks");
}

async function viewJobDetail() {
  const id = hashParam("id");
  const jobs = await getJSON("/api/jobs");
  const job = jobs.find((j) => j.job_id === id);
  if (!job) {
    $("#main").innerHTML = `<h2 class="drill-title">job ${esc(id)}</h2>` +
      `<p>(unknown job)</p>` + backLink("jobs", "all jobs");
    return;
  }
  const mib = (v) => ((v || 0) / 1048576).toFixed(1) + " MiB";
  const quota = job.quota || {};
  const usage = job.usage || {};
  const quotaCards = ["CPU", "TPU"].map((r) =>
    `<div class="card"><b>${usage[r] ?? 0}/${quota[r] || "∞"}</b>
      <span>${esc(r)} used/quota</span></div>`).join("");
  $("#main").innerHTML =
    `<h2 class="drill-title">job ${esc(id)}</h2>` +
    `<div class="cards">
      <div class="card"><b>${badge(job.stopped ? "STOPPED" :
        (job.status || "RUNNING"))}</b><span>status</span></div>
      <div class="card"><b>${(job.dominant_share ?? 0).toFixed
        ? (job.dominant_share ?? 0).toFixed(3)
        : esc(job.dominant_share)}</b><span>dominant share</span></div>
      <div class="card"><b>${esc(String(job.weight ?? 1))}</b>
        <span>weight</span></div>
      ${quotaCards}
      <div class="card"><b>${mib(job.object_bytes)}${job.object_quota
        ? " / " + mib(job.object_quota) : ""}</b>
        <span>object store</span></div>
      <div class="card"><b>${mib(job.spilled_bytes)}</b>
        <span>spilled</span></div>
      <div class="card"><b>${job.task_event_drops ?? 0}</b>
        <span>task-event drops</span></div>
      <div class="card"><b>${job.over_quota_waits ?? 0}</b>
        <span>over-quota waits</span></div>
      <div class="card"><b>${job.submitted ?? 0}/${job.finished ?? 0}</b>
        <span>tasks submitted/finished</span></div>
    </div>` +
    `<h3>raw</h3>` +
    renderTable("job_raw", [job]) +
    backLink("jobs", "all jobs");
}

const DETAIL_VIEWS = {
  node: viewNodeDetail, worker: viewWorkerDetail, task: viewTaskDetail,
  job: viewJobDetail,
};

/* ---------------- router + refresh loop ---------------- */

let refreshTimer = null;

async function render() {
  renderNav();
  $("#clock").textContent = new Date().toLocaleTimeString();
  const detail = location.hash.match(/^#\/(node|worker|task|job)\?/);
  if (detail) {
    try {
      await DETAIL_VIEWS[detail[1]]();
    } catch (e) {
      $("#main").innerHTML = `<p>${esc(e)}</p>`;
    }
    return;
  }
  const view = currentView();
  try {
    if (view === "overview") await viewOverview();
    else if (view === "timeline") await viewTimeline();
    else if (view === "train") await viewTrain();
    else if (view === "serve") await viewServe();
    else if (view === "logs") await viewLogs();
    else if (view === "jobs") await viewJobs();
    else await viewTable(view);
  } catch (e) {
    $("#main").innerHTML = `<p>${esc(e)}</p>`;
  }
}

function scheduleRefresh() {
  clearInterval(refreshTimer);
  refreshTimer = setInterval(() => {
    // Don't clobber an in-progress filter/profile interaction.
    if (document.activeElement && document.activeElement.id === "filter")
      return;
    if (["overview", "timeline"].includes(currentView())) render();
    $("#clock").textContent = new Date().toLocaleTimeString();
  }, 3000);
}

window.addEventListener("hashchange", () => { render(); });
render();
scheduleRefresh();
