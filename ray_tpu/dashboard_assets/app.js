/* ray_tpu dashboard SPA (parity role: dashboard/client React app).
   Hash-routed views over the JSON API; vanilla DOM, no build step.
   Charts: single-series line + area small multiples fed by /api/history,
   with a crosshair + tooltip hover layer. */

"use strict";

const VIEWS = [
  ["overview", "Overview"],
  ["timeline", "Timeline"],
  ["nodes", "Nodes"],
  ["workers", "Workers"],
  ["actors", "Actors"],
  ["tasks", "Tasks"],
  ["objects", "Objects"],
  ["placement_groups", "Placement groups"],
  ["jobs", "Jobs"],
  ["train", "Train"],
  ["serve", "Serve"],
  ["logs", "Logs"],
];

const $ = (sel) => document.querySelector(sel);
const esc = (s) => String(s)
  .replace(/&/g, "&amp;").replace(/</g, "&lt;")
  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");

async function getJSON(path) {
  const resp = await fetch(path);
  if (!resp.ok) throw new Error(path + ": " + resp.status);
  return resp.json();
}

function currentView() {
  const h = location.hash.replace(/^#\/?/, "").split("?")[0];
  return VIEWS.some(([v]) => v === h) ? h : "overview";
}

function renderNav() {
  $("#nav").innerHTML = VIEWS.map(([v, label]) =>
    `<a href="#/${v}" class="${v === currentView() ? "active" : ""}">` +
    `${label}</a>`).join("");
}

/* ---------------- tables with filter + sort ---------------- */

const tableState = {};  // view -> {filter, sortCol, asc}

function badge(value) {
  const v = String(value).toUpperCase();
  let cls = "";
  if (["ALIVE", "RUNNING", "FINISHED", "SUCCEEDED", "READY", "CREATED",
       "IDLE", "BUSY", "TRUE"].includes(v)) cls = "good";
  else if (["PENDING", "RESTARTING", "SCHEDULED", "SPILLED",
            "STOPPED"].includes(v)) cls = "warning";
  else if (["DEAD", "FAILED", "LOST", "REMOVED", "FALSE"].includes(v))
    cls = "critical";
  else return esc(value);
  return `<span class="badge ${cls}">${esc(value)}</span>`;
}

const STATE_COLS = new Set(["state", "status", "alive", "job_status"]);

function renderTable(view, rows) {
  const st = tableState[view] ||= { filter: "", sortCol: null, asc: false };
  let cols = rows.length ? Object.keys(rows[0]) : [];
  let shown = rows;
  if (st.filter) {
    const f = st.filter.toLowerCase();
    shown = rows.filter((r) =>
      cols.some((c) => String(r[c]).toLowerCase().includes(f)));
  }
  if (st.sortCol) {
    const c = st.sortCol;
    shown = [...shown].sort((a, b) => {
      const x = a[c], y = b[c];
      const cmp = (typeof x === "number" && typeof y === "number")
        ? x - y : String(x).localeCompare(String(y));
      return st.asc ? cmp : -cmp;
    });
  }
  const head = cols.map((c) =>
    `<th data-col="${esc(c)}" class="${st.sortCol === c ?
      "sorted" + (st.asc ? " asc" : "") : ""}">${esc(c)}</th>`).join("");
  const body = shown.length ? shown.map((r) =>
    `<tr>${cols.map((c) => `<td title="${esc(JSON.stringify(r[c]))}">` +
      (STATE_COLS.has(c) ? badge(r[c]) : esc(JSON.stringify(r[c])))
      + "</td>").join("")}</tr>`).join("")
    : `<tr><td class="empty">(empty)</td></tr>`;
  return `
    <div class="toolbar">
      <input id="filter" placeholder="filter…" value="${esc(st.filter)}">
      <span class="count">${shown.length}/${rows.length} rows</span>
    </div>
    <table><thead><tr>${head}</tr></thead><tbody>${body}</tbody></table>`;
}

function wireTable(view, rerender) {
  const inp = $("#filter");
  if (inp) inp.addEventListener("input", () => {
    tableState[view].filter = inp.value;
    rerender();
    const again = $("#filter");
    again.focus();
    again.setSelectionRange(again.value.length, again.value.length);
  });
  document.querySelectorAll("th[data-col]").forEach((th) =>
    th.addEventListener("click", () => {
      const st = tableState[view];
      if (st.sortCol === th.dataset.col) st.asc = !st.asc;
      else { st.sortCol = th.dataset.col; st.asc = false; }
      rerender();
    }));
}

/* ---------------- charts ---------------- */

function lineChart(id, title, points, fmt) {
  // Single series: titled tile, no legend needed; thin 2px line over a
  // soft area, recessive grid, crosshair tooltip on hover.
  const W = 300, H = 90, PADL = 34, PADB = 12, PADT = 6;
  if (!points.length) {
    return `<div class="chart"><h3>${esc(title)}</h3>` +
      `<svg viewBox="0 0 ${W} ${H}"><text class="axis" x="8" y="45">` +
      `no samples yet</text></svg></div>`;
  }
  const xs = points.map((p) => p[0]), ys = points.map((p) => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
  const yMax = Math.max(...ys, 1e-9) * 1.1;
  const X = (x) => PADL + (x - x0) / Math.max(x1 - x0, 1e-9)
    * (W - PADL - 4);
  const Y = (y) => PADT + (1 - y / yMax) * (H - PADT - PADB);
  const path = points.map((p, i) =>
    `${i ? "L" : "M"}${X(p[0]).toFixed(1)},${Y(p[1]).toFixed(1)}`).join("");
  const area = path + `L${X(x1).toFixed(1)},${Y(0).toFixed(1)}` +
    `L${X(x0).toFixed(1)},${Y(0).toFixed(1)}Z`;
  const gridYs = [0.5, 1.0].map((f) => yMax * f / 1.1);
  const grid = gridYs.map((g) =>
    `<line class="gridline" x1="${PADL}" x2="${W - 4}" ` +
    `y1="${Y(g).toFixed(1)}" y2="${Y(g).toFixed(1)}"/>` +
    `<text class="axis" x="2" y="${(Y(g) + 3).toFixed(1)}">` +
    `${fmt(g)}</text>`).join("");
  return `<div class="chart" data-chart="${id}"><h3>${esc(title)}</h3>
    <svg viewBox="0 0 ${W} ${H}" preserveAspectRatio="none">
      ${grid}
      <path class="area" d="${area}"/>
      <path class="line" d="${path}"/>
      <line class="cursor" y1="${PADT}" y2="${H - PADB}" x1="-10" x2="-10"/>
      <circle class="dot" r="3" cx="-10" cy="-10"/>
    </svg></div>`;
}

const chartData = {};  // id -> {points, fmt, title}

function wireCharts() {
  document.querySelectorAll("[data-chart]").forEach((el) => {
    const svg = el.querySelector("svg");
    const id = el.dataset.chart;
    svg.addEventListener("mousemove", (ev) => {
      const { points, fmt, title } = chartData[id] || {};
      if (!points || !points.length) return;
      const rect = svg.getBoundingClientRect();
      const W = 300, PADL = 34;
      const fx = (ev.clientX - rect.left) / rect.width * W;
      const xs = points.map((p) => p[0]);
      const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
      const t = x0 + (fx - PADL) / (W - PADL - 4) * (x1 - x0);
      let best = 0;
      points.forEach((p, i) => {
        if (Math.abs(p[0] - t) < Math.abs(points[best][0] - t)) best = i;
      });
      const p = points[best];
      const yMax = Math.max(...points.map((q) => q[1]), 1e-9) * 1.1;
      const X = PADL + (p[0] - x0) / Math.max(x1 - x0, 1e-9)
        * (W - PADL - 4);
      const Y = 6 + (1 - p[1] / yMax) * (90 - 6 - 12);
      svg.querySelector(".cursor").setAttribute("x1", X);
      svg.querySelector(".cursor").setAttribute("x2", X);
      const dot = svg.querySelector(".dot");
      dot.setAttribute("cx", X);
      dot.setAttribute("cy", Y);
      const tip = $("#tooltip");
      tip.style.display = "block";
      tip.style.left = (ev.clientX + 12) + "px";
      tip.style.top = (ev.clientY - 10) + "px";
      tip.innerHTML = `<b>${fmt(p[1])}</b> <span>${esc(title)} · ` +
        `${new Date(p[0] * 1000).toLocaleTimeString()}</span>`;
    });
    svg.addEventListener("mouseleave", () => {
      $("#tooltip").style.display = "none";
      svg.querySelector(".cursor").setAttribute("x1", -10);
      svg.querySelector(".dot").setAttribute("cx", -10);
    });
  });
}

/* Multi-series state-over-time chart: one line per state with a legend
   (the task/actor state timelines of the reference's frontend). */
const STATE_PALETTE = ["#4c9f70", "#d9a441", "#c75c5c", "#5b8dd9",
                      "#9a6fb8", "#5bb8b0", "#8a8a8a"];

function multiChart(title, hist, field) {
  const W = 620, H = 130, PADL = 34, PADB = 14, PADT = 6;
  const states = [...new Set(hist.flatMap(
    (h) => Object.keys(h[field] || {})))].sort();
  if (!hist.length || !states.length) {
    return `<div class="chart wide"><h3>${esc(title)}</h3>` +
      `<svg viewBox="0 0 ${W} ${H}"><text class="axis" x="8" y="60">` +
      `no samples yet</text></svg></div>`;
  }
  const xs = hist.map((h) => h.ts);
  const x0 = Math.min(...xs), x1 = Math.max(...xs) || 1;
  const yMax = Math.max(1, ...hist.flatMap(
    (h) => states.map((s) => (h[field] || {})[s] || 0))) * 1.1;
  const X = (x) => PADL + (x - x0) / Math.max(x1 - x0, 1e-9)
    * (W - PADL - 4);
  const Y = (y) => PADT + (1 - y / yMax) * (H - PADT - PADB);
  const paths = states.map((s, i) => {
    const d = hist.map((h, j) =>
      `${j ? "L" : "M"}${X(h.ts).toFixed(1)},` +
      `${Y((h[field] || {})[s] || 0).toFixed(1)}`).join("");
    return `<path class="line" style="stroke:` +
      `${STATE_PALETTE[i % STATE_PALETTE.length]}" d="${d}"/>`;
  }).join("");
  const grid = [0.5, 1.0].map((f) => {
    const g = yMax * f / 1.1;
    return `<line class="gridline" x1="${PADL}" x2="${W - 4}" ` +
      `y1="${Y(g).toFixed(1)}" y2="${Y(g).toFixed(1)}"/>` +
      `<text class="axis" x="2" y="${(Y(g) + 3).toFixed(1)}">` +
      `${Math.round(g)}</text>`;
  }).join("");
  const legend = states.map((s, i) =>
    `<span class="legend-item"><i style="background:` +
    `${STATE_PALETTE[i % STATE_PALETTE.length]}"></i>${esc(s)}</span>`)
    .join("");
  return `<div class="chart wide"><h3>${esc(title)}</h3>
    <svg viewBox="0 0 ${W} ${H}" preserveAspectRatio="none">
      ${grid}${paths}</svg>
    <div class="legend">${legend}</div></div>`;
}

async function viewTimeline() {
  const hist = await getJSON("/api/history");
  $("#main").innerHTML = `<div class="charts">` +
    multiChart("Tasks by state over time", hist, "tasks_by_state") +
    multiChart("Actors by state over time", hist, "actors_by_state") +
    `</div>`;
}

async function viewTrain() {
  const runs = await getJSON("/api/train");
  const rows = runs.map((r) => ({
    name: r.name, state: r.state, workers: r.num_workers,
    iterations: r.iterations,
    started: new Date(r.started * 1000).toLocaleTimeString(),
    latest_metrics: r.latest_metrics,
  }));
  const rerender = () => {
    $("#main").innerHTML =
      `<p class="footer">training runs driven from this head ` +
      `process</p>` + renderTable("train", rows);
    wireTable("train", rerender);
  };
  rerender();
}

async function viewServe() {
  const apps = await getJSON("/api/serve");
  const rows = [];
  Object.entries(apps).forEach(([app, a]) =>
    Object.entries(a.deployments || {}).forEach(([dep, d]) =>
      rows.push({
        app, status: a.status, route: a.route_prefix, deployment: dep,
        dep_status: d.status, replicas:
          `${d.running_replicas}/${d.target_num_replicas}`,
        version: d.version,
      })));
  const rerender = () => {
    $("#main").innerHTML = renderTable("serve", rows);
    wireTable("serve", rerender);
  };
  rerender();
}

/* ---------------- views ---------------- */

async function viewOverview() {
  const [s, hist] = await Promise.all([
    getJSON("/api/cluster_status"), getJSON("/api/history")]);
  const used = (k) =>
    (s.resources.total[k] || 0) - (s.resources.available[k] || 0);
  const fmtInt = (v) => String(Math.round(v));
  const fmtMiB = (v) => Math.round(v) + "M";
  const cards = `
    <div class="card"><b>${s.nodes.alive}</b><span>nodes alive</span></div>
    <div class="card"><b>${used("CPU")}/${s.resources.total.CPU || 0}</b>
      <span>CPUs used</span></div>
    <div class="card"><b>${used("TPU")}/${s.resources.total.TPU || 0}</b>
      <span>TPUs used</span></div>
    <div class="card"><b>${s.pending_tasks}</b>
      <span>pending tasks</span></div>
    <div class="card"><b>${s.store.num_objects || 0}</b>
      <span>objects · ${Math.round((s.store.allocated || 0) / 1048576)}
      MiB</span></div>`;
  const series = [
    ["cpu", "CPU in use", hist.map((h) => [h.ts, h.cpu_used]), fmtInt],
    ["tpu", "TPU in use", hist.map((h) => [h.ts, h.tpu_used]), fmtInt],
    ["pending", "Pending tasks", hist.map((h) => [h.ts, h.pending]),
     fmtInt],
    ["tasks", "Tasks finished /s", hist.map((h) => [h.ts, h.tasks_per_s]),
     fmtInt],
    ["store", "Object store MiB", hist.map((h) => [h.ts, h.store_mib]),
     fmtMiB],
    ["workers", "Workers", hist.map((h) => [h.ts, h.workers]), fmtInt],
  ];
  series.forEach(([id, title, points, fmt]) =>
    chartData[id] = { points, fmt, title });
  $("#main").innerHTML =
    `<div class="cards">${cards}</div><div class="charts">` +
    series.map(([id, title, points, fmt]) =>
      lineChart(id, title, points, fmt)).join("") +
    `</div><p class="footer">raw: ` +
    ["cluster_status", "nodes", "actors", "tasks", "objects", "workers",
     "placement_groups", "jobs", "history"].map((r) =>
      `<a href="/api/${r}">/api/${r}</a>`).join(" ") +
    ` <a href="/metrics">/metrics</a></p>`;
  wireCharts();
}

async function viewTable(view) {
  const rows = await getJSON("/api/" + view);
  const rerender = () => {
    $("#main").innerHTML = renderTable(view, rows);
    wireTable(view, rerender);
    if (view === "workers") wireProfileButtons();
  };
  rerender();
}

function wireProfileButtons() {
  // Augment the workers table with per-row stack sampling.
  document.querySelectorAll("tbody tr").forEach((tr) => {
    const idCell = tr.querySelector("td");
    if (!idCell) return;
    const wid = JSON.parse(idCell.title || '""');
    const td = document.createElement("td");
    td.innerHTML = `<button>profile 1s</button>`;
    td.querySelector("button").addEventListener("click", async () => {
      const text = await (await fetch(
        `/api/profile?worker=${wid}&duration=1&format=text`)).text();
      $("#main").insertAdjacentHTML("beforeend",
        `<h3>stacks: ${esc(wid)}</h3><pre class="logview">` +
        `${esc(text)}</pre>`);
    });
    tr.appendChild(td);
  });
  const headRow = document.querySelector("thead tr");
  if (headRow) headRow.insertAdjacentHTML("beforeend", "<th></th>");
}

async function viewJobs() {
  const rows = await getJSON("/api/jobs");
  const rerender = () => {
    $("#main").innerHTML = renderTable("jobs", rows);
    wireTable("jobs", rerender);
  };
  rerender();
}

async function viewLogs() {
  const files = await getJSON("/api/logs");
  const sel = location.hash.split("?file=")[1] || "";
  let html = `<div class="toolbar"><select id="logfile">` +
    `<option value="">— pick a log file —</option>` +
    files.map((f) => `<option ${f === decodeURIComponent(sel) ?
      "selected" : ""}>${esc(f)}</option>`).join("") +
    `</select></div>`;
  if (sel) {
    const text = await (await fetch(
      "/api/logs?file=" + sel + "&tail=500")).text();
    html += `<pre class="logview">${esc(text)}</pre>`;
  }
  $("#main").innerHTML = html;
  $("#logfile").addEventListener("change", (ev) => {
    location.hash = "#/logs?file=" + encodeURIComponent(ev.target.value);
  });
}

/* ---------------- router + refresh loop ---------------- */

let refreshTimer = null;

async function render() {
  renderNav();
  $("#clock").textContent = new Date().toLocaleTimeString();
  const view = currentView();
  try {
    if (view === "overview") await viewOverview();
    else if (view === "timeline") await viewTimeline();
    else if (view === "train") await viewTrain();
    else if (view === "serve") await viewServe();
    else if (view === "logs") await viewLogs();
    else if (view === "jobs") await viewJobs();
    else await viewTable(view);
  } catch (e) {
    $("#main").innerHTML = `<p>${esc(e)}</p>`;
  }
}

function scheduleRefresh() {
  clearInterval(refreshTimer);
  refreshTimer = setInterval(() => {
    // Don't clobber an in-progress filter/profile interaction.
    if (document.activeElement && document.activeElement.id === "filter")
      return;
    if (["overview", "timeline"].includes(currentView())) render();
    $("#clock").textContent = new Date().toLocaleTimeString();
  }, 3000);
}

window.addEventListener("hashchange", () => { render(); });
render();
scheduleRefresh();
