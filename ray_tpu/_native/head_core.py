"""ctypes bindings for the native head core (cpp/head_core.cc).

One `HeadCore` instance per head process: the C++ side owns the
node-listener frame pump (epoll + outer-frame split + accept-readiness
surfacing), the in-place `node_done_raw` parse into flat completion
records, the (task_id, lease_seq) per-node inflight ledger, and the
native `node_exec_raw` grant-frame builds into per-node double-buffered
outboxes. Python keeps all policy and performs every socket write/accept
under the same locks as the pure-Python listener. Built on demand
through the content-hash g++ cache (ray_tpu/_native/build.py) — a
failed build degrades to the pure-Python listener, never to an error.
"""

from __future__ import annotations

import ctypes
import os
import struct

_u64 = ctypes.c_uint64
_i32 = ctypes.c_int
_i64 = ctypes.c_int64
_dbl = ctypes.c_double
_u8p = ctypes.POINTER(ctypes.c_uint8)

# Frame kinds surfaced by the pump (framecore::FrameKind).
KIND_PICKLE = 0
KIND_PROTO = 1
KIND_RAW = 2
KIND_EOF = 3
KIND_ACCEPT = 4

# Completion-record out statuses (head_core.cc OutRec.status).
_STATUS = ("inline", "err", "shm")

_lib = None
_lib_err = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from ray_tpu._native import build as _b
        from ray_tpu._native.build import load_native
        native_dir = os.path.dirname(os.path.abspath(_b.__file__))
        repo = os.path.dirname(os.path.dirname(native_dir))
        src = os.path.join(repo, "cpp", "head_core.cc")
        hdr = os.path.join(repo, "cpp", "frame_core.h")
        lib = load_native("head_core", sources=(src,), headers=(hdr,))
    except Exception as e:  # noqa: BLE001 — degrade to pure Python
        _lib_err = e
        return None
    p = ctypes.c_void_p
    lib.hdc_new.restype = p
    lib.hdc_free.argtypes = [p]
    lib.hdc_add_fd.argtypes = [p, _i32, _u64, _i32]
    lib.hdc_del_fd.argtypes = [p, _i32]
    lib.hdc_poll.argtypes = [p, _i32]
    lib.hdc_split.argtypes = [p]
    lib.hdc_frame_count.argtypes = [p]
    lib.hdc_frame_info.argtypes = [
        p, _i32, ctypes.POINTER(_u64), ctypes.POINTER(_i32),
        ctypes.POINTER(_i32), ctypes.POINTER(_u8p), ctypes.POINTER(_u64),
        ctypes.POINTER(_u8p), ctypes.POINTER(_u64), ctypes.POINTER(_i32),
        ctypes.POINTER(_i32)]
    lib.hdc_frame_buf.argtypes = [p, _i32, _i32, ctypes.POINTER(_u8p),
                                  ctypes.POINTER(_u64)]
    lib.hdc_round_end.argtypes = [p]
    lib.hdc_node_add.argtypes = [p, _u64]
    lib.hdc_node_remove.argtypes = [p, _i32]
    lib.hdc_grant_add.argtypes = [p, _i32, ctypes.c_char_p, _i32,
                                  ctypes.c_char_p, _i32, _u64,
                                  ctypes.c_char_p, _u64, _i32,
                                  ctypes.c_char_p, _u64, _i64,
                                  ctypes.c_char_p, _i32]
    lib.hdc_grant_take.argtypes = [p, _i32, ctypes.POINTER(_u8p),
                                   ctypes.POINTER(_u64)]
    lib.hdc_grant_drop.argtypes = [p, _i32]
    lib.hdc_consume_hot.argtypes = [p]
    lib.hdc_rec_count.argtypes = [p]
    lib.hdc_rec_info.argtypes = [
        p, _i32, ctypes.POINTER(_i32), ctypes.POINTER(_i32),
        ctypes.POINTER(_u8p), ctypes.POINTER(_u64),
        ctypes.POINTER(_u8p), ctypes.POINTER(_u64), ctypes.POINTER(_i32),
        ctypes.POINTER(_i64), ctypes.POINTER(_dbl), ctypes.POINTER(_i32),
        ctypes.POINTER(_i32)]
    lib.hdc_rec_out.argtypes = [
        p, _i32, ctypes.POINTER(_u8p), ctypes.POINTER(_u64),
        ctypes.POINTER(_i32), ctypes.POINTER(_u8p), ctypes.POINTER(_u64),
        ctypes.POINTER(_i32)]
    lib.hdc_recs_take.argtypes = [p, ctypes.POINTER(_u8p),
                                  ctypes.POINTER(_u64)]
    lib.hdc_inflight_pop.argtypes = [p, ctypes.c_char_p, _i32]
    lib.hdc_inflight.argtypes = [p]
    lib.hdc_inflight.restype = _u64
    lib.hdc_stats.argtypes = [p, ctypes.POINTER(_u64), ctypes.POINTER(_u64),
                              ctypes.POINTER(_u64)]
    lib.hdc_proto_tag_count.argtypes = []
    lib.hdc_proto_tag_entry.argtypes = [_i32, ctypes.POINTER(_i32),
                                        ctypes.POINTER(ctypes.c_char_p)]
    _lib = lib
    return lib


def _view(ptr, n):
    if not n:
        return b""
    return memoryview((ctypes.c_uint8 * n).from_address(
        ctypes.cast(ptr, ctypes.c_void_p).value))


class HeadCore:
    """Python face of one native head-listener context."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"head_core build failed: {_lib_err!r}")
        self._lib = lib
        self._ctx = lib.hdc_new()
        self._next_tag = 16

    def close(self):
        if self._ctx:
            self._lib.hdc_free(self._ctx)
            self._ctx = None

    # -- pump --

    def add_fd(self, fd: int, tag: int, accept: bool = False):
        self._lib.hdc_add_fd(self._ctx, fd, tag, 2 if accept else 0)

    def del_fd(self, fd: int):
        self._lib.hdc_del_fd(self._ctx, fd)

    def alloc_tag(self) -> int:
        self._next_tag += 1
        return self._next_tag

    def poll(self, timeout_ms: int) -> int:
        return self._lib.hdc_poll(self._ctx, timeout_ms)

    def split(self) -> int:
        return self._lib.hdc_split(self._ctx)

    def consume_hot(self) -> int:
        return self._lib.hdc_consume_hot(self._ctx)

    def frames(self):
        """Yield (tag, kind, proto_tag, payload_view, bufs, whole_view) for
        every frame Python must handle. Views die at round_end()."""
        lib, ctx = self._lib, self._ctx
        n = lib.hdc_frame_count(ctx)
        tag, kind, ptag = _u64(), _i32(), _i32()
        pp, pl = _u8p(), _u64()
        wp, wl = _u8p(), _u64()
        nb, cons = _i32(), _i32()
        for i in range(n):
            if lib.hdc_frame_info(ctx, i, tag, kind, ptag, pp, pl, wp, wl,
                                  nb, cons) != 0:
                continue
            if cons.value:
                continue
            bufs = []
            for j in range(nb.value):
                bp, bl = _u8p(), _u64()
                if lib.hdc_frame_buf(ctx, i, j, bp, bl) == 0:
                    # bytes COPY, not a view: out-of-band buffers can
                    # outlive the round inside decoded messages (an
                    # inline result banked in the directory) while the
                    # native conn buffer is recycled at round_end —
                    # matching FrameBuffer, which also yields bytes.
                    bufs.append(bytes(_view(bp, bl.value)))
            yield (tag.value, kind.value, ptag.value,
                   _view(pp, pl.value), bufs, _view(wp, wl.value))

    def round_end(self):
        self._lib.hdc_round_end(self._ctx)

    # -- node ledger / grant builder --

    def node_add(self, tag: int) -> int:
        return self._lib.hdc_node_add(self._ctx, tag)

    def node_remove(self, nidx: int):
        self._lib.hdc_node_remove(self._ctx, nidx)

    def grant_add(self, nidx: int, tid: bytes, fn: bytes | None, seq: int,
                  blob: bytes | None, spec_bytes: bytes, attempt: int,
                  name: str | None):
        fn = fn or b""
        nm = (name or "").encode("utf-8", "replace")
        self._lib.hdc_grant_add(
            self._ctx, nidx, tid, len(tid), fn, len(fn), seq or 0,
            blob or b"", len(blob or b""), 0 if blob is None else 1,
            spec_bytes, len(spec_bytes), attempt or 0, nm, len(nm))

    def grant_take(self, nidx: int):
        """The staged grant batch as ONE complete node_exec_raw outer
        frame (view valid until the next take for this node)."""
        pp, pl = _u8p(), _u64()
        if self._lib.hdc_grant_take(self._ctx, nidx, pp, pl) != 0:
            return b""
        return _view(pp, pl.value) if pl.value else b""

    def grant_drop(self, nidx: int):
        self._lib.hdc_grant_drop(self._ctx, nidx)

    # -- completion ledger --

    _REC_HDR = struct.Struct("<iBBHHq4dH")
    _OUT_HDR = struct.Struct("<BBIQ")

    def completions(self):
        """Yield one (nidx, known, tid, whex, outs, tev) per natively
        consumed lease completion, where outs is the rebuilt
        [(rid, status, payload, bufs)] list `_on_node_done` consumes and
        tev the piggybacked exec record (or None). Byte fields are
        COPIES (they outlive the round inside the directory). The whole
        round drains through ONE native call (hdc_recs_take) + struct
        unpacks — per-record ctypes accessor chatter measurably hit the
        16-agent storm. Call between consume_hot() and round_end()."""
        lib, ctx = self._lib, self._ctx
        pp, pl = _u8p(), _u64()
        n = lib.hdc_recs_take(ctx, pp, pl)
        if n <= 0:
            return
        buf = bytes(_view(pp, pl.value))
        rec_hdr, out_hdr = self._REC_HDR, self._OUT_HDR
        off = 0
        for _ in range(n):
            (nidx, known, tevp, tlen, wlen, teva, t0, t1, t2, t3,
             nouts) = rec_hdr.unpack_from(buf, off)
            off += rec_hdr.size
            tid = buf[off:off + tlen]
            off += tlen
            whex = buf[off:off + wlen].decode("ascii", "replace")
            off += wlen
            outs = []
            for _j in range(nouts):
                st, pnone, rlen, plen = out_hdr.unpack_from(buf, off)
                off += out_hdr.size
                rid = buf[off:off + rlen]
                off += rlen
                if pnone:
                    payload = None
                else:
                    payload = buf[off:off + plen]
                    off += plen
                outs.append((rid, _STATUS[st], payload,
                             [] if st < 2 else None))
            tev = (teva, t0, t1, t2, t3) if tevp else None
            yield (nidx, bool(known), tid, whex, outs, tev)

    def inflight_pop(self, tid: bytes) -> int:
        return self._lib.hdc_inflight_pop(self._ctx, tid, len(tid))

    def inflight(self) -> int:
        return int(self._lib.hdc_inflight(self._ctx))

    def stats(self) -> dict:
        g, d, f = _u64(), _u64(), _u64()
        self._lib.hdc_stats(self._ctx, g, d, f)
        return {"native_grants": g.value, "native_dones": d.value,
                "native_done_frames": f.value}


def proto_tag_table() -> dict:
    """The AgentFrame oneof tags compiled into the shared sniffer
    (staticcheck cross-checks these against raytpu.proto)."""
    lib = _load()
    if lib is None:
        return {}
    out = {}
    f, name = _i32(), ctypes.c_char_p()
    for i in range(lib.hdc_proto_tag_count()):
        if lib.hdc_proto_tag_entry(i, f, name) == 0:
            out[name.value.decode()] = f.value
    return out


def available() -> bool:
    return _load() is not None
