// Shared-memory object store: a single mmap'd arena shared by every process on a
// node, with an in-shm object index and allocator so create/seal/get/release are
// direct memory operations — no broker round-trip.
//
// Parity: reference `src/ray/object_manager/plasma/` (PlasmaStore store.h:55,
// dlmalloc arena, eviction_policy.h LRU, create_request_queue.h backpressure).
// Design departure: plasma brokers create/get through a unix-socket server and
// passes fds; here clients map the arena directly and synchronize through
// robust pthread mutexes in shm, which removes the per-op socket round trip
// (the main cost in plasma's put/get calls/s) while keeping zero-copy reads.
//
// Concurrency: the index and allocator are SHARDED. Object ids hash to one of
// N shards, each with its own robust mutex, slot-table segment, and small-block
// cache (fastbins + a free list refilled in chunks), so concurrent create/get/
// release from many clients only contend when their ids collide on a shard —
// the plasma-era single store mutex serialized every client on one lock.
// Large blocks (> small_max) come from a global extent allocator under its own
// mutex; its critical sections are pointer splices (microseconds), so even
// GB-scale puts from many clients overlap their copies fully.
//
// Layout:
//   [Header | shard headers[N] | slot tables (per-shard segments) | arena]
// Free blocks form address-ordered singly-linked lists (one global, one small-
// block list per shard) for O(1) coalescing; freed small blocks park in
// per-shard size-class fastbins, and shard caches consolidate back into the
// global list past a byte threshold or on allocation pressure — the dlmalloc
// fastbin design the reference's plasma store inherits, replicated per shard.
//
// All functions return 0 on success or a negative StoreStatus.

#include <errno.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

#include <algorithm>
#include <thread>
#include <utility>
#include <vector>

extern "C" {

// Parallel memcpy for large objects: a single core's memcpy (~14 GB/s) is
// half the put_gigabytes baseline; on multi-core hosts splitting the copy
// across threads saturates DRAM bandwidth instead. Caller releases the GIL
// (ctypes does this automatically), so worker threads run truly parallel.
void store_memcpy(void* dst, const void* src, uint64_t n, int nthreads) {
  if (nthreads <= 1 || n < (8u << 20)) {
    memcpy(dst, src, n);
    return;
  }
  uint64_t chunk = (n + nthreads - 1) / nthreads;
  chunk = (chunk + 63) & ~63ULL;  // cache-line aligned splits
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  for (uint64_t off = 0; off < n; off += chunk) {
    uint64_t len = off + chunk <= n ? chunk : n - off;
    ts.emplace_back([=] { memcpy((char*)dst + off, (const char*)src + off, len); });
  }
  for (auto& t : ts) t.join();
}

// Adaptive-width arena copy: divides the thread budget by the number of
// concurrent large copies into the SAME arena. One client copying 1GB
// wants every core; ten clients each copying 80MB already parallelize
// across processes, and giving each of them `max_threads` workers
// oversubscribes the box 10x (measured as the multi-client put collapse:
// more copy threads, less aggregate bandwidth). The counter lives in the
// shm header so separate client processes see each other.
void store_copy_adaptive(void* base, void* dst, const void* src, uint64_t n,
                         int max_threads);

enum StoreStatus {
  OK = 0,
  ERR_NOTFOUND = -1,
  ERR_AGAIN = -2,       // object exists but not sealed yet
  ERR_EXISTS = -3,
  ERR_FULL = -4,        // no space even after eviction
  ERR_TABLE_FULL = -5,
  ERR_BUSY = -6,        // delete refused: nonzero refcount
  ERR_CORRUPT = -7,
};

static const uint64_t MAGIC = 0x5241595F54505535ULL;  // "RAY_TPU5" (affinity)
static const uint64_t ALIGN = 64;
static const uint64_t MIN_BLOCK = 128;
static const uint32_t SHARD_CANARY = 0x53484152;      // "SHAR"
static const uint64_t MAX_SHARDS = 256;

enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_CREATED = 1,
  SLOT_SEALED = 2,
  SLOT_TOMBSTONE = 3,
};

struct Slot {
  uint8_t id[16];
  uint64_t offset;     // arena-relative offset of data
  uint64_t data_size;
  uint64_t meta_size;  // metadata stored immediately after data
  uint32_t state;
  int32_t refcnt;
  uint64_t lru_tick;
  uint32_t pending_delete;
  uint32_t _pad;
};  // 64 bytes

struct FreeBlock {
  uint64_t size;
  uint64_t next;  // arena-relative offset of next free block, or 0 (arena off 0 is never free: we reserve first ALIGN bytes)
};

// Crash-consistency record for one live write-reservation extent: who
// carved it (pid) and how many of its bytes are still neither published
// nor released. Registered under the global mutex at store_reserve;
// store_publish / store_release_extent decrement `unpublished` and the
// record self-retires at zero. A client that dies mid-reservation leaves
// an active record whose pid no longer exists — store_reclaim_orphans
// finds those, returns the unaccounted gaps inside [off, off+size) to the
// global free list, and repairs rsv_unused_bytes, so a SIGKILLed client
// can no longer strand an extent (or wedge spill accounting) until the
// arena is unlinked.
static const uint64_t MAX_RSV_RECS = 256;
struct RsvRec {
  uint64_t pid;
  uint64_t off;          // arena-relative extent start
  uint64_t size;         // extent bytes
  uint64_t unpublished;  // bytes not yet published/released (atomic)
  uint64_t active;       // atomic 0/1; set last (release) at register
};

static const uint64_t MAX_AFF_RECS = 64;
struct AffRec {
  uint64_t pid;   // 0 = empty
  uint64_t off;   // arena-relative range start the pid last owned
  uint64_t size;
};

static const uint64_t FASTBIN_MAX = 2048;   // largest fastbinned block
static const uint64_t NUM_FASTBINS = FASTBIN_MAX / ALIGN;  // 64..2048 step 64
static const uint64_t SMALL_MAX = 256u << 10;  // shard-cache ceiling

struct Shard {
  pthread_mutex_t mutex;
  uint32_t canary;
  uint32_t _pad0;
  uint64_t free_head;              // small-block list, arena-relative, 0=none
  uint64_t fastbin[NUM_FASTBINS];  // arena-relative heads, 0 = empty
  uint64_t cache_bytes;            // bytes parked in fastbins + free list
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t num_tombstones;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t nshards;          // power of two, <= MAX_SHARDS
  uint64_t slots_per_shard;  // power of two
  uint64_t table_offset;     // from base
  uint64_t arena_offset;     // from base
  uint64_t arena_size;
  uint64_t refill_chunk;     // shard cache refill granularity
  uint64_t small_max;        // allocations <= this ride the shard cache
  uint64_t cache_limit;      // per-shard cache consolidation threshold
  pthread_mutex_t mutex;     // global: extent list + bytes_from_global
  uint64_t free_head;        // global extent list, arena-relative, 0 = none
  uint64_t bytes_from_global;  // bytes carved out of the global list
  uint64_t lru_clock;          // advanced with atomics, no lock
  // Write-reservation plane (multi-client put bandwidth): extents carved
  // once and bump-filled client-side, published as sealed slots.
  uint64_t num_reserves;       // atomic counter (diagnostics/tests)
  uint64_t rsv_unused_bytes;   // atomic: reserved but not yet published —
                               // subtracted from stats "allocated" so the
                               // spill policy sees live bytes, not parked
                               // headroom
  uint64_t active_copiers;     // atomic: in-flight large arena copies;
                               // store_copy_adaptive divides its thread
                               // budget by this so N concurrent clients
                               // don't oversubscribe N*threads workers
  RsvRec rsv_recs[MAX_RSV_RECS];  // live-extent ownership (crash sweep)
  // Owner-affinity hints: the last extent range each pid drained (recorded
  // when a reservation record retires or a tail is released). store_reserve
  // prefers carving its next extent from free bytes inside the caller's
  // hinted range, so a refill lands on pages already in that process's page
  // table — BENCH_r06 isolated the cold-refill page faults as the 8.4->2.1
  // GB/s multi-writer collapse. Hints are advisory: torn reads just cost a
  // failed range probe, never a wrong allocation (the free list is the
  // truth). All fields accessed with relaxed atomics (TSan-clean).
  uint64_t num_aff_hits;           // atomic: affinity-satisfied reserves
  AffRec aff_recs[MAX_AFF_RECS];
};

static inline Shard* shard_at(Header* h, uint64_t i) {
  return (Shard*)((char*)h + sizeof(Header)) + i;
}
static inline Slot* shard_table(Header* h, uint64_t i) {
  return (Slot*)((char*)h + h->table_offset) + i * h->slots_per_shard;
}
static inline char* arena(Header* h) { return (char*)h + h->arena_offset; }

static inline uint64_t hash_id(const uint8_t* id) {
  uint64_t x;
  memcpy(&x, id, 8);
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL; x ^= x >> 33;
  return x;
}

static inline uint64_t shard_of(Header* h, const uint8_t* id) {
  return hash_id(id) & (h->nshards - 1);
}
// Probe start inside a shard's table segment: the low bits picked the
// shard, so the in-shard position uses a disjoint bit range.
static inline uint64_t slot_start(Header* h, const uint8_t* id) {
  return (hash_id(id) >> 20) & (h->slots_per_shard - 1);
}

static inline uint64_t next_tick(Header* h) {
  return __atomic_add_fetch(&h->lru_clock, 1, __ATOMIC_RELAXED);
}

static void lock_mu(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock; shm metadata is still consistent
    // because every mutation completes all pointer updates before unlock and
    // a half-written object is just an unsealed slot (evictable).
    pthread_mutex_consistent(mu);
  }
}
static bool trylock_mu(pthread_mutex_t* mu) {
  int rc = pthread_mutex_trylock(mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    return true;
  }
  return rc == 0;
}
static void unlock_mu(pthread_mutex_t* mu) { pthread_mutex_unlock(mu); }

// ---- free-list primitives (shared by the global list and shard lists) ----

static uint64_t align_up(uint64_t v) { return (v + ALIGN - 1) & ~(ALIGN - 1); }

static void list_insert_ordered(Header* h, uint64_t* headp, uint64_t off,
                                uint64_t size) {
  // insert address-ordered, coalesce with list neighbors
  uint64_t prev = 0, cur = *headp;
  while (cur && cur < off) {
    prev = cur;
    cur = ((FreeBlock*)(arena(h) + cur))->next;
  }
  FreeBlock* nb = (FreeBlock*)(arena(h) + off);
  nb->size = size;
  nb->next = cur;
  if (prev) {
    FreeBlock* pb = (FreeBlock*)(arena(h) + prev);
    pb->next = off;
    if (prev + pb->size == off) {  // coalesce prev+new
      pb->size += nb->size;
      pb->next = nb->next;
      nb = pb;
      off = prev;
    }
  } else {
    *headp = off;
  }
  if (nb->next && off + nb->size == nb->next) {  // coalesce new+next
    FreeBlock* nx = (FreeBlock*)(arena(h) + nb->next);
    nb->size += nx->size;
    nb->next = nx->next;
  }
}

// First-fit with split. All block sizes are ALIGN multiples, so a nonzero
// remainder is always splittable and the absorb branch only fires at
// rem == 0 (freeing align_up(data+meta) later returns exactly what was
// allocated — no leaked tail).
static int64_t list_alloc_first_fit(Header* h, uint64_t* headp,
                                    uint64_t need) {
  uint64_t prev = 0;
  uint64_t cur = *headp;
  while (cur) {
    FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
    if (fb->size >= need) {
      uint64_t rem = fb->size - need;
      if (rem >= ALIGN) {
        uint64_t newoff = cur + need;
        FreeBlock* nb = (FreeBlock*)(arena(h) + newoff);
        nb->size = rem;
        nb->next = fb->next;
        if (prev) ((FreeBlock*)(arena(h) + prev))->next = newoff;
        else *headp = newoff;
      } else {
        if (prev) ((FreeBlock*)(arena(h) + prev))->next = fb->next;
        else *headp = fb->next;
      }
      return (int64_t)cur;
    }
    prev = cur;
    cur = fb->next;
  }
  return -1;
}

// ---- shard allocator ----
// Lock order: shard mutex -> (other shard via TRYLOCK only) -> global mutex.
// The global mutex is always innermost, and a second shard is only ever
// acquired with trylock, so no cycle can form.

// caller holds sh->mutex; returns bytes actually taken from the GLOBAL list
// (0 when none) via *taken so accounting stays exact.
static int64_t shard_alloc(Header* h, Shard* sh, uint64_t need_raw) {
  uint64_t need = align_up(need_raw < MIN_BLOCK ? MIN_BLOCK : need_raw);
  if (need <= FASTBIN_MAX) {
    uint64_t bin = need / ALIGN - 1;
    uint64_t off = sh->fastbin[bin];
    if (off) {  // exact-size hit: O(1), no list walk, no global lock
      FreeBlock* fb = (FreeBlock*)(arena(h) + off);
      sh->fastbin[bin] = fb->next;
      sh->cache_bytes -= need;
      return (int64_t)off;
    }
  }
  if (need <= h->small_max) {
    int64_t off = list_alloc_first_fit(h, &sh->free_head, need);
    if (off >= 0) {
      sh->cache_bytes -= need;
      return off;
    }
    // Refill the shard cache from the global list: one global-lock trip
    // buys refill_chunk/need future allocations lock-free.
    uint64_t chunk = h->refill_chunk > need ? h->refill_chunk : need;
    lock_mu(&h->mutex);
    int64_t g = list_alloc_first_fit(h, &h->free_head, chunk);
    if (g < 0 && chunk > need) {
      chunk = need;  // global list fragmented: take just what we need
      g = list_alloc_first_fit(h, &h->free_head, chunk);
    }
    if (g >= 0) h->bytes_from_global += chunk;
    unlock_mu(&h->mutex);
    if (g < 0) return -1;
    if (chunk > need) {
      list_insert_ordered(h, &sh->free_head, (uint64_t)g + need,
                          chunk - need);
      sh->cache_bytes += chunk - need;
    }
    return g;
  }
  // Large block: straight from the global extent list.
  lock_mu(&h->mutex);
  int64_t g = list_alloc_first_fit(h, &h->free_head, need);
  if (g >= 0) h->bytes_from_global += need;
  unlock_mu(&h->mutex);
  return g;
}

// caller holds sh->mutex. Flush the shard's cached free blocks back into
// the global list so neighbors from different shards can coalesce.
static void consolidate_shard(Header* h, Shard* sh) {
  lock_mu(&h->mutex);
  for (uint64_t b = 0; b < NUM_FASTBINS; b++) {
    uint64_t cur = sh->fastbin[b];
    sh->fastbin[b] = 0;
    while (cur) {
      FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
      uint64_t next = fb->next;
      h->bytes_from_global -= fb->size;
      list_insert_ordered(h, &h->free_head, cur, fb->size);
      cur = next;
    }
  }
  uint64_t cur = sh->free_head;
  sh->free_head = 0;
  while (cur) {
    FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
    uint64_t next = fb->next;
    h->bytes_from_global -= fb->size;
    list_insert_ordered(h, &h->free_head, cur, fb->size);
    cur = next;
  }
  unlock_mu(&h->mutex);
  sh->cache_bytes = 0;
}

// caller holds sh->mutex. to_global forces the block past the shard cache
// (used by eviction under global pressure, where parking freed bytes in a
// shard cache would strand them from the allocating shard).
static void shard_free(Header* h, Shard* sh, uint64_t off, uint64_t size_raw,
                       bool to_global) {
  uint64_t size = align_up(size_raw < MIN_BLOCK ? MIN_BLOCK : size_raw);
  if (to_global || size > h->small_max) {
    lock_mu(&h->mutex);
    h->bytes_from_global -= size;
    list_insert_ordered(h, &h->free_head, off, size);
    unlock_mu(&h->mutex);
    return;
  }
  if (size <= FASTBIN_MAX) {
    uint64_t bin = size / ALIGN - 1;
    FreeBlock* fb = (FreeBlock*)(arena(h) + off);
    fb->size = size;
    fb->next = sh->fastbin[bin];
    sh->fastbin[bin] = off;
  } else {
    list_insert_ordered(h, &sh->free_head, off, size);
  }
  sh->cache_bytes += size;
  if (sh->cache_bytes >= h->cache_limit) consolidate_shard(h, sh);
}

// ---- slot table (per-shard segments) ----

static Slot* find_slot(Header* h, uint64_t sidx, const uint8_t* id) {
  Slot* tab = shard_table(h, sidx);
  uint64_t mask = h->slots_per_shard - 1;
  uint64_t i = slot_start(h, id);
  for (uint64_t probes = 0; probes < h->slots_per_shard;
       probes++, i = (i + 1) & mask) {
    Slot* s = &tab[i];
    if (s->state == SLOT_EMPTY) return nullptr;
    if (s->state != SLOT_TOMBSTONE && memcmp(s->id, id, 16) == 0) return s;
  }
  return nullptr;
}

static Slot* insert_slot(Header* h, uint64_t sidx, const uint8_t* id) {
  Slot* tab = shard_table(h, sidx);
  uint64_t mask = h->slots_per_shard - 1;
  uint64_t i = slot_start(h, id);
  Slot* reuse = nullptr;
  for (uint64_t probes = 0; probes < h->slots_per_shard;
       probes++, i = (i + 1) & mask) {
    Slot* s = &tab[i];
    if (s->state == SLOT_EMPTY) return reuse ? reuse : s;
    if (s->state == SLOT_TOMBSTONE) { if (!reuse) reuse = s; continue; }
    if (memcmp(s->id, id, 16) == 0) return nullptr;  // exists
  }
  return reuse;  // segment may be all tombstones
}

// Rebuild one shard's segment in place once tombstones dominate: with
// linear probing, chains only terminate at SLOT_EMPTY, so a segment that
// has seen many delete cycles degrades every lookup MISS to O(segment)
// even when nearly empty. Rehashing live entries restores short chains.
static void rehash_shard(Header* h, uint64_t sidx) {
  Shard* sh = shard_at(h, sidx);
  Slot* tab = shard_table(h, sidx);
  uint64_t n = h->slots_per_shard;
  std::vector<Slot> live;
  live.reserve(sh->num_objects + 16);
  for (uint64_t i = 0; i < n; i++)
    if (tab[i].state == SLOT_CREATED || tab[i].state == SLOT_SEALED)
      live.push_back(tab[i]);
  memset(tab, 0, n * sizeof(Slot));
  uint64_t mask = n - 1;
  for (const Slot& s : live) {
    uint64_t i = slot_start(h, s.id);
    while (tab[i].state != SLOT_EMPTY) i = (i + 1) & mask;
    tab[i] = s;
  }
  sh->num_tombstones = 0;
}

// caller holds the shard's mutex
static void evict_entry(Header* h, uint64_t sidx, Slot* s, bool to_global) {
  Shard* sh = shard_at(h, sidx);
  shard_free(h, sh, s->offset, s->data_size + s->meta_size, to_global);
  s->state = SLOT_TOMBSTONE;
  s->refcnt = 0;
  sh->num_objects--;
  if (++sh->num_tombstones > h->slots_per_shard / 4) rehash_shard(h, sidx);
}

// caller holds shard sidx's mutex; oldest sealed refcnt==0 slot or null
static Slot* oldest_evictable(Header* h, uint64_t sidx) {
  Slot* tab = shard_table(h, sidx);
  Slot* victim = nullptr;
  for (uint64_t i = 0; i < h->slots_per_shard; i++) {
    Slot* s = &tab[i];
    if (s->state == SLOT_SEALED && s->refcnt == 0 &&
        (!victim || s->lru_tick < victim->lru_tick))
      victim = s;
  }
  return victim;
}

// Evict sealed refcnt==0 objects until `need` is allocatable: own shard's
// oldest first (exact LRU within the shard), then sweep sibling shards via
// trylock, consolidating their caches so freed bytes reach the global
// list. Approximate-global-LRU across shards — the per-victim full-table
// scan the single-lock store did under one mutex is now a segment scan
// under the victim shard's lock only. Returns offset or -1.
static int64_t alloc_with_eviction(Header* h, uint64_t sidx, uint64_t need) {
  Shard* sh = shard_at(h, sidx);
  bool to_global = align_up(need) > h->small_max;
  int64_t off = shard_alloc(h, sh, need);
  while (off < 0) {
    Slot* victim = oldest_evictable(h, sidx);
    if (victim != nullptr) {
      evict_entry(h, sidx, victim, to_global);
      sh->num_evictions++;
      off = shard_alloc(h, sh, need);
      continue;
    }
    // Own shard dry: flush our cache and sweep siblings for victims.
    consolidate_shard(h, sh);
    off = shard_alloc(h, sh, need);
    if (off >= 0) return off;
    bool progress = false;
    for (uint64_t i = 0; i < h->nshards && off < 0; i++) {
      if (i == sidx) continue;
      Shard* o = shard_at(h, i);
      if (!trylock_mu(&o->mutex)) continue;  // busy: it is making progress
      Slot* v = oldest_evictable(h, i);
      if (v != nullptr) {
        evict_entry(h, i, v, true);
        o->num_evictions++;
        progress = true;
      }
      consolidate_shard(h, o);
      unlock_mu(&o->mutex);
      off = shard_alloc(h, sh, need);
    }
    if (off >= 0) return off;
    if (!progress) return -1;
  }
  return off;
}

// ---- write reservations (per-client lock-free put extents) ----
//
// The multi-client put path: a client carves one large extent under the
// global mutex (store_reserve), bump-allocates object payloads inside it
// with NO shared lock, memcpys each payload lock-free, and publishes each
// finished object as an already-SEALED slot (store_publish — one short
// shard-lock critical section; the state store is the visibility point).
// Unused tail space returns via store_release_extent. Block geometry
// contract: every published object occupies align_up(max(data+meta,
// MIN_BLOCK)) bytes inside the extent — exactly what shard_free returns
// on later eviction/delete, so reservation-born blocks coalesce like any
// other.

static void sweep_evict_all_shards(Header* h, bool* progress) {
  *progress = false;
  for (uint64_t i = 0; i < h->nshards; i++) {
    Shard* sh = shard_at(h, i);
    lock_mu(&sh->mutex);
    Slot* v = oldest_evictable(h, i);
    if (v != nullptr) {
      evict_entry(h, i, v, true);
      sh->num_evictions++;
      *progress = true;
    }
    consolidate_shard(h, sh);
    unlock_mu(&sh->mutex);
  }
}

// ---- owner-affinity hints (process-local gates + in-shm hint table) ----

// Per-process knobs (store_reserve_config): compiled-in defaults ON; the
// Python side lowers them from the put_extent_affinity / put_extent_pretouch
// config knobs at configure time.
static int g_rsv_affinity = 1;
static int g_rsv_pretouch = 1;

void store_reserve_config(int affinity, int pretouch) {
  g_rsv_affinity = affinity;
  g_rsv_pretouch = pretouch;
}

static void aff_note(Header* h, uint64_t pid, uint64_t off, uint64_t size) {
  if (!pid) return;
  AffRec* r = &h->aff_recs[pid % MAX_AFF_RECS];
  __atomic_store_n(&r->off, off, __ATOMIC_RELAXED);
  __atomic_store_n(&r->size, size, __ATOMIC_RELAXED);
  __atomic_store_n(&r->pid, pid, __ATOMIC_RELAXED);
}

// Carve `need` bytes from a free block whose usable span intersects
// [lo, hi) — allocation starts at max(block_start, lo) so a hinted range
// coalesced into a larger block still yields the warm bytes (3-way split:
// head remainder, carve, tail remainder). Caller holds the global mutex.
static int64_t list_alloc_in_range(Header* h, uint64_t* headp, uint64_t lo,
                                   uint64_t hi, uint64_t need) {
  if (hi <= lo || hi - lo < need) return -1;
  uint64_t prev = 0, cur = *headp;
  while (cur) {
    FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
    uint64_t b = cur, e = cur + fb->size, nxt = fb->next;
    uint64_t start = b > lo ? b : lo;
    if (start < hi && start + need <= e) {
      // unlink the block, re-insert the remainders
      if (prev) ((FreeBlock*)(arena(h) + prev))->next = nxt;
      else *headp = nxt;
      if (start > b) list_insert_ordered(h, headp, b, start - b);
      if (e > start + need)
        list_insert_ordered(h, headp, start + need, e - (start + need));
      return (int64_t)start;
    }
    prev = cur;
    cur = nxt;
  }
  return -1;
}

// Pre-fault a carved extent so the client's bump-fill memcpys never
// minor-fault mid-copy: MADV_POPULATE_WRITE where the kernel has it,
// else one write per page (the bytes are ours and uninitialized).
#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23
#endif
static void pretouch(char* p, uint64_t n) {
  uint64_t page = 4096;
  uint64_t lo = (uint64_t)p & ~(page - 1);
  uint64_t hi = ((uint64_t)p + n + page - 1) & ~(page - 1);
  if (madvise((void*)lo, hi - lo, MADV_POPULATE_WRITE) == 0) return;
  for (volatile char* q = (volatile char*)p; q < (volatile char*)(p + n);
       q += page)
    *q = *q;
  if (n) { volatile char* q = (volatile char*)(p + n - 1); *q = *q; }
}

// Find the active record whose extent contains arena-relative `off`, or
// null. Records are few and mutate rarely; the scan is lock-free (active
// flips 0->1 with release ordering after the fields are written, and only
// the owner — or the sweeper, for a DEAD owner — flips it back).
static RsvRec* rsv_find(Header* h, uint64_t off) {
  for (uint64_t i = 0; i < MAX_RSV_RECS; i++) {
    RsvRec* r = &h->rsv_recs[i];
    if (!__atomic_load_n(&r->active, __ATOMIC_ACQUIRE)) continue;
    // Atomic field reads: a sibling thread may be re-initializing a
    // RETIRED record slot concurrently; active's acquire/release pairing
    // guarantees the fields are consistent whenever active reads 1, and
    // the atomics keep the (ignored) racing reads untorn.
    uint64_t ro = __atomic_load_n(&r->off, __ATOMIC_RELAXED);
    uint64_t rs = __atomic_load_n(&r->size, __ATOMIC_RELAXED);
    if (off >= ro && off < ro + rs) return r;
  }
  return nullptr;
}

// Owner-side accounting for bytes leaving the "reserved, unaccounted"
// state (a publish or an explicit release): the record self-retires when
// nothing unpublished remains, so a cleanly drained extent needs no
// explicit close call and the record slot recycles.
static void rsv_account(Header* h, uint64_t off, uint64_t bytes) {
  RsvRec* r = rsv_find(h, off);
  if (r == nullptr) return;  // unrecorded extent (table was full)
  uint64_t left =
      __atomic_sub_fetch(&r->unpublished, bytes, __ATOMIC_RELAXED);
  if (left == 0
      || left > __atomic_load_n(&r->size, __ATOMIC_RELAXED)) {
    // drained (or accounting drift): retire the record slot, leaving an
    // owner-affinity hint behind — the drained extent's pages are warm in
    // this pid's page table, so its NEXT reserve should carve from here.
    aff_note(h, __atomic_load_n(&r->pid, __ATOMIC_RELAXED),
             __atomic_load_n(&r->off, __ATOMIC_RELAXED),
             __atomic_load_n(&r->size, __ATOMIC_RELAXED));
    __atomic_store_n(&r->active, 0, __ATOMIC_RELEASE);
  }
}

// Carve a raw extent of `size` bytes; *out_offset is ABSOLUTE (from
// base), like store_create's. Evicts sealed refcnt==0 objects across all
// shards under pressure. Returns OK or ERR_FULL. The extent is recorded
// with this process's pid so store_reclaim_orphans can return it if the
// owner dies before publishing/releasing every byte.
int store_reserve(void* base, uint64_t size, uint64_t* out_offset) {
  Header* h = (Header*)base;
  uint64_t need = align_up(size < MIN_BLOCK ? MIN_BLOCK : size);
  uint64_t self = (uint64_t)getpid();
  // Owner-affinity probe (advisory hint; relaxed reads — the free list
  // walk below is the truth): prefer bytes this pid drained before.
  uint64_t aff_lo = 0, aff_hi = 0;
  if (g_rsv_affinity) {
    AffRec* ar = &h->aff_recs[self % MAX_AFF_RECS];
    if (__atomic_load_n(&ar->pid, __ATOMIC_RELAXED) == self) {
      aff_lo = __atomic_load_n(&ar->off, __ATOMIC_RELAXED);
      aff_hi = aff_lo + __atomic_load_n(&ar->size, __ATOMIC_RELAXED);
      if (aff_hi <= aff_lo || aff_hi > h->arena_size) aff_lo = aff_hi = 0;
    }
  }
  for (;;) {
    lock_mu(&h->mutex);
    int64_t off = -1;
    if (aff_hi > aff_lo) {
      off = list_alloc_in_range(h, &h->free_head, aff_lo, aff_hi, need);
      if (off >= 0)
        __atomic_add_fetch(&h->num_aff_hits, 1, __ATOMIC_RELAXED);
    }
    if (off < 0) off = list_alloc_first_fit(h, &h->free_head, need);
    if (off >= 0) {
      h->bytes_from_global += need;
      // Register ownership INSIDE the critical section: a death after
      // unlock leaves a consistent (counted + recorded) extent for the
      // sweeper. Table full => proceed unrecorded (no crash protection
      // for this extent; 256 concurrent extents per node is the bound).
      for (uint64_t i = 0; i < MAX_RSV_RECS; i++) {
        RsvRec* r = &h->rsv_recs[i];
        if (__atomic_load_n(&r->active, __ATOMIC_RELAXED)) continue;
        __atomic_store_n(&r->pid, (uint64_t)getpid(), __ATOMIC_RELAXED);
        __atomic_store_n(&r->off, (uint64_t)off, __ATOMIC_RELAXED);
        __atomic_store_n(&r->size, need, __ATOMIC_RELAXED);
        __atomic_store_n(&r->unpublished, need, __ATOMIC_RELAXED);
        __atomic_store_n(&r->active, 1, __ATOMIC_RELEASE);
        break;
      }
      __atomic_add_fetch(&h->num_reserves, 1, __ATOMIC_RELAXED);
      __atomic_add_fetch(&h->rsv_unused_bytes, need, __ATOMIC_RELAXED);
    }
    unlock_mu(&h->mutex);
    if (off >= 0) {
      *out_offset = h->arena_offset + (uint64_t)off;
      if (g_rsv_pretouch)
        pretouch(arena(h) + (uint64_t)off, need);
      return OK;
    }
    bool progress = false;
    sweep_evict_all_shards(h, &progress);
    if (!progress) return ERR_FULL;
  }
}

uint64_t store_aff_hits(void* base) {
  return __atomic_load_n(&((Header*)base)->num_aff_hits, __ATOMIC_RELAXED);
}

// Return an unused reservation slice (tail, aborted chunk, or the whole
// extent) to the global list. abs_offset/size must delimit bytes that
// were reserved and never published.
int store_release_extent(void* base, uint64_t abs_offset, uint64_t size) {
  Header* h = (Header*)base;
  if (size == 0) return OK;
  uint64_t off = abs_offset - h->arena_offset;
  lock_mu(&h->mutex);
  h->bytes_from_global -= size;
  list_insert_ordered(h, &h->free_head, off, size);
  unlock_mu(&h->mutex);
  __atomic_sub_fetch(&h->rsv_unused_bytes, size, __ATOMIC_RELAXED);
  // The released slice is warm in this pid's page table — hint the next
  // reserve at it even when the record has publishes still outstanding.
  aff_note(h, (uint64_t)getpid(), off, size);
  rsv_account(h, off, size);
  return OK;
}

// Publish a filled reservation chunk as a sealed object. The data +
// metadata bytes are already in place at abs_offset; this inserts the
// slot (SEALED, refcnt 0) under the shard lock — the single point where
// the object becomes visible to store_get.
int store_publish(void* base, const uint8_t* id, uint64_t abs_offset,
                  uint64_t data_size, uint64_t meta_size) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  uint64_t raw = data_size + meta_size;
  uint64_t block = align_up(raw < MIN_BLOCK ? MIN_BLOCK : raw);
  lock_mu(&sh->mutex);
  Slot* s = insert_slot(h, sidx, id);
  if (s == nullptr) {
    int rc = find_slot(h, sidx, id) ? ERR_EXISTS : ERR_TABLE_FULL;
    unlock_mu(&sh->mutex);
    return rc;
  }
  memcpy(s->id, id, 16);
  s->offset = abs_offset - h->arena_offset;
  s->data_size = data_size;
  s->meta_size = meta_size;
  if (s->state == SLOT_TOMBSTONE) sh->num_tombstones--;
  s->refcnt = 0;
  s->lru_tick = next_tick(h);
  s->pending_delete = 0;
  __atomic_store_n(&s->state, (uint32_t)SLOT_SEALED, __ATOMIC_RELEASE);
  sh->num_objects++;
  unlock_mu(&sh->mutex);
  __atomic_sub_fetch(&h->rsv_unused_bytes, block, __ATOMIC_RELAXED);
  rsv_account(h, abs_offset - h->arena_offset, block);
  return OK;
}

uint64_t store_num_reserves(void* base) {
  return __atomic_load_n(&((Header*)base)->num_reserves, __ATOMIC_RELAXED);
}

uint64_t store_rsv_unused(void* base) {
  return __atomic_load_n(&((Header*)base)->rsv_unused_bytes,
                         __ATOMIC_RELAXED);
}

// ---- orphaned-reservation reclamation (pid-liveness sweep) ----
//
// A client SIGKILLed between store_reserve and its final store_publish /
// store_release_extent leaves (a) the extent's unaccounted bytes carved
// out of the global list forever and (b) rsv_unused_bytes inflated by the
// same amount — stats under-report "allocated" and the spill policy can
// wedge. The sweep: for every active record whose pid no longer exists,
// compute which bytes of [off, off+size) are ACCOUNTED FOR elsewhere
// (live slots the client published before dying; free-list blocks from
// slices it released or published-then-evicted) and return every
// remaining gap to the global free list, repairing both counters.

static bool pid_alive(uint64_t pid) {
  if (pid == 0) return true;  // unknown owner: never reclaim
  if (kill((pid_t)pid, 0) == 0) return true;
  return errno != ESRCH;  // EPERM = alive under another uid
}

// Collect [lo,hi)-clamped intervals of one free list into `iv`.
static void collect_list(Header* h, uint64_t head, uint64_t lo, uint64_t hi,
                         std::vector<std::pair<uint64_t, uint64_t>>* iv) {
  for (uint64_t cur = head; cur;) {
    FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
    uint64_t b = cur, e = cur + fb->size;
    if (b < hi && e > lo)
      iv->push_back({b < lo ? lo : b, e > hi ? hi : e});
    cur = fb->next;
  }
}

// Reclaim one dead record. Caller holds NO locks; takes every shard
// mutex then the global mutex (the store's shard->global lock order).
static int64_t reclaim_record(Header* h, uint64_t ri) {
  for (uint64_t i = 0; i < h->nshards; i++) lock_mu(&shard_at(h, i)->mutex);
  lock_mu(&h->mutex);
  RsvRec* rec = &h->rsv_recs[ri];
  int64_t freed = 0;
  if (__atomic_load_n(&rec->active, __ATOMIC_ACQUIRE)
      && !pid_alive(rec->pid)) {
    uint64_t lo = rec->off, hi = rec->off + rec->size;
    std::vector<std::pair<uint64_t, uint64_t>> iv;
    // Live slots published into the extent (block footprint, align_up —
    // the geometry contract shared with eviction).
    for (uint64_t si = 0; si < h->nshards; si++) {
      Slot* tab = shard_table(h, si);
      for (uint64_t i = 0; i < h->slots_per_shard; i++) {
        Slot* s = &tab[i];
        if (s->state != SLOT_CREATED && s->state != SLOT_SEALED) continue;
        uint64_t raw = s->data_size + s->meta_size;
        uint64_t blk = align_up(raw < MIN_BLOCK ? MIN_BLOCK : raw);
        uint64_t b = s->offset, e = s->offset + blk;
        if (b < hi && e > lo)
          iv.push_back({b < lo ? lo : b, e > hi ? hi : e});
      }
    }
    // Free bytes already returned (released slices, evicted publishes —
    // possibly coalesced across the extent boundary, hence the clamp).
    collect_list(h, h->free_head, lo, hi, &iv);
    for (uint64_t si = 0; si < h->nshards; si++) {
      Shard* sh = shard_at(h, si);
      collect_list(h, sh->free_head, lo, hi, &iv);
      for (uint64_t b = 0; b < NUM_FASTBINS; b++)
        collect_list(h, sh->fastbin[b], lo, hi, &iv);
    }
    std::sort(iv.begin(), iv.end());
    // Walk the gaps: bytes of the dead extent no structure accounts for.
    uint64_t cursor = lo;
    auto free_gap = [&](uint64_t b, uint64_t e) {
      if (e <= b) return;
      list_insert_ordered(h, &h->free_head, b, e - b);
      h->bytes_from_global -= e - b;
      freed += (int64_t)(e - b);
    };
    for (auto& p : iv) {
      if (p.first > cursor) free_gap(cursor, p.first);
      if (p.second > cursor) cursor = p.second;
    }
    free_gap(cursor, hi);
    if (freed > 0) {
      uint64_t cur =
          __atomic_load_n(&h->rsv_unused_bytes, __ATOMIC_RELAXED);
      uint64_t sub = (uint64_t)freed < cur ? (uint64_t)freed : cur;
      __atomic_sub_fetch(&h->rsv_unused_bytes, sub, __ATOMIC_RELAXED);
    }
    __atomic_store_n(&rec->active, 0, __ATOMIC_RELEASE);
  }
  unlock_mu(&h->mutex);
  for (uint64_t i = h->nshards; i-- > 0;)
    unlock_mu(&shard_at(h, i)->mutex);
  return freed;
}

// Sweep every active record for dead owners; returns bytes reclaimed.
// Cheap when nothing died: one lock-free record scan + one kill(pid, 0)
// per live extent — safe to call from heartbeat/pressure paths.
int64_t store_reclaim_orphans(void* base) {
  Header* h = (Header*)base;
  uint64_t self = (uint64_t)getpid();
  int64_t total = 0;
  for (uint64_t i = 0; i < MAX_RSV_RECS; i++) {
    RsvRec* rec = &h->rsv_recs[i];
    if (!__atomic_load_n(&rec->active, __ATOMIC_ACQUIRE)) continue;
    uint64_t pid = __atomic_load_n(&rec->pid, __ATOMIC_RELAXED);
    if (pid == self || pid_alive(pid)) continue;
    total += reclaim_record(h, i);
  }
  return total;
}

void store_copy_adaptive(void* base, void* dst, const void* src, uint64_t n,
                         int max_threads) {
  Header* h = (Header*)base;
  uint64_t active =
      __atomic_add_fetch(&h->active_copiers, 1, __ATOMIC_RELAXED);
  int threads = max_threads / (int)(active ? active : 1);
  if (threads < 1) threads = 1;
  store_memcpy(dst, src, n, threads);
  __atomic_sub_fetch(&h->active_copiers, 1, __ATOMIC_RELAXED);
}

// ---- public API ----

int store_init(void* base, uint64_t total_size, uint64_t num_slots,
               uint64_t nshards) {
  Header* h = (Header*)base;
  memset(h, 0, sizeof(Header));
  h->magic = MAGIC;
  h->total_size = total_size;
  if (nshards < 1) nshards = 1;
  if (nshards > MAX_SHARDS) nshards = MAX_SHARDS;
  while (nshards & (nshards - 1)) nshards &= nshards - 1;  // round down pow2
  h->nshards = nshards;
  uint64_t per = num_slots / nshards;
  uint64_t p2 = 64;
  while (p2 < per) p2 <<= 1;
  h->slots_per_shard = p2;
  uint64_t shards_bytes = nshards * sizeof(Shard);
  uint64_t table_bytes = nshards * h->slots_per_shard * sizeof(Slot);
  h->table_offset = align_up(sizeof(Header) + shards_bytes);
  h->arena_offset = align_up(h->table_offset + table_bytes);
  if (h->arena_offset + MIN_BLOCK * 2 > total_size) return ERR_FULL;
  h->arena_size = total_size - h->arena_offset;

  // Shard-cache tuning: refills large enough to amortize the global lock,
  // small enough that N idle caches can't strand a meaningful arena slice.
  uint64_t refill = h->arena_size / (nshards * 16);
  if (refill < (64u << 10)) refill = 64u << 10;
  if (refill > (4u << 20)) refill = 4u << 20;
  h->refill_chunk = align_up(refill);
  h->small_max = SMALL_MAX < h->refill_chunk ? SMALL_MAX : h->refill_chunk;
  h->cache_limit = h->refill_chunk * 4;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  memset((char*)h + sizeof(Header), 0, shards_bytes);
  memset(shard_table(h, 0), 0, table_bytes);
  for (uint64_t i = 0; i < nshards; i++) {
    Shard* sh = shard_at(h, i);
    pthread_mutex_init(&sh->mutex, &attr);
    sh->canary = SHARD_CANARY;
  }
  pthread_mutexattr_destroy(&attr);

  // Reserve the first ALIGN bytes so offset 0 means "no block".
  h->free_head = ALIGN;
  FreeBlock* fb = (FreeBlock*)(arena(h) + ALIGN);
  fb->size = align_up(h->arena_size - ALIGN) - ALIGN;
  if (fb->size > h->arena_size - ALIGN) fb->size = h->arena_size - ALIGN;
  fb->size &= ~(ALIGN - 1);
  fb->next = 0;
  return OK;
}

int store_validate(void* base) {
  Header* h = (Header*)base;
  if (h->magic != MAGIC) return ERR_CORRUPT;
  if (h->nshards < 1 || h->nshards > MAX_SHARDS ||
      (h->nshards & (h->nshards - 1)))
    return ERR_CORRUPT;
  if (h->arena_offset + h->arena_size > h->total_size) return ERR_CORRUPT;
  for (uint64_t i = 0; i < h->nshards; i++)
    if (shard_at(h, i)->canary != SHARD_CANARY) return ERR_CORRUPT;
  return OK;
}

uint64_t store_num_shards(void* base) { return ((Header*)base)->nshards; }

// Creates an unsealed object and returns the absolute byte offset (from base)
// where the caller should write data_size bytes of data then meta_size bytes
// of metadata, then call store_seal.
int store_create(void* base, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size, uint64_t* out_offset) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  if (find_slot(h, sidx, id)) { unlock_mu(&sh->mutex); return ERR_EXISTS; }
  // Allocate BEFORE claiming a slot: eviction inside the allocator can
  // trip the tombstone rehash, which relocates the shard's slot segment
  // and would invalidate a Slot* held across the call.
  int64_t off = alloc_with_eviction(h, sidx, data_size + meta_size);
  if (off < 0) { unlock_mu(&sh->mutex); return ERR_FULL; }
  Slot* s = insert_slot(h, sidx, id);
  if (!s) {
    shard_free(h, sh, (uint64_t)off, data_size + meta_size, false);
    unlock_mu(&sh->mutex);
    return ERR_TABLE_FULL;
  }
  memcpy(s->id, id, 16);
  s->offset = (uint64_t)off;
  s->data_size = data_size;
  s->meta_size = meta_size;
  if (s->state == SLOT_TOMBSTONE) sh->num_tombstones--;
  s->state = SLOT_CREATED;
  s->refcnt = 1;  // creator holds a ref until seal+release
  s->lru_tick = next_tick(h);
  s->pending_delete = 0;
  sh->num_objects++;
  *out_offset = h->arena_offset + (uint64_t)off;
  unlock_mu(&sh->mutex);
  return OK;
}

int store_seal(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  Slot* s = find_slot(h, sidx, id);
  if (!s) { unlock_mu(&sh->mutex); return ERR_NOTFOUND; }
  s->state = SLOT_SEALED;
  s->refcnt--;  // drop creator ref
  unlock_mu(&sh->mutex);
  return OK;
}

// On success takes a reference; caller must store_release when done with the
// memory. Returns absolute offset + sizes.
int store_get(void* base, const uint8_t* id, uint64_t* out_offset,
              uint64_t* out_data_size, uint64_t* out_meta_size) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  Slot* s = find_slot(h, sidx, id);
  if (!s) { unlock_mu(&sh->mutex); return ERR_NOTFOUND; }
  if (s->state != SLOT_SEALED) { unlock_mu(&sh->mutex); return ERR_AGAIN; }
  s->refcnt++;
  s->lru_tick = next_tick(h);
  *out_offset = h->arena_offset + s->offset;
  *out_data_size = s->data_size;
  *out_meta_size = s->meta_size;
  unlock_mu(&sh->mutex);
  return OK;
}

int store_release(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  Slot* s = find_slot(h, sidx, id);
  if (!s) { unlock_mu(&sh->mutex); return ERR_NOTFOUND; }
  if (s->refcnt > 0) s->refcnt--;
  if (s->pending_delete && s->refcnt == 0)
    evict_entry(h, sidx, s, false);
  unlock_mu(&sh->mutex);
  return OK;
}

int store_contains(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  Slot* s = find_slot(h, sidx, id);
  int rc = (s && s->state == SLOT_SEALED) ? 1 : 0;
  unlock_mu(&sh->mutex);
  return rc;
}

// Abort an unsealed create (e.g. writer failed mid-copy).
int store_abort(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  Slot* s = find_slot(h, sidx, id);
  if (!s) { unlock_mu(&sh->mutex); return ERR_NOTFOUND; }
  if (s->state == SLOT_CREATED) {
    evict_entry(h, sidx, s, false);
    unlock_mu(&sh->mutex);
    return OK;
  }
  unlock_mu(&sh->mutex);
  return ERR_BUSY;
}

int store_delete(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  uint64_t sidx = shard_of(h, id);
  Shard* sh = shard_at(h, sidx);
  lock_mu(&sh->mutex);
  Slot* s = find_slot(h, sidx, id);
  if (!s) { unlock_mu(&sh->mutex); return ERR_NOTFOUND; }
  if (s->refcnt > 0) {
    s->pending_delete = 1;  // freed on last release
    unlock_mu(&sh->mutex);
    return OK;
  }
  evict_entry(h, sidx, s, false);
  unlock_mu(&sh->mutex);
  return OK;
}

// LOCK-FREE: stats feed monitoring and the spill-threshold heuristic,
// which tolerate a momentarily torn sum — taking the global plus every
// shard mutex here would re-serialize the very put path the sharding
// unlocked (the head-node spill check reads stats on EVERY worker put).
void store_stats(void* base, uint64_t* out_allocated, uint64_t* out_capacity,
                 uint64_t* out_num_objects, uint64_t* out_num_evictions) {
  Header* h = (Header*)base;
  uint64_t allocated =
      __atomic_load_n(&h->bytes_from_global, __ATOMIC_RELAXED);
  uint64_t nobj = 0, nevict = 0, cached = 0;
  for (uint64_t i = 0; i < h->nshards; i++) {
    Shard* sh = shard_at(h, i);
    nobj += __atomic_load_n(&sh->num_objects, __ATOMIC_RELAXED);
    nevict += __atomic_load_n(&sh->num_evictions, __ATOMIC_RELAXED);
    cached += __atomic_load_n(&sh->cache_bytes, __ATOMIC_RELAXED);
  }
  // Bytes parked in shard caches are free capacity, not live objects —
  // and so are reserved-but-unpublished reservation slices (counting
  // them would trip the spill policy on parked headroom).
  cached += __atomic_load_n(&h->rsv_unused_bytes, __ATOMIC_RELAXED);
  *out_allocated = allocated > cached ? allocated - cached : 0;
  *out_capacity = h->arena_size;
  *out_num_objects = nobj;
  *out_num_evictions = nevict;
}

uint64_t store_header_size() { return sizeof(Header); }

// Write the 16-byte ids of all sealed objects into `out` (room for
// max_ids). Returns the number written. Used to rebuild a restarted
// head's object directory from each node's surviving arena (parity:
// raylets resyncing object locations with a restarted GCS).
int64_t store_list_ids(void* base, uint8_t* out, uint64_t max_ids) {
  Header* h = (Header*)base;
  uint64_t n = 0;
  for (uint64_t si = 0; si < h->nshards; si++) {
    Shard* sh = shard_at(h, si);
    Slot* tab = shard_table(h, si);
    lock_mu(&sh->mutex);
    for (uint64_t i = 0; i < h->slots_per_shard && n < max_ids; i++) {
      if (tab[i].state == SLOT_SEALED) {
        memcpy(out + n * 16, tab[i].id, 16);
        n++;
      }
    }
    unlock_mu(&sh->mutex);
    if (n >= max_ids) break;
  }
  return (int64_t)n;
}

}  // extern "C"
