// Shared-memory object store: a single mmap'd arena shared by every process on a
// node, with an in-shm object index and allocator so create/seal/get/release are
// direct memory operations under a robust process-shared mutex — no broker
// round-trip.
//
// Parity: reference `src/ray/object_manager/plasma/` (PlasmaStore store.h:55,
// dlmalloc arena, eviction_policy.h LRU, create_request_queue.h backpressure).
// Design departure: plasma brokers create/get through a unix-socket server and
// passes fds; here clients map the arena directly and synchronize through a
// robust pthread mutex in shm, which removes the per-op socket round trip
// (the main cost in plasma's put/get calls/s) while keeping zero-copy reads.
//
// Layout:
//   [Header | slot table (open addressing) | arena]
// Free blocks form an address-ordered singly-linked list for O(1) coalescing.
//
// All functions return 0 on success or a negative StoreStatus.

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

#include <thread>
#include <vector>

extern "C" {

// Parallel memcpy for large objects: a single core's memcpy (~14 GB/s) is
// half the put_gigabytes baseline; on multi-core hosts splitting the copy
// across threads saturates DRAM bandwidth instead. Caller releases the GIL
// (ctypes does this automatically), so worker threads run truly parallel.
void store_memcpy(void* dst, const void* src, uint64_t n, int nthreads) {
  if (nthreads <= 1 || n < (8u << 20)) {
    memcpy(dst, src, n);
    return;
  }
  uint64_t chunk = (n + nthreads - 1) / nthreads;
  chunk = (chunk + 63) & ~63ULL;  // cache-line aligned splits
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  for (uint64_t off = 0; off < n; off += chunk) {
    uint64_t len = off + chunk <= n ? chunk : n - off;
    ts.emplace_back([=] { memcpy((char*)dst + off, (const char*)src + off, len); });
  }
  for (auto& t : ts) t.join();
}

enum StoreStatus {
  OK = 0,
  ERR_NOTFOUND = -1,
  ERR_AGAIN = -2,       // object exists but not sealed yet
  ERR_EXISTS = -3,
  ERR_FULL = -4,        // no space even after eviction
  ERR_TABLE_FULL = -5,
  ERR_BUSY = -6,        // delete refused: nonzero refcount
  ERR_CORRUPT = -7,
};

static const uint64_t MAGIC = 0x5241595F54505531ULL;  // "RAY_TPU1"
static const uint64_t ALIGN = 64;
static const uint64_t MIN_BLOCK = 128;

enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_CREATED = 1,
  SLOT_SEALED = 2,
  SLOT_TOMBSTONE = 3,
};

struct Slot {
  uint8_t id[16];
  uint64_t offset;     // arena-relative offset of data
  uint64_t data_size;
  uint64_t meta_size;  // metadata stored immediately after data
  uint32_t state;
  int32_t refcnt;
  uint64_t lru_tick;
  uint32_t pending_delete;
  uint32_t _pad;
};  // 64 bytes

struct FreeBlock {
  uint64_t size;
  uint64_t next;  // arena-relative offset of next free block, or 0 (arena off 0 is never free: we reserve first ALIGN bytes)
};

// Small freed blocks park in size-class fastbins (O(1) push/pop, one
// singly-linked list per size class) instead of the address-ordered main
// list, whose ordered insert is O(free blocks) — under small-object churn
// (thousands of task results freed per second) that walk turned every
// delete quadratic. Fastbins consolidate back into the main list (where
// coalescing happens) past a byte threshold or on allocation pressure —
// the dlmalloc fastbin design the reference's plasma store inherits.
static const uint64_t FASTBIN_MAX = 2048;   // largest fastbinned block
static const uint64_t NUM_FASTBINS = FASTBIN_MAX / ALIGN;  // 64..2048 step 64
static const uint64_t FASTBIN_CONSOLIDATE_BYTES = 8u << 20;

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t num_slots;
  uint64_t arena_offset;   // from base
  uint64_t arena_size;
  pthread_mutex_t mutex;
  uint64_t free_head;      // arena-relative, 0 = none
  uint64_t lru_clock;
  uint64_t bytes_allocated;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t fastbin[NUM_FASTBINS];  // arena-relative heads, 0 = empty
  uint64_t fastbin_bytes;
  uint64_t num_tombstones;
};

static inline Slot* slots(Header* h) {
  return (Slot*)((char*)h + sizeof(Header));
}
static inline char* arena(Header* h) { return (char*)h + h->arena_offset; }

static inline uint64_t hash_id(const uint8_t* id) {
  uint64_t x;
  memcpy(&x, id, 8);
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL; x ^= x >> 33;
  return x;
}

static void lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock; shm metadata is still consistent because
    // every mutation below completes all pointer updates before unlock and a
    // half-written object is just an unsealed slot (evictable).
    pthread_mutex_consistent(&h->mutex);
  }
}
static void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

// ---- allocator: address-ordered first-fit free list in the arena ----

static uint64_t align_up(uint64_t v) { return (v + ALIGN - 1) & ~(ALIGN - 1); }

static void consolidate_fastbins(Header* h);
static int64_t alloc_block_main(Header* h, uint64_t need);
static void insert_ordered(Header* h, uint64_t off, uint64_t size);

static int64_t alloc_block(Header* h, uint64_t need) {
  need = align_up(need < MIN_BLOCK ? MIN_BLOCK : need);
  if (need <= FASTBIN_MAX) {
    uint64_t bin = need / ALIGN - 1;
    uint64_t off = h->fastbin[bin];
    if (off) {  // exact-size hit: O(1), no list walk
      FreeBlock* fb = (FreeBlock*)(arena(h) + off);
      h->fastbin[bin] = fb->next;
      h->fastbin_bytes -= fb->size;
      h->bytes_allocated += fb->size;
      return (int64_t)off;
    }
  }
  for (int pass = 0; pass < 2; pass++) {
    if (pass) {  // main list exhausted: merge the fastbin cache back in
      if (!h->fastbin_bytes) break;
      consolidate_fastbins(h);
    }
    int64_t got = alloc_block_main(h, need);
    if (got >= 0) return got;
  }
  return -1;
}

static int64_t alloc_block_main(Header* h, uint64_t need) {
  uint64_t prev = 0;
  uint64_t cur = h->free_head;
  while (cur) {
    FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
    if (fb->size >= need) {
      uint64_t rem = fb->size - need;
      // All sizes are ALIGN multiples, so rem is 0 or >= ALIGN: a nonzero
      // remainder is always splittable and the absorb branch only fires at
      // rem == 0 (so freeing align_up(data+meta) later returns exactly what
      // was allocated — no leaked tail).
      if (rem >= ALIGN) {
        uint64_t newoff = cur + need;
        FreeBlock* nb = (FreeBlock*)(arena(h) + newoff);
        nb->size = rem;
        nb->next = fb->next;
        if (prev) ((FreeBlock*)(arena(h) + prev))->next = newoff;
        else h->free_head = newoff;
      } else {
        need = fb->size;  // absorb remainder
        if (prev) ((FreeBlock*)(arena(h) + prev))->next = fb->next;
        else h->free_head = fb->next;
      }
      h->bytes_allocated += need;
      return (int64_t)cur;
    }
    prev = cur;
    cur = fb->next;
  }
  return -1;
}

static void free_block(Header* h, uint64_t off, uint64_t size) {
  size = align_up(size < MIN_BLOCK ? MIN_BLOCK : size);
  h->bytes_allocated -= size;
  if (size <= FASTBIN_MAX) {
    uint64_t bin = size / ALIGN - 1;
    FreeBlock* fb = (FreeBlock*)(arena(h) + off);
    fb->size = size;
    fb->next = h->fastbin[bin];
    h->fastbin[bin] = off;
    h->fastbin_bytes += size;
    if (h->fastbin_bytes >= FASTBIN_CONSOLIDATE_BYTES)
      consolidate_fastbins(h);
    return;
  }
  insert_ordered(h, off, size);
}

static void consolidate_fastbins(Header* h) {
  for (uint64_t b = 0; b < NUM_FASTBINS; b++) {
    uint64_t cur = h->fastbin[b];
    h->fastbin[b] = 0;
    while (cur) {
      FreeBlock* fb = (FreeBlock*)(arena(h) + cur);
      uint64_t next = fb->next;
      insert_ordered(h, cur, fb->size);
      cur = next;
    }
  }
  h->fastbin_bytes = 0;
}

static void insert_ordered(Header* h, uint64_t off, uint64_t size) {
  // insert address-ordered, coalesce with neighbors
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = ((FreeBlock*)(arena(h) + cur))->next;
  }
  FreeBlock* nb = (FreeBlock*)(arena(h) + off);
  nb->size = size;
  nb->next = cur;
  if (prev) {
    FreeBlock* pb = (FreeBlock*)(arena(h) + prev);
    pb->next = off;
    if (prev + pb->size == off) {  // coalesce prev+new
      pb->size += nb->size;
      pb->next = nb->next;
      nb = pb;
      off = prev;
    }
  } else {
    h->free_head = off;
  }
  if (nb->next && off + nb->size == nb->next) {  // coalesce new+next
    FreeBlock* nx = (FreeBlock*)(arena(h) + nb->next);
    nb->size += nx->size;
    nb->next = nx->next;
  }
}

// ---- slot table ----

static Slot* find_slot(Header* h, const uint8_t* id) {
  uint64_t mask = h->num_slots - 1;
  uint64_t i = hash_id(id) & mask;
  for (uint64_t probes = 0; probes < h->num_slots; probes++, i = (i + 1) & mask) {
    Slot* s = &slots(h)[i];
    if (s->state == SLOT_EMPTY) return nullptr;
    if (s->state != SLOT_TOMBSTONE && memcmp(s->id, id, 16) == 0) return s;
  }
  return nullptr;
}

static Slot* insert_slot(Header* h, const uint8_t* id) {
  uint64_t mask = h->num_slots - 1;
  uint64_t i = hash_id(id) & mask;
  Slot* reuse = nullptr;
  for (uint64_t probes = 0; probes < h->num_slots; probes++, i = (i + 1) & mask) {
    Slot* s = &slots(h)[i];
    if (s->state == SLOT_EMPTY) return reuse ? reuse : s;
    if (s->state == SLOT_TOMBSTONE) { if (!reuse) reuse = s; continue; }
    if (memcmp(s->id, id, 16) == 0) return nullptr;  // exists
  }
  return reuse;  // table may be all tombstones
}

// Rebuild the table in place once tombstones dominate: with linear
// probing, chains only terminate at SLOT_EMPTY, so a table that has seen
// many delete cycles degrades every lookup MISS to O(num_slots) even when
// nearly empty. Rehashing live entries restores short chains.
static void rehash_table(Header* h) {
  Slot* tab = slots(h);
  uint64_t n = h->num_slots;
  std::vector<Slot> live;
  live.reserve(h->num_objects + 16);
  for (uint64_t i = 0; i < n; i++)
    if (tab[i].state == SLOT_CREATED || tab[i].state == SLOT_SEALED)
      live.push_back(tab[i]);
  memset(tab, 0, n * sizeof(Slot));
  uint64_t mask = n - 1;
  for (const Slot& s : live) {
    uint64_t i = hash_id(s.id) & mask;
    while (tab[i].state != SLOT_EMPTY) i = (i + 1) & mask;
    tab[i] = s;
  }
  h->num_tombstones = 0;
}

static void evict_entry(Header* h, Slot* s) {
  free_block(h, s->offset, s->data_size + s->meta_size);
  s->state = SLOT_TOMBSTONE;
  s->refcnt = 0;
  h->num_objects--;
  if (++h->num_tombstones > h->num_slots / 4) rehash_table(h);
}

// Evict sealed refcnt==0 objects (oldest lru first) until `need` is allocatable.
// Returns offset or -1.
static int64_t alloc_with_eviction(Header* h, uint64_t need) {
  int64_t off = alloc_block(h, need);
  while (off < 0) {
    Slot* victim = nullptr;
    for (uint64_t i = 0; i < h->num_slots; i++) {
      Slot* s = &slots(h)[i];
      if (s->state == SLOT_SEALED && s->refcnt == 0 &&
          (!victim || s->lru_tick < victim->lru_tick))
        victim = s;
    }
    if (!victim) return -1;
    evict_entry(h, victim);
    h->num_evictions++;
    off = alloc_block(h, need);
  }
  return off;
}

// ---- public API ----

int store_init(void* base, uint64_t total_size, uint64_t num_slots) {
  Header* h = (Header*)base;
  memset(h, 0, sizeof(Header));
  h->magic = MAGIC;
  h->total_size = total_size;
  h->num_slots = num_slots;
  uint64_t table_bytes = num_slots * sizeof(Slot);
  h->arena_offset = align_up(sizeof(Header) + table_bytes);
  if (h->arena_offset + MIN_BLOCK * 2 > total_size) return ERR_FULL;
  h->arena_size = total_size - h->arena_offset;
  memset(slots(h), 0, table_bytes);

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // Reserve the first ALIGN bytes so offset 0 means "no block".
  h->free_head = ALIGN;
  FreeBlock* fb = (FreeBlock*)(arena(h) + ALIGN);
  fb->size = align_up(h->arena_size - ALIGN) - ALIGN;
  if (fb->size > h->arena_size - ALIGN) fb->size = h->arena_size - ALIGN;
  fb->size &= ~(ALIGN - 1);
  fb->next = 0;
  return OK;
}

int store_validate(void* base) {
  return ((Header*)base)->magic == MAGIC ? OK : ERR_CORRUPT;
}

// Creates an unsealed object and returns the absolute byte offset (from base)
// where the caller should write data_size bytes of data then meta_size bytes
// of metadata, then call store_seal.
int store_create(void* base, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size, uint64_t* out_offset) {
  Header* h = (Header*)base;
  lock(h);
  if (find_slot(h, id)) { unlock(h); return ERR_EXISTS; }
  // Allocate BEFORE claiming a slot: eviction inside the allocator can
  // trip the tombstone rehash, which relocates the whole slot table and
  // would invalidate a Slot* held across the call.
  int64_t off = alloc_with_eviction(h, data_size + meta_size);
  if (off < 0) { unlock(h); return ERR_FULL; }
  Slot* s = insert_slot(h, id);
  if (!s) {
    free_block(h, off, data_size + meta_size);
    unlock(h);
    return ERR_TABLE_FULL;
  }
  memcpy(s->id, id, 16);
  s->offset = (uint64_t)off;
  s->data_size = data_size;
  s->meta_size = meta_size;
  if (s->state == SLOT_TOMBSTONE) h->num_tombstones--;
  s->state = SLOT_CREATED;
  s->refcnt = 1;  // creator holds a ref until seal+release
  s->lru_tick = ++h->lru_clock;
  s->pending_delete = 0;
  h->num_objects++;
  *out_offset = h->arena_offset + (uint64_t)off;
  unlock(h);
  return OK;
}

int store_seal(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  lock(h);
  Slot* s = find_slot(h, id);
  if (!s) { unlock(h); return ERR_NOTFOUND; }
  s->state = SLOT_SEALED;
  s->refcnt--;  // drop creator ref
  unlock(h);
  return OK;
}

// On success takes a reference; caller must store_release when done with the
// memory. Returns absolute offset + sizes.
int store_get(void* base, const uint8_t* id, uint64_t* out_offset,
              uint64_t* out_data_size, uint64_t* out_meta_size) {
  Header* h = (Header*)base;
  lock(h);
  Slot* s = find_slot(h, id);
  if (!s) { unlock(h); return ERR_NOTFOUND; }
  if (s->state != SLOT_SEALED) { unlock(h); return ERR_AGAIN; }
  s->refcnt++;
  s->lru_tick = ++h->lru_clock;
  *out_offset = h->arena_offset + s->offset;
  *out_data_size = s->data_size;
  *out_meta_size = s->meta_size;
  unlock(h);
  return OK;
}

int store_release(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  lock(h);
  Slot* s = find_slot(h, id);
  if (!s) { unlock(h); return ERR_NOTFOUND; }
  if (s->refcnt > 0) s->refcnt--;
  if (s->pending_delete && s->refcnt == 0) evict_entry(h, s);
  unlock(h);
  return OK;
}

int store_contains(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  lock(h);
  Slot* s = find_slot(h, id);
  int rc = (s && s->state == SLOT_SEALED) ? 1 : 0;
  unlock(h);
  return rc;
}

// Abort an unsealed create (e.g. writer failed mid-copy).
int store_abort(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  lock(h);
  Slot* s = find_slot(h, id);
  if (!s) { unlock(h); return ERR_NOTFOUND; }
  if (s->state == SLOT_CREATED) { evict_entry(h, s); unlock(h); return OK; }
  unlock(h);
  return ERR_BUSY;
}

int store_delete(void* base, const uint8_t* id) {
  Header* h = (Header*)base;
  lock(h);
  Slot* s = find_slot(h, id);
  if (!s) { unlock(h); return ERR_NOTFOUND; }
  if (s->refcnt > 0) {
    s->pending_delete = 1;  // freed on last release
    unlock(h);
    return OK;
  }
  evict_entry(h, s);
  unlock(h);
  return OK;
}

void store_stats(void* base, uint64_t* out_allocated, uint64_t* out_capacity,
                 uint64_t* out_num_objects, uint64_t* out_num_evictions) {
  Header* h = (Header*)base;
  lock(h);
  *out_allocated = h->bytes_allocated;
  *out_capacity = h->arena_size;
  *out_num_objects = h->num_objects;
  *out_num_evictions = h->num_evictions;
  unlock(h);
}

uint64_t store_header_size() { return sizeof(Header); }

// Write the 16-byte ids of all sealed objects into `out` (room for
// max_ids). Returns the number written. Used to rebuild a restarted
// head's object directory from each node's surviving arena (parity:
// raylets resyncing object locations with a restarted GCS).
int64_t store_list_ids(void* base, uint8_t* out, uint64_t max_ids) {
  Header* h = (Header*)base;
  lock(h);
  Slot* tab = slots(h);
  uint64_t n = 0;
  for (uint64_t i = 0; i < h->num_slots && n < max_ids; i++) {
    if (tab[i].state == SLOT_SEALED) {
      memcpy(out + n * 16, tab[i].id, 16);
      n++;
    }
  }
  unlock(h);
  return (int64_t)n;
}

}  // extern "C"
