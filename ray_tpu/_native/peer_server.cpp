// Native object-transfer peer server: serves cross-node object pulls
// straight out of the node's shm arena, no Python (or GIL) on the send path.
//
// Parity: reference `src/ray/object_manager/` — the PushManager side of the
// chunked object transfer protocol (push_manager.h:32, object_manager.h:119).
// Design departure: requests are pull-driven whole objects over persistent
// TCP connections; the server reads sealed objects zero-copy from the same
// mmap'd arena the store clients use (store_get/store_release from
// object_store.cpp, compiled into this .so).
//
// Wire protocol (little endian):
//   request:  16-byte object id
//   response: u8 ok; if ok: u64 data_size, u64 meta_size, meta bytes,
//             data bytes
//   range request (multi-stream pulls): 16-byte RANGE_MAGIC, 16-byte
//   object id, u64 offset, u64 length; response carries the TOTAL
//   data_size/meta_size + meta, then only the requested byte slice.
// Connections are persistent (many requests) and closed on peer EOF.
//
// Threading: one accept thread + one detached thread per connection —
// node counts are small and blocking IO in native threads costs no GIL.

#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <arpa/inet.h>

#include <atomic>
#include <mutex>
#include <set>

extern "C" {

// from object_store.cpp (same .so)
int store_get(void* base, const uint8_t* id, uint64_t* out_offset,
              uint64_t* out_data_size, uint64_t* out_meta_size);
int store_release(void* base, const uint8_t* id);

struct PeerState {
  void* store_base;
  int listen_fd;
  std::atomic<int> active{0};
  std::atomic<bool> stopping{false};
  std::mutex conn_mu;
  std::set<int> conn_fds;
};

struct ConnCtx {
  PeerState* st;
  int fd;
};

static int read_exact(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;  // CPython signals lack SA_RESTART
    if (r <= 0) return -1;
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

static int write_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w < 0 && errno == EINTR) continue;
    if (w <= 0) return -1;
    p += w;
    n -= (size_t)w;
  }
  return 0;
}

static void* conn_main(void* arg) {
  ConnCtx* ctx = (ConnCtx*)arg;
  PeerState* st = ctx->st;
  int fd = ctx->fd;
  void* base = st->store_base;
  delete ctx;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Mirrors objxfer.RANGE_MAGIC: 0xff "RAYTPU_RANGE_1" 0xff.
  static const uint8_t kRangeMagic[16] = {
      0xff, 'R', 'A', 'Y', 'T', 'P', 'U', '_',
      'R', 'A', 'N', 'G', 'E', '_', '1', 0xff};
  uint8_t oid[16];
  while (!st->stopping.load() && read_exact(fd, oid, 16) == 0) {
    uint64_t want_off = 0, want_len = 0;
    bool ranged = false;
    if (memcmp(oid, kRangeMagic, 16) == 0) {
      uint8_t req[16 + 8 + 8];
      if (read_exact(fd, req, sizeof(req)) != 0) break;
      memcpy(oid, req, 16);
      memcpy(&want_off, req + 16, 8);
      memcpy(&want_len, req + 24, 8);
      ranged = true;
    }
    uint64_t off = 0, dsize = 0, msize = 0;
    int rc = store_get(base, oid, &off, &dsize, &msize);
    if (rc != 0) {
      // -2 (ERR_AGAIN) = created but not yet sealed: tell the client to
      // retry shortly instead of reporting the object absent.
      uint8_t ok = (rc == -2) ? 2 : 0;
      if (write_all(fd, &ok, 1) != 0) break;
      continue;
    }
    uint64_t s_off = 0, s_len = dsize;
    if (ranged) {
      s_off = want_off > dsize ? dsize : want_off;
      s_len = dsize - s_off;
      if (want_len < s_len) s_len = want_len;
    }
    uint8_t hdr[1 + 8 + 8];
    hdr[0] = 1;
    memcpy(hdr + 1, &dsize, 8);
    memcpy(hdr + 9, &msize, 8);
    const char* data = (const char*)base + off;
    int err = write_all(fd, hdr, sizeof(hdr));
    if (!err && msize) err = write_all(fd, data + dsize, msize);
    if (!err && s_len) err = write_all(fd, data + s_off, s_len);
    store_release(base, oid);
    if (err) break;
  }
  {
    // Erase BEFORE close: once closed, the fd number can be reused by a
    // brand-new accepted connection — erasing after would delete the live
    // connection's entry and hide it from peer_server_stop.
    std::lock_guard<std::mutex> g(st->conn_mu);
    st->conn_fds.erase(fd);
  }
  close(fd);
  st->active.fetch_sub(1);
  return nullptr;
}

static void* accept_main(void* arg) {
  PeerState* st = (PeerState*)arg;
  for (;;) {
    int fd = accept(st->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listen socket closed: shut down
    }
    if (st->stopping.load()) {
      close(fd);
      continue;
    }
    ConnCtx* cc = new ConnCtx{st, fd};
    st->active.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(st->conn_mu);
      st->conn_fds.insert(fd);
    }
    pthread_t t;
    if (pthread_create(&t, nullptr, conn_main, cc) == 0) {
      pthread_detach(t);
    } else {
      close(fd);
      {
        std::lock_guard<std::mutex> g(st->conn_mu);
        st->conn_fds.erase(fd);
      }
      st->active.fetch_sub(1);
      delete cc;
    }
  }
  st->active.fetch_sub(1);  // accept thread's own ref
  return nullptr;
}

// Stops the server behind `handle` (from peer_server_start): closes the
// listener, shuts down live connections, and waits (bounded) for server
// threads to leave the arena — REQUIRED before unmapping the store.
void peer_server_stop(void* handle, int timeout_ms) {
  PeerState* st = (PeerState*)handle;
  if (!st) return;
  st->stopping.store(true);
  shutdown(st->listen_fd, SHUT_RDWR);
  close(st->listen_fd);
  {
    std::lock_guard<std::mutex> g(st->conn_mu);
    for (int fd : st->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  for (int waited = 0; st->active.load() > 0 && waited < timeout_ms;
       waited += 10) {
    usleep(10 * 1000);
  }
  // Leak st if threads are wedged past the timeout — a freed PeerState
  // under a live thread would be worse.
  if (st->active.load() == 0) delete st;
}

// Starts the server; returns the bound port (>0) or -1; *out_handle gets
// the opaque server handle for peer_server_stop.
int peer_server_start(void* store_base, const char* bind_ip, int port,
                      void** out_handle) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, bind_ip, &addr.sin_addr) != 1) {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  }
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(fd, 64) != 0) {
    close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  if (getsockname(fd, (sockaddr*)&addr, &len) != 0) {
    close(fd);
    return -1;
  }
  PeerState* st = new PeerState;
  st->store_base = store_base;
  st->listen_fd = fd;
  st->active.store(1);  // the accept thread itself
  pthread_t t;
  if (pthread_create(&t, nullptr, accept_main, st) != 0) {
    close(fd);
    delete st;
    return -1;
  }
  pthread_detach(t);
  if (out_handle) *out_handle = st;
  return ntohs(addr.sin_port);
}

}  // extern "C"
