"""ctypes bindings for the native select-round core (cpp/agent_core.cc).

One `AgentCore` instance per agent process: the C++ side owns the frame
pump (epoll + outer-frame split + pickle-prefix sniff), the lease ledger
(queue of raw spec bytes, dedup, inflight, per-worker load/fn tables) and
the native frame builders; Python keeps policy and performs every socket
write under the same locks as the pure-Python path. Built on demand
through the content-hash g++ cache (ray_tpu/_native/build.py) — a failed
build degrades to the pure-Python scheduler, never to an error.
"""

from __future__ import annotations

import ctypes
import os

_u64 = ctypes.c_uint64
_i32 = ctypes.c_int
_u8p = ctypes.POINTER(ctypes.c_uint8)

# Frame kinds surfaced by the pump.
KIND_PICKLE = 0
KIND_PROTO = 1
KIND_RAW = 2
KIND_EOF = 3

_lib = None
_lib_err = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from ray_tpu._native import build as _b
        from ray_tpu._native.build import load_native
        native_dir = os.path.dirname(os.path.abspath(_b.__file__))
        repo = os.path.dirname(os.path.dirname(native_dir))
        src = os.path.join(repo, "cpp", "agent_core.cc")
        hdr = os.path.join(repo, "cpp", "frame_core.h")
        lib = load_native("agent_core", sources=(src,), headers=(hdr,))
    except Exception as e:  # noqa: BLE001 — degrade to pure Python
        _lib_err = e
        return None
    p = ctypes.c_void_p
    lib.agc_new.restype = p
    lib.agc_free.argtypes = [p]
    lib.agc_add_fd.argtypes = [p, _i32, _u64, _i32]
    lib.agc_del_fd.argtypes = [p, _i32]
    lib.agc_poll.argtypes = [p, _i32]
    lib.agc_split.argtypes = [p]
    lib.agc_consume_hot.argtypes = [p, _u64]
    lib.agc_dispatch.argtypes = [p, _i32, _i32]
    lib.agc_outbox_widx.argtypes = [p, _i32]
    lib.agc_take_outbox.argtypes = [p, _i32, ctypes.POINTER(_u8p),
                                    ctypes.POINTER(_u64)]
    lib.agc_drec_count.argtypes = [p]
    lib.agc_drec.argtypes = [p, _i32, ctypes.POINTER(_u8p),
                             ctypes.POINTER(_u64), ctypes.POINTER(_i32),
                             ctypes.POINTER(ctypes.c_int64),
                             ctypes.POINTER(_u8p), ctypes.POINTER(_u64)]
    lib.agc_nd_take.argtypes = [p, ctypes.POINTER(_u8p),
                                ctypes.POINTER(_u64)]
    lib.agc_frame_count.argtypes = [p]
    lib.agc_frame_info.argtypes = [
        p, _i32, ctypes.POINTER(_u64), ctypes.POINTER(_i32),
        ctypes.POINTER(_i32), ctypes.POINTER(_u8p), ctypes.POINTER(_u64),
        ctypes.POINTER(_u8p), ctypes.POINTER(_u64), ctypes.POINTER(_i32),
        ctypes.POINTER(_i32)]
    lib.agc_frame_buf.argtypes = [p, _i32, _i32, ctypes.POINTER(_u8p),
                                  ctypes.POINTER(_u64)]
    lib.agc_round_end.argtypes = [p]
    lib.agc_worker_add.argtypes = [p, _u64, _i32, ctypes.c_char_p, _i32,
                                   ctypes.c_char_p, _i32]
    lib.agc_worker_remove.argtypes = [p, _i32]
    lib.agc_worker_eligible.argtypes = [p, _i32, _i32]
    lib.agc_load_add.argtypes = [p, _i32, _i32]
    lib.agc_worker_load.argtypes = [p, _i32]
    lib.agc_seen.argtypes = [p, ctypes.c_char_p, _i32, _u64]
    lib.agc_push.argtypes = [p, ctypes.c_char_p, _i32, ctypes.c_char_p,
                             _i32, _u64, ctypes.c_char_p, _u64,
                             ctypes.c_int64, ctypes.c_char_p, _i32, _i32]
    lib.agc_fn_blob.argtypes = [p, ctypes.c_char_p, _i32, ctypes.c_char_p,
                                _u64]
    lib.agc_get_fn_blob.argtypes = [p, ctypes.c_char_p, _i32,
                                    ctypes.POINTER(_u8p),
                                    ctypes.POINTER(_u64)]
    lib.agc_has_fn_blob.argtypes = [p, ctypes.c_char_p, _i32]
    lib.agc_backlog.argtypes = [p]
    lib.agc_backlog.restype = _u64
    lib.agc_inflight.argtypes = [p]
    lib.agc_inflight.restype = _u64
    lib.agc_idle.argtypes = [p]
    lib.agc_inflight_pop.argtypes = [p, ctypes.c_char_p, _i32]
    lib.agc_steal_tail.argtypes = [p, _i32]
    lib.agc_fail_worker.argtypes = [p, _i32]
    lib.agc_stolen.argtypes = [
        p, _i32, ctypes.POINTER(_u8p), ctypes.POINTER(_u64),
        ctypes.POINTER(_u8p), ctypes.POINTER(_u64), ctypes.POINTER(_u64),
        ctypes.POINTER(_u8p), ctypes.POINTER(_u64)]
    lib.agc_stats.argtypes = [p, ctypes.POINTER(_u64), ctypes.POINTER(_u64),
                              ctypes.POINTER(_u64)]
    lib.agc_proto_tag_count.argtypes = []
    lib.agc_proto_tag_entry.argtypes = [_i32, ctypes.POINTER(_i32),
                                        ctypes.POINTER(ctypes.c_char_p)]
    _lib = lib
    return lib


def _view(ptr, n):
    if not n:
        return b""
    return memoryview((ctypes.c_uint8 * n).from_address(
        ctypes.cast(ptr, ctypes.c_void_p).value))


HEAD_TAG = 1  # the agent's head link; worker tags are assigned per worker


class AgentCore:
    """Python face of one native select-round context."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"agent_core build failed: {_lib_err!r}")
        self._lib = lib
        self._ctx = lib.agc_new()
        self._next_tag = 16

    def close(self):
        if self._ctx:
            self._lib.agc_free(self._ctx)
            self._ctx = None

    # -- pump --

    def add_fd(self, fd: int, tag: int, raw: bool = False):
        self._lib.agc_add_fd(self._ctx, fd, tag, 1 if raw else 0)

    def del_fd(self, fd: int):
        self._lib.agc_del_fd(self._ctx, fd)

    def alloc_tag(self) -> int:
        self._next_tag += 1
        return self._next_tag

    def poll(self, timeout_ms: int) -> int:
        return self._lib.agc_poll(self._ctx, timeout_ms)

    def split(self) -> int:
        return self._lib.agc_split(self._ctx)

    def consume_hot(self, head_tag: int = HEAD_TAG) -> int:
        return self._lib.agc_consume_hot(self._ctx, head_tag)

    def frames(self):
        """Yield (tag, kind, proto_tag, payload_view, bufs, whole_view) for
        every frame Python must handle. Views die at round_end()."""
        lib, ctx = self._lib, self._ctx
        n = lib.agc_frame_count(ctx)
        tag, kind, ptag = _u64(), _i32(), _i32()
        pp, pl = _u8p(), _u64()
        wp, wl = _u8p(), _u64()
        nb, cons = _i32(), _i32()
        for i in range(n):
            if lib.agc_frame_info(ctx, i, tag, kind, ptag, pp, pl, wp, wl,
                                  nb, cons) != 0:
                continue
            if cons.value:
                continue
            bufs = []
            for j in range(nb.value):
                bp, bl = _u8p(), _u64()
                if lib.agc_frame_buf(ctx, i, j, bp, bl) == 0:
                    # bytes COPY, not a view: out-of-band buffers can
                    # outlive the round inside decoded messages (a spec
                    # parked on a dial thread, a relayed obj push) while
                    # the native conn buffer is recycled at round_end —
                    # matching FrameBuffer, which also yields bytes.
                    bufs.append(bytes(_view(bp, bl.value)))
            yield (tag.value, kind.value, ptag.value,
                   _view(pp, pl.value), bufs, _view(wp, wl.value))

    def round_end(self):
        self._lib.agc_round_end(self._ctx)

    # -- dispatch --

    def dispatch(self, depth: int, record: bool) -> list:
        """Plan + natively build per-worker batches; returns the widx list
        whose outboxes gained frames."""
        lib, ctx = self._lib, self._ctx
        k = lib.agc_dispatch(ctx, depth, 1 if record else 0)
        return [lib.agc_outbox_widx(ctx, i) for i in range(k)]

    def take_outbox(self, widx: int):
        pp, pl = _u8p(), _u64()
        if self._lib.agc_take_outbox(self._ctx, widx, pp, pl) != 0:
            return b""
        return _view(pp, pl.value)

    def dispatch_records(self):
        """[(tid, widx, attempt, name|None)] for this round's dispatches."""
        lib, ctx = self._lib, self._ctx
        out = []
        tp, tl, widx = _u8p(), _u64(), _i32()
        att = ctypes.c_int64()
        np_, nl = _u8p(), _u64()
        for i in range(lib.agc_drec_count(ctx)):
            if lib.agc_drec(ctx, i, tp, tl, widx, att, np_, nl) == 0:
                name = bytes(_view(np_, nl.value)).decode(
                    "utf-8", "replace") if nl.value else None
                out.append((bytes(_view(tp, tl.value)), widx.value,
                            att.value, name))
        return out

    def take_node_done(self):
        pp, pl = _u8p(), _u64()
        self._lib.agc_nd_take(self._ctx, pp, pl)
        return _view(pp, pl.value) if pl.value else b""

    # -- ledger --

    def worker_add(self, tag, fd, wid: bytes, whex: str,
                   eligible: bool = True) -> int:
        return self._lib.agc_worker_add(self._ctx, tag, fd, wid, len(wid),
                                        whex.encode(), 1 if eligible else 0)

    def worker_remove(self, widx: int):
        self._lib.agc_worker_remove(self._ctx, widx)

    def worker_eligible(self, widx: int, ok: bool):
        self._lib.agc_worker_eligible(self._ctx, widx, 1 if ok else 0)

    def load_add(self, widx: int, n: int):
        self._lib.agc_load_add(self._ctx, widx, n)

    def worker_load(self, widx: int) -> int:
        return self._lib.agc_worker_load(self._ctx, widx)

    def seen(self, tid: bytes, seq: int) -> bool:
        return bool(self._lib.agc_seen(self._ctx, tid, len(tid), seq or 0))

    def push(self, tid: bytes, fn: bytes | None, seq: int,
             spec_bytes: bytes, attempt: int = 0, name: str | None = None,
             front: bool = False):
        fn = fn or b""
        nm = (name or "").encode("utf-8", "replace")
        self._lib.agc_push(self._ctx, tid, len(tid), fn, len(fn), seq or 0,
                           spec_bytes, len(spec_bytes), attempt or 0,
                           nm, len(nm), 1 if front else 0)

    def fn_blob(self, fn: bytes, blob: bytes):
        self._lib.agc_fn_blob(self._ctx, fn, len(fn), blob, len(blob))

    def get_fn_blob(self, fn: bytes):
        pp, pl = _u8p(), _u64()
        if self._lib.agc_get_fn_blob(self._ctx, fn, len(fn), pp, pl) != 0:
            return None
        return bytes(_view(pp, pl.value))

    def has_fn_blob(self, fn: bytes) -> bool:
        return bool(self._lib.agc_has_fn_blob(self._ctx, fn, len(fn)))

    def backlog(self) -> int:
        return int(self._lib.agc_backlog(self._ctx))

    def inflight(self) -> int:
        return int(self._lib.agc_inflight(self._ctx))

    def idle(self) -> int:
        return int(self._lib.agc_idle(self._ctx))

    def inflight_pop(self, tid: bytes) -> int:
        return self._lib.agc_inflight_pop(self._ctx, tid, len(tid))

    def _stolen(self, n: int) -> list:
        lib, ctx = self._lib, self._ctx
        out = []
        tp, tl = _u8p(), _u64()
        fp, fl = _u8p(), _u64()
        seq = _u64()
        sp, sl = _u8p(), _u64()
        for i in range(n):
            if lib.agc_stolen(ctx, i, tp, tl, fp, fl, seq, sp, sl) == 0:
                out.append((bytes(_view(tp, tl.value)),
                            bytes(_view(fp, fl.value)) or None,
                            seq.value, bytes(_view(sp, sl.value))))
        return out

    def steal_tail(self, n: int) -> list:
        """Pop up to n newest un-started leases: [(tid, fn, seq, spec)]."""
        return self._stolen(self._lib.agc_steal_tail(self._ctx, n))

    def fail_worker(self, widx: int) -> list:
        """Drain a dead worker's inflight leases: [(tid, fn, seq, spec)]."""
        return self._stolen(self._lib.agc_fail_worker(self._ctx, widx))

    def stats(self) -> dict:
        g, d, x = _u64(), _u64(), _u64()
        self._lib.agc_stats(self._ctx, g, d, x)
        return {"native_grants": g.value, "native_dones": d.value,
                "native_dispatched": x.value}


def proto_tag_table() -> dict:
    """The AgentFrame oneof tags compiled into the native sniffer
    (staticcheck cross-checks these against raytpu.proto)."""
    lib = _load()
    if lib is None:
        return {}
    out = {}
    f, name = _i32(), ctypes.c_char_p()
    for i in range(lib.agc_proto_tag_count()):
        if lib.agc_proto_tag_entry(i, f, name) == 0:
            out[name.value.decode()] = f.value
    return out


def available() -> bool:
    return _load() is not None
