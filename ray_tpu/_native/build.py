"""Compile-on-demand for the native components.

The .so is built with g++ the first time it is needed and cached next to the
source keyed by a content hash, so `pip install`-style build steps are never
required and edits to the .cpp invalidate the cache automatically.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "_build")
_lock = threading.Lock()
_loaded: dict[str, ctypes.CDLL] = {}


def _source_hash(src_path: str) -> str:
    with open(src_path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def load_native(name: str) -> ctypes.CDLL:
    """Build (if needed) and dlopen ray_tpu/_native/<name>.cpp."""
    with _lock:
        if name in _loaded:
            return _loaded[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        tag = _source_hash(src)
        so_path = os.path.join(_BUILD_DIR, f"{name}-{tag}.so")
        if not os.path.exists(so_path):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            tmp = so_path + f".tmp{os.getpid()}"
            cmd = [
                "g++", "-O2", "-fPIC", "-shared", "-pthread",
                "-std=c++17", "-o", tmp, src,
            ]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, so_path)  # atomic: concurrent builders race safely
        lib = ctypes.CDLL(so_path)
        _loaded[name] = lib
        return lib
