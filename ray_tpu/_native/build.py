"""Compile-on-demand for the native components.

The .so is built with g++ the first time it is needed and cached next to the
source keyed by a content hash, so `pip install`-style build steps are never
required and edits to the .cpp invalidate the cache automatically.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "_build")
_lock = threading.Lock()
_loaded: dict[str, ctypes.CDLL] = {}


def _source_hash(paths) -> str:
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def load_native(name: str, sources: tuple = ()) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native lib from ray_tpu/_native/.

    Default source is <name>.cpp; `sources` names additional .cpp files
    compiled into the same .so (the hash covers all of them, so editing
    any source invalidates the cache)."""
    with _lock:
        if name in _loaded:
            return _loaded[name]
        srcs = [os.path.join(_DIR, f"{name}.cpp")]
        srcs += [os.path.join(_DIR, s) for s in sources]
        tag = _source_hash(srcs)
        so_path = os.path.join(_BUILD_DIR, f"{name}-{tag}.so")
        if not os.path.exists(so_path):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            tmp = so_path + f".tmp{os.getpid()}"
            cmd = [
                "g++", "-O2", "-fPIC", "-shared", "-pthread",
                "-std=c++17", "-o", tmp, *srcs,
            ]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, so_path)  # atomic: concurrent builders race safely
        lib = ctypes.CDLL(so_path)
        _loaded[name] = lib
        return lib
