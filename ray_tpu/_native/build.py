"""Compile-on-demand for the native components.

The .so is built with g++ the first time it is needed and cached next to the
source keyed by a content hash, so `pip install`-style build steps are never
required and edits to the .cpp invalidate the cache automatically.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "_build")
_lock = threading.Lock()
_loaded: dict[str, ctypes.CDLL] = {}


def _source_hash(paths) -> str:
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


# Sanitizer builds (parity: the reference's bazel --config=tsan/asan for
# the C++ runtime, .bazelrc:112-155): RAY_TPU_NATIVE_SANITIZER=thread|
# address compiles the native components under TSan/ASan. Sanitized .so's
# are cached under a distinct tag; loading an ASan lib into a regular
# python needs LD_PRELOAD of the asan runtime — build_native() compiles
# without loading for CI-style race hunts.
_SANITIZE_ENV = "RAY_TPU_NATIVE_SANITIZER"


def _sanitizer_flags(sanitizer: str | None) -> tuple[list, str]:
    san = (sanitizer if sanitizer is not None
           else os.environ.get(_SANITIZE_ENV, ""))
    if san in ("thread", "tsan"):
        return ["-fsanitize=thread", "-g", "-O1"], "-tsan"
    if san in ("address", "asan"):
        return ["-fsanitize=address", "-g", "-O1"], "-asan"
    return [], ""


def build_native(name: str, sources: tuple = (),
                 sanitizer: str | None = None,
                 headers: tuple = ()) -> str:
    """Compile (if needed) and return the .so path WITHOUT loading it.

    `sanitizer` overrides the env var ("thread"/"address"/""/None) — passed
    through as a parameter, never by mutating process-global env (a
    concurrent load_native in another thread must not pick it up)."""
    return _build(name, sources, sanitizer=sanitizer, headers=headers)


def _build(name: str, sources: tuple = (),
           sanitizer: str | None = None, headers: tuple = ()) -> str:
    # Default source is _native/<name>.cpp; absolute `sources` entries
    # (e.g. cpp/agent_core.cc, which lives beside the other cross-language
    # C++ in the repo's cpp/ tree) are taken as-is, so one cache serves
    # both layouts. `headers` are hashed (so an edit to a shared .h like
    # cpp/frame_core.h invalidates every .so that includes it) and their
    # directories ride -I; they are never handed to g++ as inputs.
    srcs = []
    primary = os.path.join(_DIR, f"{name}.cpp")
    if os.path.exists(primary):
        srcs.append(primary)
    srcs += [s if os.path.isabs(s) else os.path.join(_DIR, s)
             for s in sources]
    if not srcs:
        raise FileNotFoundError(f"no sources for native module {name!r}")
    hdrs = [p if os.path.isabs(p) else os.path.join(_DIR, p)
            for p in headers]
    extra, san_tag = _sanitizer_flags(sanitizer)
    tag = _source_hash(srcs + hdrs) + san_tag
    so_path = os.path.join(_BUILD_DIR, f"{name}-{tag}.so")
    if not os.path.exists(so_path):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O2", "-fPIC", "-shared", "-pthread",
            "-std=c++17", *extra,
            *sorted({f"-I{os.path.dirname(p)}" for p in hdrs}),
            "-o", tmp, *srcs,
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, so_path)  # atomic: concurrent builders race safely
    return so_path


def build_binary(name: str, sources: tuple, include_dirs: tuple = (),
                 sanitizer: str | None = None, headers: tuple = ()) -> str:
    """Compile (if needed) a standalone EXECUTABLE through the same
    content-hash g++ cache and return its path.

    Unlike _build, `sources` are absolute paths (the cpp worker's sources
    live under the repo's cpp/ tree, not _native/). Used for the
    cross-language worker binary (cpp/raytpu_worker.cc + object_store.cpp)
    so no build-system step is ever required — the node agent compiles on
    first spawn and every later spawn hits the cache. `headers` ride the
    content hash only (an edit to a shared .h rebuilds the binary)."""
    srcs = [s if os.path.isabs(s) else os.path.join(_DIR, s)
            for s in sources]
    hdrs = [p if os.path.isabs(p) else os.path.join(_DIR, p)
            for p in headers]
    extra, san_tag = _sanitizer_flags(sanitizer)
    tag = _source_hash(srcs + hdrs) + san_tag
    out_path = os.path.join(_BUILD_DIR, f"{name}-{tag}")
    if not os.path.exists(out_path):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = out_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-pthread", "-std=c++17", *extra]
        cmd += [f"-I{d}" for d in include_dirs]
        cmd += ["-o", tmp, *srcs]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out_path)  # atomic: concurrent builders race safely
    return out_path


def load_native(name: str, sources: tuple = (),
                headers: tuple = ()) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native lib from ray_tpu/_native/.

    Default source is <name>.cpp; `sources` names additional .cpp files
    compiled into the same .so and `headers` shared includes (the hash
    covers all of them, so editing any source OR header invalidates the
    cache)."""
    with _lock:
        if name in _loaded:
            return _loaded[name]
        lib = ctypes.CDLL(_build(name, sources, headers=headers))
        _loaded[name] = lib
        return lib
