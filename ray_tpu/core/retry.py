"""One deadline/backoff-with-jitter policy for the data/control planes.

Parity: reference `src/ray/common/ray_config_def.h` backoff knobs +
`retryable_grpc_client.h` — ONE policy object instead of the scattered
ad-hoc `time.sleep(0.5)` / `delay = min(delay * 2, ...)` constants that
had grown across the peer dial, the agent's head reconnect, and objxfer's
created-but-unsealed (status-2) poll. Every retry loop in core/ sleeps
through a `Backoff` so the cadence is config-tunable in one place and
jittered (synchronized retry storms from N processes hammering one
restarted peer are the failure mode jitter exists for).

`ray_tpu.util.retry` remains the HTTP/cloud-API wrapper (attempt-count
shaped); this module is deadline-shaped — data-plane loops know how long
the operation may take, not how many tries it deserves.
"""

from __future__ import annotations

import random
import time


def policy_from_config(cfg=None):
    """(base_s, cap_s, jitter_frac) from the config table (falls back to
    the defaults when the config is not importable — bare unit tests)."""
    if cfg is None:
        try:
            from ray_tpu.core.config import get_config
            cfg = get_config()
        except Exception:  # noqa: BLE001 — config not importable
            return 0.05, 2.0, 0.2
    return (cfg.retry_backoff_base_s, cfg.retry_backoff_cap_s,
            cfg.retry_backoff_jitter)


class Backoff:
    """Capped exponential backoff with jitter against a deadline.

        bo = Backoff(deadline_s=grace)          # config-tuned cadence
        while not bo.expired():
            if try_once():
                return
            if not bo.sleep():
                break                            # deadline exhausted

    `sleep()` waits the next interval (never past the deadline) and
    returns False once the deadline is exhausted. Each interval is
    `base * 2^k`, capped at `cap`, then jittered by ±`jitter` fraction —
    all three default from the `retry_backoff_*` config knobs.
    """

    def __init__(self, base_s: float | None = None,
                 cap_s: float | None = None,
                 jitter: float | None = None,
                 deadline_s: float | None = None,
                 rng: random.Random | None = None):
        cfg_base, cfg_cap, cfg_jitter = policy_from_config()
        self.base_s = cfg_base if base_s is None else base_s
        self.cap_s = cfg_cap if cap_s is None else cap_s
        self.jitter = cfg_jitter if jitter is None else jitter
        self._rng = rng or random
        self._attempt = 0
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + deadline_s)

    def reset(self) -> None:
        """Back to the base interval (progress was made)."""
        self._attempt = 0

    def expired(self) -> bool:
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def remaining(self) -> float:
        if self._deadline is None:
            return float("inf")
        return max(0.0, self._deadline - time.monotonic())

    def next_interval(self) -> float:
        """The next sleep length (advances the attempt counter)."""
        d = min(self.base_s * (2 ** self._attempt), self.cap_s)
        self._attempt += 1
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def sleep(self) -> bool:
        """Sleep the next interval, clipped to the deadline. Returns
        False when the deadline is exhausted (nothing left to wait)."""
        d = self.next_interval()
        if self._deadline is not None:
            left = self._deadline - time.monotonic()
            if left <= 0:
                return False
            d = min(d, left)
        time.sleep(d)
        return not self.expired()


def call_with_backoff(fn, deadline_s: float, retry_on=(OSError,),
                      base_s: float | None = None,
                      cap_s: float | None = None):
    """Run `fn()` until it returns without raising `retry_on`, sleeping a
    jittered capped-exponential interval between attempts, for at most
    `deadline_s`. The final failure propagates unchanged."""
    bo = Backoff(base_s=base_s, cap_s=cap_s, deadline_s=deadline_s)
    while True:
        try:
            return fn()
        except retry_on:
            if not bo.sleep():
                raise
