"""Worker process: executes tasks and hosts actors.

Parity: reference `python/ray/_private/workers/default_worker.py` +
`src/ray/core_worker/` execution side (`transport/task_receiver.h`,
`actor_scheduling_queue.h`, async-actor fibers `transport/fiber.h`) and the
task-execution callback `python/ray/_raylet.pyx:1727 execute_task`.

One socket to the head multiplexes: inbound task dispatch, and outbound
API calls (nested task submission, object waits) + results. A receiver
thread routes frames; execution happens on the main executor thread, a
thread pool (threaded actors), or an asyncio loop (async actors).
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import contextlib
import inspect
import os
import pickle
import socket
import sys
import threading
import time
import traceback

import cloudpickle

from ray_tpu.core import chaos, serialization, task_events
from ray_tpu.core.config import Config, set_config, get_config
from ray_tpu.core.ids import ObjectID, WorkerID
from ray_tpu.core.object_store import SharedMemoryStore, arrow_block_of
from ray_tpu.core.status import TaskError
from ray_tpu.core.task import TaskSpec
from ray_tpu.core.transport import FrameBuffer, send_msg, socket_from_fd

# Process-global task-event ring (core/task_events.py): emission sites
# guard on `.enabled` (one attribute check when the pipeline is off).
_TEV = task_events.ring()


class _LRUCache:
    """Bounded oid->value cache. A long-lived worker sees millions of inline
    values; on miss the value is re-fetched from the head (directory/shm), so
    eviction is always safe."""

    def __init__(self, cap: int = 4096):
        import collections
        self._d = collections.OrderedDict()
        self._cap = cap
        self._lock = threading.Lock()

    def __contains__(self, key):
        with self._lock:
            return key in self._d

    def __getitem__(self, key):
        with self._lock:
            val = self._d[key]
            self._d.move_to_end(key)
            return val

    def __setitem__(self, key, val):
        with self._lock:
            self._d[key] = val
            self._d.move_to_end(key)
            while len(self._d) > self._cap:
                self._d.popitem(last=False)

    def pop(self, key, default=None):
        with self._lock:
            return self._d.pop(key, default)

    def get(self, key, default=None):
        with self._lock:
            if key not in self._d:
                return default
            self._d.move_to_end(key)
            return self._d[key]


class _WorkerRefCounter:
    """Worker-side counting for objects THIS worker owns (its own put()s);
    borrowed refs stay uncounted — the head pins those for the lifetime of
    tasks that reference them (runtime.submit_task).

    An owned ref that gets serialized (into a return value, a task arg, a
    nested put) has "escaped" to an unknown borrower and is never freed from
    here; the overwhelmingly common temporary — put, use locally, drop —
    frees eagerly instead of leaking into the shared arena until eviction."""

    def __init__(self, free_fn, escape_fn=None):
        self._owned: dict[bytes, int] = {}
        self._escaped: set[bytes] = set()
        self._lock = threading.Lock()
        self._free_fn = free_fn
        self._escape_fn = escape_fn  # first escape of an owned key

    def register_owned(self, object_id):
        """Call BEFORE constructing the first (strong) ObjectRef: the ref's
        own add_local_ref provides the initial count."""
        with self._lock:
            self._owned[object_id.binary()] = 0

    def add_local_ref(self, object_id):
        key = object_id.binary()
        with self._lock:
            if key in self._owned:
                self._owned[key] += 1

    def remove_local_ref(self, object_id):
        key = object_id.binary()
        free = False
        with self._lock:
            if key not in self._owned:
                return
            self._owned[key] -= 1
            if self._owned[key] <= 0:
                del self._owned[key]
                free = key not in self._escaped
                self._escaped.discard(key)
        if free:
            try:
                self._free_fn(key)
            except Exception:  # noqa: BLE001 — freeing is best effort
                pass

    def mark_escaped(self, object_id):
        key = object_id.binary()
        fire = False
        with self._lock:
            if key in self._owned and key not in self._escaped:
                self._escaped.add(key)
                fire = self._escape_fn is not None
        if fire:
            try:
                self._escape_fn(key)
            except Exception:  # noqa: BLE001 — escape hook is safety net
                pass

    def is_owned(self, key: bytes) -> bool:
        with self._lock:
            return key in self._owned


class _WorkerPeer:
    """One worker<->worker unix-socket channel of the head-node peer
    plane (parity role: the reference's direct worker-to-worker gRPC
    actor transport, actor_task_submitter.h:78 — here between pooled
    workers of the head node, where there is no agent to route through).

    The initiating side sends ("wexec", spec) frames and receives
    ("wdone", ...) replies; the accepting side is the executor. Failures
    signal as channel EOF (calls fall back through the head). Frames on
    one channel are FIFO, which carries per-caller call order."""

    def __init__(self, rt: "WorkerRuntime", sock, initiated: bool):
        self.rt = rt
        self.sock = sock
        self.send_lock = threading.Lock()
        self.alive = True
        self.initiated = initiated
        self.path: str | None = None       # dial target (initiator only)
        self.inflight: dict[bytes, TaskSpec] = {}  # initiator bookkeeping

    def send(self, msg):
        send_msg(self.sock, msg, self.send_lock)

    def start(self):
        threading.Thread(target=self._read_loop, daemon=True,
                         name="rtpu-wpeer").start()

    def _read_loop(self):
        fb = FrameBuffer()
        while True:
            try:
                data = self.sock.recv(1 << 20)
            except OSError:
                data = b""
            if not data:
                self.alive = False
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.rt._on_wpeer_eof(self)
                return
            fb.feed(data)
            for msg in fb.frames():
                try:
                    self.rt._on_wpeer_frame(self, msg)
                except Exception:  # noqa: BLE001 — keep the channel alive
                    traceback.print_exc()


class WorkerRuntime:
    """Per-worker client runtime; the worker-side half of the core API."""

    def __init__(self, sock, worker_id: WorkerID, store_path: str):
        self.sock = sock
        self.send_lock = threading.Lock()
        self._send_q: collections.deque = collections.deque()
        self._send_cv = threading.Condition()
        self._last_send = 0.0
        self._send_exc: OSError | None = None
        self._sender_started = False
        # In-flight channel claims (inline senders + the sender thread
        # each hold one while writing): a COUNTER, not a bool — an inline
        # send finishing while the sender thread still owns a batch must
        # not mark the channel free (that would let a later frame
        # inline-send ahead of the queued batch).
        self._sending = 0
        self.worker_id = worker_id
        self.store_path = store_path
        self._store: SharedMemoryStore | None = None
        self.functions: dict[bytes, object] = {}
        self.object_cache = _LRUCache()
        self.object_errors: dict[bytes, object] = {}
        self._pending_waits: dict[bytes, list[threading.Event]] = {}
        self._wait_lock = threading.Lock()
        self.task_queue: "queue.Queue" = None  # set in main
        self.cancelled_tasks: set = set()  # dropped before execution
        # Stolen back; skip silently. A COUNTER, not a set: the same task
        # can be stolen, re-dispatched, pipelined back onto this very
        # worker, and stolen again — each acked drop corresponds to exactly
        # one stale queued exec copy that must be skipped, and a set would
        # absorb the second mark and let the stale copy run (duplicate).
        self.dropped_tasks: dict = {}      # task_id -> pending skip count
        # Two-phase steal: ids whose execution has begun. The receiver
        # thread consults this under steal_lock to decide a drop_task's ack
        # (begun -> drop_ack False, the head aborts the steal).
        self.begun_tasks: set = set()
        self.steal_lock = threading.Lock()
        # pubsub subscriber registry (pubsub_msg pushes dispatch here)
        self._pubsub_cbs: dict[tuple, list] = {}
        self._pubsub_lock = threading.Lock()
        self.actor_instance = None
        self.actor_id: bytes | None = None
        self.shutdown = threading.Event()
        self.current_task = None
        self.refcount = _WorkerRefCounter(
            self._on_owned_free, escape_fn=self._on_owned_escape)
        # ---- worker<->worker peer plane (head-node pooled workers) ----
        # Direct actor calls between workers of the head node ride unix
        # sockets: 2 frame hops instead of 4 (caller->head->executor->
        # head->caller), with the head entirely out of the data path.
        # The agent plane's counterpart is node_agent._PeerConn.
        self._peer_path: str | None = None   # our UDS listener (executor)
        self._peer_srv: socket.socket | None = None
        self._peer_conns: dict[str, "_WorkerPeer"] = {}  # path -> conn
        self._peer_lock = threading.Lock()
        # Executor side: task_id -> _WorkerPeer the exec arrived on.
        self.direct_routes: dict[bytes, "_WorkerPeer"] = {}
        # Caller side: inline results of direct calls, pinned while the
        # ref lives (the 4096-LRU object_cache would silently evict them
        # and a re-fetch from the head — which never saw the call — would
        # hang). rid -> value.
        self._direct_values: dict[bytes, object] = {}
        # rid -> bool(escaped before arrival): set at submit, consumed at
        # wdone/wfail.
        self._direct_pending: dict[bytes, bool] = {}
        self._direct_lock = threading.Lock()
        # Diagnostics: direct (peer-plane) calls this worker shipped —
        # tests pair this against the head's actor_head_dispatches to
        # assert storms stay off the head/agent relay.
        self.direct_calls_sent = 0
        # Executor-side per-(caller, actor) submission-order gate: peer
        # frames race head-relayed frames exactly like the agent plane.
        from ray_tpu.core.order_gate import OrderGate
        self.order_gate = OrderGate()
        # Actor location cache for the direct agent<->agent call path
        # (parity: the resolved actor address inside
        # actor_task_submitter.h:78); poisoned by "actor_moved" pushes.
        self.actor_locations: dict[bytes, tuple] = {}
        self.on_agent_node = os.environ.get("RAY_TPU_IS_HEAD_NODE") == "0"
        # Per-target-actor submission counter: stamped on every actor call
        # this worker submits (direct OR head path) so the executing agent
        # can restore per-caller order across the two transports.
        self._actor_seq_lock = threading.Lock()
        import collections as _collections
        self._actor_call_seq: "_collections.OrderedDict[bytes, int]" = (
            _collections.OrderedDict())
        self._req_lock = threading.Lock()
        self._req_seq = 0
        self._req_futures: dict[int, "concurrent.futures.Future"] = {}
        # Caller-side pins for direct actor calls that carry locally-owned
        # object deps (and for offloaded arg packs): rid -> [remaining,
        # [oid, ...]]. The head never sees a peer-plane call, so ITS
        # submit-time dep pinning can't protect these — the caller holds a
        # local ref on each dep until every return of the call resolves.
        self._dep_pins: dict[bytes, list] = {}
        self._dep_pin_lock = threading.Lock()
        # Task-event / metric flush pacing (task_events_flush_ms): the
        # ring drains onto the write-combined reply channel, so a flush
        # rides the same coalesced sendmsg as the done frame it follows.
        self._tev_last_flush = 0.0
        self._tev_flush_s = get_config().task_events_flush_ms / 1000.0

    def flush_task_events(self, force: bool = False):
        """Ship the ring + dirty metric registry to the head (via the
        agent relay on agent nodes). Rate-limited; piggybacks on the
        sender-thread batching, so a flush right after a reply rides the
        same coalesced write as the done frame before it."""
        pending = _TEV.enabled and (_TEV.events or _TEV.dropped)
        now = time.monotonic()
        due = force or (now - self._tev_last_flush) >= self._tev_flush_s
        if not due:
            return
        self._tev_last_flush = now
        try:
            if pending:
                batch, dropped = _TEV.drain()
                if batch or dropped:
                    self.send(("task_events", batch, dropped))
            from ray_tpu.util import metrics as _metrics
            snap = _metrics.registry_delta()
            if snap:
                self.send(("metrics_update", snap))
        except OSError:
            pass  # head/agent gone; the worker is on its way out

    # -- pubsub (subscriber side; parity: pubsub/subscriber.h:73) --

    def pubsub_subscribe(self, channel: str, key: str, callback):
        with self._pubsub_lock:
            self._pubsub_cbs.setdefault((channel, key), []).append(callback)
        self.send(("subscribe", channel, key))

    def pubsub_unsubscribe(self, channel: str, key: str, callback):
        last = False
        with self._pubsub_lock:
            cbs = self._pubsub_cbs.get((channel, key))
            if cbs is not None:
                try:
                    cbs.remove(callback)
                except ValueError:
                    pass
                if not cbs:
                    self._pubsub_cbs.pop((channel, key), None)
                    last = True
        if last:
            self.send(("unsubscribe", channel, key))

    def pubsub_publish(self, channel: str, key: str, message):
        self.send(("publish", channel, key, message))

    # -- object plane --

    @property
    def store(self) -> SharedMemoryStore:
        if self._store is None:
            from ray_tpu.core.object_store import configure_store
            st = SharedMemoryStore(self.store_path)
            configure_store(st, get_config())
            if os.environ.get("RAY_TPU_IS_HEAD_NODE") == "1":
                # Reservation refills ask the head for room once per
                # extent (the old path probed stats + requested spill on
                # every large put). Agent arenas rely on LRU eviction.
                def _spill_refill_hook(need: int, _st=st):
                    stats = _st.stats()
                    cap = stats["capacity"] or 1
                    limit = get_config().object_spill_threshold * cap
                    if stats["allocated"] + need > limit:
                        self.request(
                            "spill",
                            int(stats["allocated"] + need - limit)
                            + (4 << 20))

                st.spill_hook = _spill_refill_hook
            self._store = st
        return self._store

    def put(self, value):
        from ray_tpu.core.object_ref import ObjectRef
        oid = ObjectID.from_random()
        _put_with_spill(self, oid, value,
                        int(getattr(value, "nbytes", 0) or (1 << 20)))
        self.send(("put_notify", oid.binary()))
        self.refcount.register_owned(oid)
        return ObjectRef(oid, owner=self.worker_id.binary())

    def put_arg_object(self, value, nbytes) -> bytes:
        """Store one offloaded-args pack (serialization.maybe_offload_args)
        owned by this worker: the submitter releases the local ref when the
        call's returns resolve (pin_call_deps), and the head additionally
        frees it after the final completion of head-routed tasks."""
        oid = ObjectID.from_random()
        _put_with_spill(self, oid, value, nbytes)
        self.refcount.register_owned(oid)
        self.refcount.add_local_ref(oid)
        self.send(("put_notify", oid.binary()))
        return oid.binary()

    def get(self, refs, timeout=None):
        from ray_tpu.core.object_ref import ObjectRef
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        out = [self._get_one(r, timeout) for r in refs]
        return out[0] if single else out

    def _get_one(self, ref, timeout=None):
        oid = ref.id.binary()
        _MISS = object()
        cached = self.object_cache.get(oid, _MISS)
        if cached is not _MISS:
            return self._raise_if_error(cached)
        if oid in self._direct_values:  # pinned direct-call result that
            return self._raise_if_error(  # fell out of the LRU cache
                self._direct_values[oid])
        found, value = self.store.get_deserialized(ref.id, timeout=0)
        if found:
            self._maybe_cache_scalar(oid, value)
            return value
        # Ask the owner; block until the push arrives.
        ev = threading.Event()
        with self._wait_lock:
            self._pending_waits.setdefault(oid, []).append(ev)
        # Close the check-then-subscribe window: a peer-plane wdone that
        # landed between the cache probes above and the registration just
        # now signalled NOBODY — and unlike head-path objects, wait_obj
        # cannot recover it (the head never saw a direct call). Re-probe
        # now that any later arrival is guaranteed to set `ev`.
        if oid in self.object_cache or oid in self._direct_values:
            with self._wait_lock:
                lst = self._pending_waits.get(oid)
                if lst is not None:
                    try:
                        lst.remove(ev)
                    except ValueError:
                        pass
                    if not lst:
                        self._pending_waits.pop(oid, None)
        else:
            self.send(("wait_obj", oid))
            if not ev.wait(timeout):
                from ray_tpu.core.status import GetTimeoutError
                raise GetTimeoutError(f"get() timed out on {ref}")
        cached = self.object_cache.get(oid, _MISS)
        if cached is not _MISS:
            return self._raise_if_error(cached)
        if oid in self._direct_values:
            return self._raise_if_error(self._direct_values[oid])
        found, value = self.store.get_deserialized(ref.id, timeout=5.0)
        if found:
            self._maybe_cache_scalar(oid, value)
            return value
        from ray_tpu.core.status import ObjectLostError
        raise ObjectLostError(ref.id)

    _SCALAR_TYPES = (int, float, bool, bytes, str, type(None))

    def _maybe_cache_scalar(self, oid: bytes, value):
        """Cache tiny immutable scalars read from the arena: an actor
        hammered with the same small ref arg (fan-out bursts passing one
        put() handle) re-reads it per call otherwise — a shard-lock +
        unpickle round trip for a value that can never change. Larger or
        composite values stay uncached so the LRU can't pin arena-aliasing
        buffers alive."""
        if type(value) in self._SCALAR_TYPES and sys.getsizeof(value) < 4096:
            self.object_cache[oid] = value

    @staticmethod
    def _raise_if_error(value):
        if isinstance(value, TaskError):
            raise value.cause if value.cause is not None else value
        if isinstance(value, Exception):
            raise value
        return value

    def prefetch_refs(self, refs):
        """Vectored dependency fetch: subscribe to every locally-missing
        ref in ONE wait_objs frame so the head materializes them
        concurrently (and groups same-source pulls into one batched
        objxfer round). Best-effort warm-up — anything still missing
        afterward falls back to _get_one's own per-ref wait/timeout."""
        if len(refs) < 2:
            return
        min_refs = get_config().vectored_arg_fetch_min
        if min_refs <= 0 or len(refs) < min_refs:
            return
        missing: list = []
        events: list = []
        seen: set = set()
        for r in refs:
            oid = r.id.binary()
            if (oid in seen or oid in self.object_cache
                    or oid in self._direct_values
                    or self.store.contains(r.id)):
                continue
            seen.add(oid)
            ev = threading.Event()
            with self._wait_lock:
                self._pending_waits.setdefault(oid, []).append(ev)
            missing.append(oid)
            events.append(ev)
        if len(missing) < min_refs:
            # Below the vectored floor: drop the subscriptions — the
            # per-ref path will re-subscribe with its own timeout story.
            with self._wait_lock:
                for oid, ev in zip(missing, events):
                    lst = self._pending_waits.get(oid)
                    if lst is not None:
                        try:
                            lst.remove(ev)
                        except ValueError:
                            pass
                        if not lst:
                            self._pending_waits.pop(oid, None)
            return
        try:
            self.send(("wait_objs", missing))
        except OSError:
            return
        deadline = time.monotonic() + 60.0
        for ev in events:
            if not ev.wait(max(0.0, deadline - time.monotonic())):
                break  # per-arg resolve owns the error/timeout story

    def wait(self, refs, num_returns=1, timeout=None):
        import time as _t
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        deadline = None if timeout is None else _t.monotonic() + timeout
        subscribed: dict[bytes, threading.Event] = {}

        def is_ready(r) -> bool:
            oid = r.id.binary()
            if (oid in self.object_cache or oid in self._direct_values
                    or self.store.contains(r.id)):
                return True
            ev = subscribed.get(oid)
            if ev is not None and ev.is_set():
                return True
            if ev is None:  # subscribe exactly once per ref
                ev = threading.Event()
                subscribed[oid] = ev
                with self._wait_lock:
                    self._pending_waits.setdefault(oid, []).append(ev)
                self.send(("wait_obj", oid))
            return False

        while True:
            ready = [r for r in refs if is_ready(r)]
            if len(ready) >= num_returns:
                break
            if deadline is not None and _t.monotonic() > deadline:
                break
            _t.sleep(0.002)
        ready_set = {r.id.binary() for r in ready[:num_returns]}
        ready = [r for r in refs if r.id.binary() in ready_set]
        not_ready = [r for r in refs if r.id.binary() not in ready_set]
        return ready, not_ready

    # -- task submission from inside a worker --

    def submit(self, spec: TaskSpec):
        self.send(("submit", spec))

    def send(self, msg):
        """Send one frame, write-combining under load. A lone frame on an
        idle channel sends inline (sync-call latency unchanged); frames
        arriving while a send syscall is in flight queue behind it and the
        sender thread coalesces them into one write — a task fanning out
        actor calls or puts stops paying one syscall+wakeup per call.
        Order is exactly send-call order, so every head-side invariant
        that held under inline sends still holds.

        Burst detection: a SEQUENTIAL fan-out loop (submit, submit, ...)
        never finds the channel busy — each inline sendall completes, and
        worse, wakes the head per frame (on a shared core that preemption
        doubles the cost). When the previous send was <150us ago, hand the
        frame to the sender thread instead: while its send_many syscall is
        in flight the loop keeps queueing, so bursts collapse into a few
        large writes."""
        burst = False
        now = time.monotonic()
        if now - self._last_send < 150e-6:
            burst = True
        self._last_send = now
        with self._send_cv:
            if self._send_exc is not None:
                raise self._send_exc
            if self._send_q or self._sending or burst:
                if not self._sender_started:
                    self._sender_started = True
                    threading.Thread(target=self._sender_loop, daemon=True,
                                     name="rtpu-sender").start()
                self._send_q.append(msg)
                self._send_cv.notify()
                return
            self._sending += 1  # claim the channel for an inline send
        try:
            send_msg(self.sock, msg, self.send_lock)
        finally:
            with self._send_cv:
                self._sending -= 1
                self._send_cv.notify_all()

    def _sender_loop(self):
        from ray_tpu.core.transport import send_many
        while True:
            with self._send_cv:
                while not self._send_q:
                    self._send_cv.notify_all()  # wake flush_sends waiters
                    self._send_cv.wait()
                batch = list(self._send_q)
                self._send_q.clear()
                self._sending += 1
            try:
                send_many(self.sock, batch, self.send_lock)
            except OSError as e:
                with self._send_cv:
                    self._send_exc = e
                    self._send_q.clear()
                    self._sending -= 1
                    self._send_cv.notify_all()
                return
            with self._send_cv:
                self._sending -= 1
                self._send_cv.notify_all()

    def flush_sends(self, timeout: float = 2.0):
        """Drain the send queue (used before os._exit so the last frames —
        replies, actor_err — reach the head)."""
        deadline = time.monotonic() + timeout
        with self._send_cv:
            while ((self._send_q or self._sending)
                   and self._send_exc is None):
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._send_cv.wait(left)
        # An in-flight sendall holds send_lock past the flag flip; taking
        # the lock once guarantees the final write hit the socket before
        # the caller os._exits.
        with self.send_lock:
            pass

    def next_actor_call_seq(self, actor_id: bytes) -> int:
        with self._actor_seq_lock:
            n = self._actor_call_seq.get(actor_id, 0)
            self._actor_call_seq[actor_id] = n + 1
            self._actor_call_seq.move_to_end(actor_id)
            if len(self._actor_call_seq) > 4096:
                # Bounded, LRU: evicting an idle counter restarts that
                # pair at 0, which the executing agent treats as an
                # immediate replay slot — order degrades gracefully.
                self._actor_call_seq.popitem(last=False)
            return n

    # -- caller-side dep pinning (direct calls + offloaded arg packs) --

    def deps_ready_local(self, refs) -> bool:
        """True when every ref dep is owned by THIS worker and already
        sealed in the local arena — the precondition for taking the direct
        actor-call path with args: the executor resolves them instantly
        (no head-of-line blocking in its queue) and pin_call_deps below
        replaces the head's submit-time borrow pin."""
        for r in refs:
            if not self.refcount.is_owned(r.id.binary()):
                return False
            if not self.store.contains(r.id):
                return False
        return True

    def pin_call_deps(self, spec, add_oids=(), held_oids=()):
        """Hold a local ref on each oid until every return of this call
        resolves (wdone on the peer plane, or the head's obj push on a
        fallback/get). `add_oids` take a fresh count here (direct-call
        user deps); `held_oids` were already counted by the caller
        (offloaded arg packs — put_arg_object's ref transfers in). A call
        whose results are never observed keeps its pins for the worker's
        lifetime — bounded by the caller's own working set, same as
        holding the arg refs in a local."""
        oids = list(add_oids) + list(held_oids)
        if not oids:
            return
        from ray_tpu.core.ids import ObjectID as _OID
        for oid in add_oids:
            self.refcount.add_local_ref(_OID(oid))
        if not spec.return_ids:
            for oid in oids:  # fire-and-forget: nothing will resolve
                self.refcount.remove_local_ref(_OID(oid))
            return
        pin = [len(spec.return_ids), oids]
        with self._dep_pin_lock:
            for rid in spec.return_ids:
                self._dep_pins[rid] = pin

    def _release_dep_pin(self, rid: bytes):
        with self._dep_pin_lock:
            pin = self._dep_pins.pop(rid, None)
            if pin is None:
                return
            pin[0] -= 1
            done = pin[0] <= 0
        if done:
            from ray_tpu.core.ids import ObjectID as _OID
            for oid in pin[1]:
                self.refcount.remove_local_ref(_OID(oid))

    _HEAD_HOSTED = ("head", b"")  # negative-cache sentinel

    def resolve_actor_location(self, actor_id: bytes):
        """(node_id, worker_id) of a live remote actor, or None. Cached —
        including the negative result (head-hosted/unstable actors must not
        pay a resolution round-trip on EVERY call); a stale entry of either
        kind is dropped by the agent's actor_moved push."""
        loc = self.actor_locations.get(actor_id)
        if loc is not None:
            return None if loc == self._HEAD_HOSTED else loc
        try:
            loc = self.request("actor_location", actor_id, timeout=10.0)
        except Exception:  # noqa: BLE001 — resolution is an optimization
            return None
        self.actor_locations[actor_id] = (tuple(loc) if loc is not None
                                          else self._HEAD_HOSTED)
        return tuple(loc) if loc is not None else None

    # -- worker<->worker peer plane (head-node direct actor calls) --

    def start_peer_listener(self) -> str | None:
        """Bind this worker's UDS exec listener (executor half of the
        peer plane). The path rides the "ready" frame so the head can
        hand it to callers resolving this worker's actor — on head nodes
        AND agent nodes (same-node actor->actor calls skip the agent
        relay both ways; the agent learns of results asynchronously via
        put_notify/task-event frames only)."""
        if not get_config().worker_direct_calls:
            return None
        path = f"{self.store_path}_w{self.worker_id.hex()[:12]}.sock"
        try:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(path)
            srv.listen(64)
        except OSError:
            return None
        self._peer_srv = srv
        self._peer_path = path

        def accept_loop():
            while not self.shutdown.is_set():
                try:
                    s, _ = srv.accept()
                except OSError:
                    return
                _WorkerPeer(self, s, initiated=False).start()

        threading.Thread(target=accept_loop, daemon=True,
                         name="rtpu-wpeer-accept").start()
        return path

    def send_direct_worker(self, path: str, spec) -> bool:
        """Ship an actor call straight to the hosting worker's UDS.
        False = couldn't (caller falls back to the head path)."""
        try:
            with self._peer_lock:
                conn = self._peer_conns.get(path)
            if conn is None or not conn.alive:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    s.connect(path)
                except OSError:
                    # The dial failed before any owner existed: close
                    # here or the fd leaks on every stale-path retry.
                    s.close()
                    raise
                fresh = _WorkerPeer(self, s, initiated=True)
                fresh.path = path
                with self._peer_lock:
                    live = self._peer_conns.get(path)
                    if live is not None and live.alive:
                        try:
                            s.close()
                        except OSError:
                            pass
                        conn = live
                    else:
                        self._peer_conns[path] = fresh
                        conn = fresh
                if conn is fresh:
                    conn.start()
        except OSError:
            return False
        # The caller owns a direct call's results (the head never sees
        # the call, so nobody else can): register BEFORE the ObjectRefs
        # are constructed so their local refcounts take.
        with self._direct_lock:
            for rid in spec.return_ids:
                self.refcount.register_owned(ObjectID(rid))
                self._direct_pending[rid] = False
        conn.inflight[spec.task_id] = spec
        if chaos.site("worker.direct_call.reset"):
            try:  # injected channel death under an outgoing call: the
                # send below fails and EOF replay races it — exactly one
                # of the two owns the fallback token
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            conn.send(("wexec", spec))
        except OSError:
            # The inflight entry is the fallback TOKEN: exactly one of
            # this path and _on_wpeer_eof's replay pops it (dict.pop is
            # atomic under the GIL), so a send failing concurrently with
            # channel EOF can never submit the call twice.
            if conn.inflight.pop(spec.task_id, None) is None:
                return True  # EOF handler owns the fallback already
            with self._direct_lock:
                for rid in spec.return_ids:
                    self._direct_pending.pop(rid, None)
            return False
        self.direct_calls_sent += 1
        return True

    def _on_wpeer_frame(self, conn: "_WorkerPeer", msg):
        op = msg[0]
        if op == "wexec":
            spec: TaskSpec = msg[1]
            self.direct_routes[spec.task_id] = conn
            self.order_gate.submit(
                spec, lambda s=spec: self.task_queue.put(s))
        elif op == "wdone":
            for task_id, outs in msg[1]:
                conn.inflight.pop(task_id, None)
                self._apply_direct_done(outs)

    def _on_wpeer_eof(self, conn: "_WorkerPeer"):
        if conn.initiated:
            with self._peer_lock:
                if self._peer_conns.get(conn.path) is conn:
                    self._peer_conns.pop(conn.path, None)
            # Poison location-cache entries that point at the dead path.
            for aid, loc in list(self.actor_locations.items()):
                if (isinstance(loc, tuple) and len(loc) > 1
                        and loc[0] == "uds" and loc[1] == conn.path):
                    self.actor_locations.pop(aid, None)
            # In-flight calls MAY have executed (the frame was sent):
            # only retry-permitted calls replay, the rest fail cleanly.
            # The pop is the fallback token shared with the sender's
            # OSError path — whoever pops the entry owns the fallback.
            for task_id, spec in list(conn.inflight.items()):
                if conn.inflight.pop(task_id, None) is not None:
                    self._direct_fallback(spec, maybe_executed=True)
        else:
            # The calling worker died: its results are moot — drop the
            # routes so replies fall through to the discard path.
            for task_id, c in list(self.direct_routes.items()):
                if c is conn:
                    self.direct_routes.pop(task_id, None)

    def _apply_direct_done(self, outs):
        """Caller side of a wdone: resolve futures like head obj pushes.
        Inline values are pinned while their ref lives (see
        _direct_values); escaped-while-pending refs materialize now."""
        for rid, status, payload, bufs in outs:
            if status in ("inline", "err"):
                value = serialization.deserialize(payload, bufs)
                self.object_cache[rid] = value
                escaped = None
                with self._direct_lock:
                    escaped = self._direct_pending.pop(rid, None)
                    if escaped is not None and (
                            escaped or self.refcount.is_owned(rid)):
                        self._direct_values[rid] = value
                if escaped:
                    self._materialize_direct(rid, value)
            else:  # shm: already in the shared arena + head notified
                with self._direct_lock:
                    self._direct_pending.pop(rid, None)
            if self._dep_pins:
                self._release_dep_pin(rid)
            with self._wait_lock:
                for ev in self._pending_waits.pop(rid, []):
                    ev.set()

    def _direct_fallback(self, spec, maybe_executed: bool):
        """A direct call's channel failed. Retry-permitted calls replay
        through the head (which parks/fails them against the actor's
        fate); a possibly-executed non-retryable call must only have its
        returns failed — replaying could double-execute."""
        with self._direct_lock:
            for rid in spec.return_ids:
                self._direct_pending.pop(rid, None)
        retryable = (spec.retries_left or 0) > 0
        try:
            if maybe_executed and not retryable:
                self.send(("direct_fail", spec))
            else:
                if maybe_executed:
                    # The replay consumes retry budget (same contract as
                    # the agent plane's _direct_fallback): a maybe-
                    # executed call must not replay for free forever.
                    spec.retries_left -= 1
                self.send(("direct_actor_head", spec))
        except OSError:
            pass

    def _materialize_direct(self, rid: bytes, value):
        """An owned direct-call result escaped this process: store it
        under its exact id and tell the head, so borrowers anywhere can
        resolve it (mirrors put() visibility)."""
        nbytes = int(getattr(value, "nbytes", 0) or (1 << 20))
        try:
            _put_with_spill(self, ObjectID(rid), value, nbytes)
            self.send(("put_notify", rid))
        except Exception:  # noqa: BLE001 — borrower get() will surface it
            traceback.print_exc()

    def _on_owned_free(self, key: bytes):
        with self._direct_lock:
            self._direct_values.pop(key, None)
            self._direct_pending.pop(key, None)
        self.send(("free_put", key))

    def _on_owned_escape(self, key: bytes):
        with self._direct_lock:
            if key in self._direct_values:
                value = self._direct_values[key]
            elif key in self._direct_pending:
                # Escaped before the result arrived: flag so
                # _apply_direct_done materializes on arrival.
                self._direct_pending[key] = True
                return
            else:
                return  # a plain put() escaping; head already knows it
        self._materialize_direct(key, value)

    # -- streaming (ObjectRefGenerator consumed from a worker) --

    def next_stream_item(self, task_id: bytes, idx: int,
                         timeout: float | None = None):
        """Blocks until yield #idx of a streaming task exists; None = the
        stream closed first. The head parks the request off-thread."""
        return self.request("stream_next", (task_id, idx, timeout),
                            timeout=None if timeout is None else timeout + 10)

    def stream_finished(self, task_id: bytes) -> bool:
        return self.request("stream_finished", task_id)

    def release_stream(self, task_id: bytes):
        try:
            self.request("stream_release", task_id)
        except Exception:  # noqa: BLE001 — release is best effort
            pass

    def request(self, what, arg=None, timeout=30.0):
        """Synchronous control-plane query to the head."""
        fut = concurrent.futures.Future()
        with self._req_lock:
            self._req_seq += 1
            req_id = self._req_seq
            self._req_futures[req_id] = fut
        self.send(("request", req_id, what, arg))
        result = fut.result(timeout)
        if isinstance(result, Exception):
            raise result
        return result

    # -- frame routing --

    def handle_push(self, msg):
        op = msg[0]
        if op == "obj":
            _, oid, status, payload, bufs = msg
            if status == "inline":
                self.object_cache[oid] = serialization.deserialize(payload, bufs)
            elif status == "err":
                self.object_cache[oid] = serialization.deserialize(payload, bufs)
            # "shm": value readable from the store
            if self._dep_pins:
                self._release_dep_pin(oid)
            with self._wait_lock:
                for ev in self._pending_waits.pop(oid, []):
                    ev.set()
        elif op == "reg_fn":
            _, fn_id, blob = msg
            self.functions[fn_id] = cloudpickle.loads(blob)
        elif op == "resp":
            _, req_id, result = msg
            with self._req_lock:
                fut = self._req_futures.pop(req_id, None)
            if fut is not None:
                fut.set_result(result)
        elif op == "actor_moved":
            self.actor_locations.pop(msg[1], None)
        elif op == "pubsub_msg":
            _, channel, key, message = msg
            with self._pubsub_lock:
                cbs = list(self._pubsub_cbs.get((channel, key), ()))
            for cb in cbs:
                try:
                    cb(message)
                except Exception:  # noqa: BLE001 — keep dispatching
                    import traceback
                    traceback.print_exc()
        else:
            raise RuntimeError(f"worker: unknown push {op}")


def _put_with_spill(rt: "WorkerRuntime", oid: ObjectID, value, nbytes: int):
    """Store a value with the spill-before-pressure policy: arena LRU
    eviction silently destroys owned objects, so a head-node worker asks
    the head to make room BEFORE crossing the spill threshold (and retries
    once on full). On other nodes the head could not help — the request is
    skipped and the agent arena's eviction is the pressure valve."""
    from ray_tpu.core.status import ObjectExistsError, ObjectStoreFullError
    on_head = os.environ.get("RAY_TPU_IS_HEAD_NODE") == "1"
    if on_head and not rt.store.reservation_fits(nbytes):
        stats = rt.store.stats()
        cap = stats["capacity"] or 1
        limit = get_config().object_spill_threshold * cap
        if stats["allocated"] + nbytes > limit:
            rt.request("spill",
                       int(stats["allocated"] + nbytes - limit) + (4 << 20))
    table = arrow_block_of(value)
    try:
        if table is not None:
            rt.store.put_arrow(oid, table)
        else:
            rt.store.put_serialized(oid, value)
    except ObjectExistsError:
        # Replayed task: a restarted head re-grants any lease whose
        # node_done it never saw, so a PRIOR attempt may have sealed this
        # exact result already. The publication is done — report success
        # (at-least-once execution, exactly-once publication).
        return
    except ObjectStoreFullError:
        if not on_head:
            raise
        rt.request("spill", int(nbytes * 1.5) + (1 << 20))
        try:
            if table is not None:
                rt.store.put_arrow(oid, table)
            else:
                rt.store.put_serialized(oid, value)
        except ObjectExistsError:
            return


GLOBAL: WorkerRuntime | None = None


def _resolve_arg(rt: WorkerRuntime, obj):
    from ray_tpu.core.object_ref import ObjectRef
    if isinstance(obj, ObjectRef):
        return rt._get_one(obj, timeout=60.0)
    return obj


def _resolve_args(rt: WorkerRuntime, args, kwargs):
    """Resolve a task's (args, kwargs), prefetching ref args as ONE
    vectored batch first — a reduce task's N exchange pieces pull
    concurrently (same-source groups over one objxfer round) instead of
    N serial get rounds."""
    from ray_tpu.core.object_ref import ObjectRef
    refs = [a for a in args if isinstance(a, ObjectRef)]
    refs += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
    if len(refs) >= 2:
        rt.prefetch_refs(refs)
    return ([_resolve_arg(rt, a) for a in args],
            {k: _resolve_arg(rt, v) for k, v in kwargs.items()})


def _spec_args(rt: WorkerRuntime, spec: TaskSpec):
    """Decode a spec's (args, kwargs), wherever they live: an offloaded
    shm ArgPack (args_ref), a language-neutral proto payload, or the
    inline pickle frame."""
    aref = getattr(spec, "args_ref", None)
    if aref is not None:
        found, pack = rt.store.get_deserialized(ObjectID(aref), timeout=0)
        if not found:
            # Cross-node call: the pack lives in the submitter's arena;
            # resolve through the normal object plane (head directory ->
            # peer pull), same as any ObjectRef argument.
            from ray_tpu.core.object_ref import ObjectRef
            pack = rt._get_one(ObjectRef(ObjectID(aref)), timeout=60.0)
        return pack.load()
    if getattr(spec, "payload_format", None) == "proto":
        # Client-plane submissions keep their tagged args end to end —
        # never re-pickled.
        from ray_tpu.core import proto_wire
        return proto_wire.decode_task_args(spec.payload)
    return serialization.deserialize(spec.payload, spec.buffers)


class _RuntimeEnv:
    """Apply a per-task/actor runtime_env (parity: the runtime-env agent
    materializing env_vars / working_dir / py_modules,
    `_private/runtime_env/agent/runtime_env_agent.py:167`).
    env_vars are node-independent; working_dir/py_modules are applied as
    LOCAL paths and assume a shared filesystem across nodes (no packaging/
    upload yet — a missing path fails the task with FileNotFoundError,
    conda/container isolation out of scope). Context-manager use restores
    state for tasks; actors enter() permanently."""

    def __init__(self, renv: dict | None):
        self.renv = renv or {}
        self._saved_env: dict[str, str | None] = {}
        self._saved_cwd = None
        self._added_paths: list[str] = []

    def __enter__(self):
        import sys as _sys
        try:
            for k, v in (self.renv.get("env_vars") or {}).items():
                self._saved_env[k] = os.environ.get(k)
                os.environ[k] = str(v)
            wd = self.renv.get("working_dir")
            if wd:
                self._saved_cwd = os.getcwd()
                os.chdir(wd)
                if wd not in _sys.path:
                    _sys.path.insert(0, wd)
                    self._added_paths.append(wd)
            for p in self.renv.get("py_modules") or []:
                if p not in _sys.path:
                    _sys.path.insert(0, p)
                    self._added_paths.append(p)
        except BaseException:
            # __exit__ is not called when __enter__ raises: roll back here
            # or the pooled worker keeps half-applied env forever.
            self.__exit__()
            raise
        return self

    def __exit__(self, *exc):
        import sys as _sys
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if self._saved_cwd is not None:
            os.chdir(self._saved_cwd)
        for p in self._added_paths:
            try:
                _sys.path.remove(p)
            except ValueError:
                pass
        return False


_SYNC_EXEC_LOOP = threading.local()


def _run_coroutine_sync(coro):
    """Drive a coroutine returned by a SYNC-executed function to
    completion. Keeps one loop per executor thread (matching the old
    implicit-get_event_loop() behavior, where loop-bound state survived
    across calls) without the deprecated implicit-loop API that warns on
    3.12+."""
    loop = getattr(_SYNC_EXEC_LOOP, "loop", None)
    if loop is None or loop.is_closed():
        loop = asyncio.new_event_loop()
        _SYNC_EXEC_LOOP.loop = loop
    return loop.run_until_complete(coro)


def _execute(rt: WorkerRuntime, spec: TaskSpec, fn):
    """Runs one task; returns ('ok'|'err', value_or_TaskError)."""
    for oid, (payload, bufs) in spec.inline_deps.items():
        rt.object_cache[oid] = serialization.deserialize(payload, bufs)
    renv_spec = getattr(spec, "runtime_env", None)
    tev = _TEV.enabled
    if tev:
        # Sub-span POINTS are stamped as bare floats and packed into ONE
        # event at seal time (_reply_result) — per-point emits measurably
        # moved the task storm via allocation/GC churn alone.
        spec.exec_ts = [time.time(), 0.0, 0.0]
    try:
        args, kwargs = _spec_args(rt, spec)
        args, kwargs = _resolve_args(rt, args, kwargs)
        if tev:
            spec.exec_ts[1] = time.time()  # args deserialized/resolved
        rt.current_task = spec  # describe() formatted lazily on demand
        # Read by util.placement_group.get_current_placement_group(); lives
        # on the runtime object because this module is __main__ in workers.
        # Actor methods carry no per-task strategy — fall back to the
        # strategy the actor itself was created with.
        rt.current_scheduling_strategy = (
            spec.scheduling_strategy
            or getattr(rt, "actor_scheduling_strategy", None))
        ctx = (contextlib.nullcontext() if renv_spec is None
               else _RuntimeEnv(renv_spec))
        from ray_tpu.util import tracing as _tracing
        span = (_tracing.execute_span(spec.describe(),
                                      getattr(spec, "trace_ctx", None))
                if _tracing._enabled else contextlib.nullcontext())
        with ctx, span:
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = _run_coroutine_sync(result)
        return "ok", result
    except BaseException as e:  # noqa: BLE001 — errors cross the wire
        return "err", TaskError.from_exception(e, spec.describe())
    finally:
        if tev and spec.exec_ts is not None:
            spec.exec_ts[2] = time.time()
        rt.current_scheduling_strategy = getattr(
            rt, "actor_scheduling_strategy", None)


def _execute_streaming(rt: WorkerRuntime, spec: TaskSpec, fn):
    """Run a generator task: one "stream_item" per yield, then a normal
    empty "done" (which closes the stream and re-idles this worker).
    Parity: reference streaming generator execution (_raylet.pyx
    execute_task's streaming path)."""
    cfg = get_config()

    def entry_for(value, status="inline-or-shm"):
        rid = os.urandom(16)
        if status != "err":
            table = arrow_block_of(value)
            if (table is not None
                    and table.nbytes > cfg.max_inline_object_bytes):
                _put_with_spill(rt, ObjectID(rid), table, table.nbytes)
                return (rid, "shm", None, None)
        payload, bufs, _ = serialization.serialize_value(value)
        if status == "err":
            return (rid, "err", payload, bufs)
        nbytes = serialization.total_nbytes(payload, bufs)
        if nbytes <= cfg.max_inline_object_bytes:
            return (rid, "inline", payload, bufs)
        _put_with_spill(rt, ObjectID(rid), value, nbytes)
        return (rid, "shm", None, None)

    renv_spec = getattr(spec, "runtime_env", None)
    if _TEV.enabled:
        task_events.emit_task(spec, "EXEC_START")
    try:
        for oid, (payload, bufs) in spec.inline_deps.items():
            rt.object_cache[oid] = serialization.deserialize(payload, bufs)
        args, kwargs = _spec_args(rt, spec)
        args, kwargs = _resolve_args(rt, args, kwargs)
        rt.current_task = spec
        rt.current_scheduling_strategy = (
            spec.scheduling_strategy
            or getattr(rt, "actor_scheduling_strategy", None))
        from ray_tpu.util import tracing as _tracing
        ctx = (contextlib.nullcontext() if renv_spec is None
               else _RuntimeEnv(renv_spec))
        span = (_tracing.execute_span(spec.describe(),
                                      getattr(spec, "trace_ctx", None))
                if _tracing._enabled else contextlib.nullcontext())
        with ctx, span:
            gen = fn(*args, **kwargs)
            if inspect.isasyncgen(gen):
                raise TypeError(
                    "async-generator streaming methods are not supported; "
                    "use a sync generator (yield from an asyncio loop via "
                    "run_until_complete if needed)")
            for value in gen:
                rt.send(("stream_item", spec.task_id, entry_for(value)))
    except BaseException as e:  # noqa: BLE001 — errors ride the stream
        err = TaskError.from_exception(e, spec.describe())
        try:
            rt.send(("stream_item", spec.task_id, entry_for(err, "err")))
        except OSError:
            pass
    finally:
        if _TEV.enabled:
            task_events.emit_task(spec, "EXEC_DONE")
        rt.current_scheduling_strategy = getattr(
            rt, "actor_scheduling_strategy", None)
    rt.send(("done", spec.task_id, spec.actor_id, []))
    rt.flush_task_events()


def _reply_cancelled(rt: WorkerRuntime, spec: TaskSpec):
    from ray_tpu.core.status import TaskCancelledError
    _reply_result(rt, spec, "err", TaskError.from_exception(
        TaskCancelledError(f"task {spec.describe()} was cancelled"),
        spec.describe()))


def _reply_result(rt: WorkerRuntime, spec: TaskSpec, status, result,
                  batcher: "_ReplyBatcher | None" = None):
    """Report task results. With `batcher`, the reply rides the coalescing
    flusher (one "done_batch" frame per burst of pipelined actor calls)
    instead of its own frame."""
    cfg = get_config()
    n_returns = len(spec.return_ids)
    if status == "ok" and n_returns > 1:
        results = list(result) if isinstance(result, (tuple, list)) else [result]
        if len(results) != n_returns:
            status = "err"
            result = TaskError.from_exception(
                ValueError(f"task returned {len(results)} values, expected {n_returns}"),
                spec.describe())
    if status == "err":
        payload, bufs, _ = serialization.serialize_value(result)
        outs = [(rid, "err", payload, bufs) for rid in spec.return_ids]
    else:
        values = results if n_returns > 1 else [result]
        outs = []
        for rid, value in zip(spec.return_ids, values):
            table = arrow_block_of(value)
            if (table is not None
                    and table.nbytes > cfg.max_inline_object_bytes):
                # Arrow block return: streamed straight into the arena in
                # the tagged IPC layout — no pickle of the block bytes.
                _put_with_spill(rt, ObjectID(rid), table, table.nbytes)
                outs.append((rid, "shm", None, None))
                continue
            payload, bufs, _ = serialization.serialize_value(value)
            nbytes = serialization.total_nbytes(payload, bufs)
            if nbytes <= cfg.max_inline_object_bytes:
                outs.append((rid, "inline", payload, bufs))
            else:
                _put_with_spill(rt, ObjectID(rid), value, nbytes)
                outs.append((rid, "shm", None, None))
    tev = None
    if _TEV.enabled and spec.exec_ts is not None:
        # Packed exec record: (attempt, exec_start, args_ready,
        # exec_done, seal). It PIGGYBACKS ON THE DONE FRAME itself (the
        # ultimate already-sent frame) — the head unpacks it into an
        # EXEC_SPANS pipeline event, so the reply hot path adds three
        # clock reads and one tuple, with no extra frames, ring traffic
        # or flush work (a separate event-ring hop here measurably moved
        # the 1-CPU task storm).
        es, ar, ed = spec.exec_ts
        tev = (max(0, (spec.max_retries or 0)
                   - (spec.retries_left or 0)), es, ar, ed, time.time())
    route = (rt.direct_routes.pop(spec.task_id, None)
             if rt.direct_routes else None)
    if route is not None:
        # Direct-call reply: straight back on the caller's channel — the
        # head/agent never saw this task, so its exec record ships
        # through the event ring instead of a done frame (flushed on the
        # piggybacked cadence).
        if tev is not None:
            _TEV.emit(spec.task_id, tev[0], "EXEC_SPANS", None,
                      tev[1:4], ts=tev[4])
            tev = None
        # Big results went into the node's SHARED arena; notify the head
        # of the location so borrowers beyond the caller can still
        # resolve them (async on agent nodes: the frame rides the relay).
        for entry in outs:
            if entry[1] == "shm":
                rt.send(("put_notify", entry[0]))
        if batcher is not None:
            # A burst of pipelined direct calls coalesces into ONE wdone
            # frame per caller channel (the flusher groups by route).
            batcher.add(spec.task_id, spec.actor_id, outs, route=route)
            return
        if route.alive:
            try:
                route.send(("wdone", [(spec.task_id, outs)]))
                return
            except OSError:
                pass
        # Channel broke under the reply (the caller may well be alive —
        # only its conn died): fall through to a plain head "done". The
        # head banks the outs in its directory and the caller's wait_obj
        # resolves them, so a reply is never silently lost.
    if batcher is not None:
        batcher.add(spec.task_id, spec.actor_id, outs, tev)
        return
    rt.send(("done", spec.task_id, spec.actor_id, outs) if tev is None
            else ("done", spec.task_id, spec.actor_id, outs, tev))
    # Piggyback: a due task-event/metric flush rides the sender batching
    # right behind the done frame (one coalesced write, no extra wakeup).
    rt.flush_task_events()


class _ReplyBatcher:
    """Coalesces actor completion frames with a BOUNDED delay.

    A burst of pipelined fast calls flushes as one "done_batch" (head
    path) or one "wdone" per caller channel (direct worker-peer path); a
    result never waits on the NEXT call's execution (the flusher thread
    sends it within `max_delay` regardless) and flushes immediately when
    the task queue is drained — so get(timeout)/wait progress semantics
    hold even when a slow call sits behind a fast one."""

    def __init__(self, rt: WorkerRuntime, max_delay: float = 0.001,
                 max_batch: int = 64):
        self.rt = rt
        self.max_delay = max_delay
        self.max_batch = max_batch
        self._cv = threading.Condition()
        self._batch: list = []          # head-path entries
        self._routed: list = []         # (route, task_id, actor_id, outs)
        self._urgent = False
        threading.Thread(target=self._loop, daemon=True,
                         name="rtpu-reply-flush").start()

    def add(self, task_id, actor_id, outs, tev=None, route=None):
        with self._cv:
            if route is not None:
                self._routed.append((route, task_id, actor_id, outs))
            else:
                self._batch.append((task_id, actor_id, outs) if tev is None
                                   else (task_id, actor_id, outs, tev))
            if (len(self._batch) + len(self._routed) >= self.max_batch
                    or self.rt.task_queue.empty()):
                self._urgent = True
            self._cv.notify()

    def flush_now(self):
        """Synchronous drain — used at shutdown, where waking the daemon
        flusher would race os._exit. Entries are popped under the lock, so
        a concurrent flusher pass and this call each send disjoint sets."""
        with self._cv:
            batch = self._batch
            routed = self._routed
            self._batch = []
            self._routed = []
            self._urgent = False
        try:
            self._send(batch, routed)
        except OSError:
            pass

    def _send(self, batch: list, routed: list):
        for route, pairs, entries in self._group_routes(routed):
            sent = False
            if route.alive:
                try:
                    route.send(("wdone", pairs))
                    sent = True
                except OSError:
                    pass
            if not sent:
                # Caller channel died under the reply: bank each result
                # at the head instead (its directory resolves the
                # caller's wait_obj) — a reply is never silently lost.
                batch = batch + [(tid, aid, outs)
                                 for (tid, aid, outs) in entries]
        if len(batch) == 1:
            self.rt.send(("done",) + tuple(batch[0]))
        elif batch:
            self.rt.send(("done_batch", batch))

    @staticmethod
    def _group_routes(routed: list):
        if not routed:
            return ()
        groups: dict = {}
        for route, task_id, actor_id, outs in routed:
            g = groups.get(id(route))
            if g is None:
                g = groups[id(route)] = (route, [], [])
            g[1].append((task_id, outs))
            g[2].append((task_id, actor_id, outs))
        return groups.values()

    def _loop(self):
        while True:
            with self._cv:
                while not (self._batch or self._routed):
                    self._urgent = False
                    self._cv.notify_all()
                    self._cv.wait()
                if not self._urgent:
                    # Let a burst accumulate, but never longer than
                    # max_delay past the first pending reply.
                    self._cv.wait(self.max_delay)
                batch = self._batch
                routed = self._routed
                self._batch = []
                self._routed = []
                self._urgent = False
            try:
                self._send(batch, routed)
            except OSError:
                return  # head gone; the worker is about to exit anyway


async def _execute_async(rt, spec, fn):
    from ray_tpu.core.object_ref import ObjectRef
    for oid, (payload, bufs) in spec.inline_deps.items():
        rt.object_cache[oid] = serialization.deserialize(payload, bufs)
    if _TEV.enabled:
        spec.exec_ts = [time.time(), 0.0, 0.0]
    try:
        loop = asyncio.get_running_loop()
        aref = getattr(spec, "args_ref", None)
        payload = spec.payload
        if (aref is None and not spec.buffers
                and getattr(spec, "payload_format", None) != "proto"
                and (payload is None or len(payload) <= 65536)):
            # Fast path (the async ping storm): tiny inline args decode
            # right on the loop — an executor round trip per call costs
            # far more than the unpickle (this hop, plus one per arg and
            # one for the reply, was the bulk of the old per-actor
            # asyncio funnel's 8x gap vs sync actors).
            args, kwargs = serialization.deserialize(payload, spec.buffers)
        else:
            # Off-thread: an offloaded arg pack may need a cross-node
            # fetch.
            args, kwargs = await loop.run_in_executor(
                None, _spec_args, rt, spec)
        if any(type(a) is ObjectRef for a in args):
            # Only ref args can block (store probe / head round trip).
            args = [await loop.run_in_executor(None, _resolve_arg, rt, a)
                    if type(a) is ObjectRef else a for a in args]
        if kwargs:
            kwargs = {k: (await loop.run_in_executor(
                              None, _resolve_arg, rt, v)
                          if type(v) is ObjectRef else v)
                      for k, v in kwargs.items()}
        if _TEV.enabled and spec.exec_ts is not None:
            spec.exec_ts[1] = time.time()
        result = fn(*args, **kwargs)
        if inspect.iscoroutine(result):
            result = await result
        return "ok", result
    except BaseException as e:  # noqa: BLE001
        return "err", TaskError.from_exception(e, spec.describe())
    finally:
        if _TEV.enabled and spec.exec_ts is not None:
            spec.exec_ts[2] = time.time()


class _AsyncShard:
    """One event-loop thread of the sharded async-actor executor."""

    __slots__ = ("idx", "dq", "loop", "wake", "sem", "inflight", "thread")

    def __init__(self, idx: int):
        self.idx = idx
        self.dq: collections.deque = collections.deque()
        self.loop = None
        self.wake = None
        self.sem = None
        self.inflight = 0
        self.thread = None


class _AsyncActorExecutor:
    """Sharded, work-stealing asyncio executor for async actors.

    Replaces the single per-actor asyncio funnel: N threads each run
    their own event loop; the worker's main thread dispatches specs to
    the least-loaded shard's deque, and a shard that drains its own
    queue steals from the busiest sibling (deque ops are atomic under
    the GIL, so steals need no locks). Replies coalesce through the
    shared _ReplyBatcher — direct-path results flush as ONE wdone frame
    per caller channel per burst.

    Concurrency semantics: max_concurrency splits across shards (each
    shard bounds its slice with an asyncio.Semaphore). With >1 shard,
    coroutines of one actor run on several OS threads — the GIL keeps
    attribute access atomic, but methods that mutate instance state
    across awaits and assumed loop-serialized interleaving should set
    async_actor_executor_shards=1."""

    def __init__(self, rt: WorkerRuntime, n_shards: int,
                 max_concurrency: int, batcher: "_ReplyBatcher"):
        self.rt = rt
        self.batcher = batcher
        self.stopping = False
        per = max(1, max_concurrency // n_shards)
        # Append as they boot: a shard's loop may probe `shards` (steal)
        # before its siblings exist.
        self.shards: list[_AsyncShard] = []
        for i in range(n_shards):
            self.shards.append(self._start_shard(i, per))

    def _start_shard(self, idx: int, per: int) -> _AsyncShard:
        sh = _AsyncShard(idx)
        ready = threading.Event()

        def run():
            asyncio.run(self._shard_main(sh, per, ready))

        sh.thread = threading.Thread(target=run, daemon=True,
                                     name=f"rtpu-async-{idx}")
        sh.thread.start()
        ready.wait()
        return sh

    def _steal(self, me: _AsyncShard):
        busiest, depth = None, 0
        for sh in self.shards:
            if sh is not me and len(sh.dq) > depth:
                busiest, depth = sh, len(sh.dq)
        if busiest is None:
            return None
        try:
            return busiest.dq.pop()  # newest end: cheapest cache handoff
        except IndexError:
            return None

    async def _shard_main(self, sh: _AsyncShard, per: int,
                          ready: threading.Event):
        sh.loop = asyncio.get_running_loop()
        sh.wake = asyncio.Event()
        sh.sem = asyncio.Semaphore(per)
        ready.set()
        rt = self.rt
        while True:
            try:
                item = sh.dq.popleft()
            except IndexError:
                item = self._steal(sh)
            if item is None:
                if self.stopping:
                    break
                sh.wake.clear()
                # Re-check after clear: a dispatcher append + set that
                # landed between the steal miss and the clear is caught
                # by this probe instead of sleeping until the next wake.
                if not sh.dq:
                    await sh.wake.wait()
                continue
            spec, fn, streaming = item
            if streaming:
                # Sync-generator streaming works on async actors too: the
                # generator runs on an executor thread (async generators
                # are rejected inside _execute_streaming).
                sh.loop.run_in_executor(None, _execute_streaming,
                                        rt, spec, fn)
                continue
            sh.inflight += 1
            sh.loop.create_task(self._run_one(sh, spec, fn))
        while sh.inflight:  # graceful drain before the loop closes
            await asyncio.sleep(0.005)

    async def _run_one(self, sh: _AsyncShard, spec, fn):
        rt = self.rt
        try:
            async with sh.sem:
                status, result = await _execute_async(rt, spec, fn)
            if status == "ok" and (
                    result is None or type(result) in (bool, int, float)
                    or (type(result) in (str, bytes) and len(result) < 8192)):
                # Small scalar reply: serialize + batch right on the loop
                # (one more executor hop would dominate a ping()).
                _reply_result(rt, spec, status, result,
                              batcher=self.batcher)
            else:
                await sh.loop.run_in_executor(
                    None, _reply_result, rt, spec, status, result,
                    self.batcher)
        except Exception:  # noqa: BLE001 — a reply failure must not
            traceback.print_exc()  # kill the shard loop
        finally:
            sh.inflight -= 1

    def run(self):
        """Dispatcher — runs on the worker's main thread (the old per-
        task queue-get executor hop is gone: the blocking get happens
        here, off every event loop)."""
        rt = self.rt
        shards = self.shards
        while not rt.shutdown.is_set():
            spec = rt.task_queue.get()
            if spec is None:
                break
            if spec.task_id in rt.cancelled_tasks:
                rt.cancelled_tasks.discard(spec.task_id)
                _reply_cancelled(rt, spec)
                continue
            fn = _actor_method(rt, spec)
            target = shards[0]
            if len(shards) > 1:
                load = len(target.dq) + target.inflight
                for sh in shards[1:]:
                    ln = len(sh.dq) + sh.inflight
                    if ln < load:
                        target, load = sh, ln
            target.dq.append(
                (spec, fn, bool(getattr(spec, "streaming", False))))
            try:
                target.loop.call_soon_threadsafe(target.wake.set)
            except RuntimeError:
                # Target loop died (crash on its thread): any live
                # sibling can steal the queued item once woken.
                for sh in shards:
                    try:
                        sh.loop.call_soon_threadsafe(sh.wake.set)
                        break
                    except RuntimeError:
                        continue
        self.stopping = True
        for sh in shards:
            try:
                sh.loop.call_soon_threadsafe(sh.wake.set)
            except RuntimeError:
                pass  # loop already closed
        for sh in shards:
            sh.thread.join(timeout=5.0)


def _run_actor_async(rt: WorkerRuntime, max_concurrency: int,
                     batcher: "_ReplyBatcher | None" = None):
    """Sharded asyncio executor for async actors (parity: fiber.h async
    actors, distributed over async_actor_executor_shards event loops)."""
    cfg = get_config()
    conc = max_concurrency or cfg.async_actor_default_max_concurrency
    n = cfg.async_actor_executor_shards
    if n <= 0:
        n = max(1, min(4, (os.cpu_count() or 1) // 2))
    n = max(1, min(n, conc))
    if batcher is None:
        batcher = _ReplyBatcher(rt)
    _AsyncActorExecutor(rt, n, conc, batcher).run()
    batcher.flush_now()


def _ensure_accelerator_platform(num_tpus):
    """Re-latch this worker onto the host's jax platform for TPU work.

    Pooled workers boot with JAX_PLATFORMS=cpu (accelerator visibility,
    parity: per-worker CUDA_VISIBLE_DEVICES/TPU_VISIBLE_CHIPS assignment);
    the first task/actor that actually reserves TPU chips flips the worker
    back to the driver's platform. Must happen before the worker's first
    jax computation — jax latches its backend on first use."""
    if not num_tpus:
        return
    host = os.environ.get("RAY_TPU_HOST_JAX_PLATFORMS")
    if host is None:  # visibility control disabled
        return
    if os.environ.get("JAX_PLATFORMS", "") == host:
        return
    os.environ["JAX_PLATFORMS"] = host
    try:
        import jax
        jax.config.update("jax_platforms", host or None)
    except Exception as e:  # noqa: BLE001
        raise RuntimeError(
            f"worker could not switch to host jax platform {host!r} for a "
            f"TPU task (was the CPU backend already initialized?): {e}")


def _actor_method(rt: WorkerRuntime, spec: TaskSpec):
    if spec.method_name == "__run_with_instance__":
        # Escape hatch used by compiled graphs (ray_tpu.dag): the first task
        # argument is a pickled fn(instance, *rest) executed against the
        # live actor instance (parity: the injected do_exec_tasks loop,
        # reference dag/compiled_dag_node.py:193).
        def run(fn, *args, **kwargs):
            return fn(rt.actor_instance, *args, **kwargs)
        return run
    method = getattr(rt.actor_instance, spec.method_name)
    return method


def main():
    if sys.argv[1] == "--zygote":
        return zygote_main(sys.argv[2], int(sys.argv[3]))
    _worker_main(sys.argv[1], WorkerID.from_hex(sys.argv[2]), int(sys.argv[3]))


def _die_with_parent():
    """PR_SET_PDEATHSIG: the kernel SIGKILLs this process when its parent
    dies. Belt-and-braces over the socket-EOF exit path — a SIGKILLed
    head/agent/zygote must never leave orphaned workers stealing the box
    (r4's bench starved behind exactly such a leak)."""
    if sys.platform != "linux":
        return
    try:
        import ctypes
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, 9, 0, 0, 0)  # PR_SET_PDEATHSIG, SIGKILL
    except Exception:  # noqa: BLE001 — hardening only
        pass


def zygote_main(store_path: str, ctrl_fd: int):
    """Forkserver: pays the interpreter+jax import cost once, then forks a
    ready-to-run worker in milliseconds per head request.

    Parity note: the reference amortizes worker startup with prestarted idle
    workers (`src/ray/raylet/worker_pool.h:228` prestart + idle cache); on this
    runtime a fork zygote additionally makes cold spawns (actor bursts, pool
    replenish after OOM kills) cheap. Protocol: head sends one JSON line plus
    one SCM_RIGHTS fd per spawn; zygote replies with the child pid.
    """
    import array
    import json
    import signal
    import socket as socket_mod
    import struct

    _die_with_parent()
    try:  # usually already loaded via sitecustomize; make the warmup explicit
        import jax  # noqa: F401
        _honor_platform_env(jax)
    except ImportError:
        pass
    if Config.from_env().gc_freeze_init:
        # Freeze the warmed jax universe BEFORE forking: children skip
        # re-scanning ~1M immortal objects on every full collection, and
        # the frozen pages stay COW-shared across the whole pool (gc
        # headers are never dirtied by gen-2 passes).
        import gc
        gc.freeze()

    # Live children (pid stays a zombie — unrecyclable — until we reap it
    # here, so a "kill" request can never hit a recycled pid).
    live: set[int] = set()

    def _reap(_sig=None, _frame=None):
        while True:
            try:
                pid, _ = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            live.discard(pid)

    signal.signal(signal.SIGCHLD, _reap)
    ctrl = socket_from_fd(ctrl_fd)
    # staticcheck: ok fd-use-unguarded — process-lifetime socket: the
    # zygote exits with its ctrl channel; any failure here kills it.
    ctrl.sendall(b"RDY0")
    fdsize = array.array("i").itemsize
    while True:
        fds = array.array("i")
        try:
            msg, ancdata, _flags, _addr = ctrl.recvmsg(
                4096, socket_mod.CMSG_LEN(fdsize))
        except OSError:
            os._exit(0)
        if not msg:
            os._exit(0)
        for level, ctype, data in ancdata:
            if level == socket_mod.SOL_SOCKET and ctype == socket_mod.SCM_RIGHTS:
                fds.frombytes(data[: len(data) - (len(data) % fdsize)])
        req = json.loads(msg)
        if "kill" in req:
            pid = req["kill"]
            if pid in live:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            ctrl.sendall(struct.pack("<I", 0))
            continue
        fd = fds[0]
        # Block SIGCHLD so a fast-exiting child can't be reaped before it is
        # in `live` (which would leave a stale pid eligible for os.kill).
        signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGCHLD})
        pid = os.fork()
        if pid == 0:
            signal.signal(signal.SIGCHLD, signal.SIG_DFL)
            signal.pthread_sigmask(signal.SIG_UNBLOCK, {signal.SIGCHLD})
            ctrl.close()
            logf = os.open(req["log"], os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
            os.dup2(logf, 1)
            os.dup2(logf, 2)
            os.close(logf)
            try:
                _worker_main(store_path, WorkerID.from_hex(req["worker_id"]), fd)
            except BaseException:  # noqa: BLE001 — log then die nonzero;
                traceback.print_exc()  # os._exit skips the excepthook
                os._exit(1)
            os._exit(0)
        live.add(pid)
        signal.pthread_sigmask(signal.SIG_UNBLOCK, {signal.SIGCHLD})
        os.close(fd)
        ctrl.sendall(struct.pack("<I", pid))


def _honor_platform_env(jax_mod):
    """Make jax honor JAX_PLATFORMS even though the environment's
    sitecustomize force-registers the TPU backend at interpreter start.
    Without this, a CPU-platform driver (tests, dryruns) gets workers whose
    matmuls run on the TPU backend — subtly different numerics."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        try:
            jax_mod.config.update("jax_platforms", want)
        except Exception:  # noqa: BLE001 — backend already locked in
            pass


def _worker_main(store_path: str, worker_id: WorkerID, fd: int):
    _die_with_parent()
    set_config(Config.from_env())
    if get_config().gc_gen0_threshold > 0:
        # Same rationale as the head runtime: don't run a gc pass (plus
        # jax's gc callback) every ~70 control messages.
        import gc
        gc.set_threshold(get_config().gc_gen0_threshold)  # gens 1-2 as-is
    venv_site = os.environ.get("RAY_TPU_VENV_SITE")
    if venv_site:
        # Env-pool worker: the pip env's packages shadow the host env for
        # every task this worker runs (parity: pip runtime_env activation).
        sys.path.insert(0, venv_site)
    try:
        import jax as _jax
        _honor_platform_env(_jax)
    except ImportError:
        pass
    if get_config().gc_freeze_init:
        import gc
        gc.freeze()  # covers zygote-less spawns and anything the fork
        # itself allocated; a second freeze after the zygote's is a no-op
    sock = socket_from_fd(fd)

    from ray_tpu.util import tracing as _tracing
    _tracing.maybe_setup_from_env()
    task_events.configure(get_config())

    import queue
    rt = WorkerRuntime(sock, worker_id, store_path)
    rt.task_queue = queue.Queue()
    global GLOBAL
    GLOBAL = rt
    # Route the public API inside this process to the worker runtime.
    from ray_tpu.core import runtime as runtime_mod
    runtime_mod.set_worker_runtime(rt)

    # Pooled workers listen for direct peer calls (head-node AND agent-
    # node); the path rides the ready frame so the head can hand it to
    # same-node callers resolving this worker's actor.
    peer_path = rt.start_peer_listener()
    rt.send(("ready", worker_id.binary(), os.getpid(),
             os.environ.get("RAY_TPU_ENV_KEY") or None, peer_path))

    def _gate_maintenance():
        # The order gate needs a pump for gap timeouts (the agent's
        # select loop plays this role on agent nodes).
        n = 0
        while not rt.shutdown.is_set():
            time.sleep(1.0)
            n += 1
            if rt.order_gate.buffered:
                rt.order_gate.flush_expired()
            if n % 60 == 0:
                rt.order_gate.sweep()

    if not rt.on_agent_node or peer_path is not None:
        # A worker with a peer listener owns the order gate for its
        # actor (peer frames race agent/head-relayed ones); a gate needs
        # a pump for gap timeouts. Agent-node workers WITHOUT a listener
        # never feed their gate (the agent's gate orders their frames).
        threading.Thread(target=_gate_maintenance, daemon=True,
                         name="rtpu-gate").start()

    if _TEV.enabled:
        # Cadence floor for the event/metric flush: the reply-path
        # piggyback covers busy workers; this covers the tail batch an
        # idle worker would otherwise hold forever.
        def _tev_floor():
            period = max(0.05,
                         get_config().task_events_flush_ms / 1000.0)
            while not rt.shutdown.is_set():
                time.sleep(period)
                try:
                    rt.flush_task_events()
                except Exception:  # noqa: BLE001 — flusher must survive
                    pass

        threading.Thread(target=_tev_floor, daemon=True,
                         name="rtpu-tev-flush").start()

    actor_cfg = {}
    executor_threads: list[threading.Thread] = []

    def receiver():
        # Buffered framing: one big recv drains many queued messages (the
        # head pipelines actor calls), halving syscalls vs per-frame reads.
        fb = FrameBuffer()
        pending = []
        while True:
            if not pending:
                try:
                    data = sock.recv(1 << 20)
                except OSError:
                    data = b""
                if not data:
                    rt.shutdown.set()
                    rt.task_queue.put(None)
                    os._exit(0)
                fb.feed(data)
                pending = fb.frames()
                if not pending:
                    continue
            msg = pending.pop(0)
            op = msg[0]
            if op == "batch":
                # One head-side sendall carrying several dispatch frames
                # (pipelined same-key tasks); unpack in order.
                pending[0:0] = msg[1]
                continue
            if op == "exec_raw":
                # Native lease plane (cpp/agent_core.cc dispatch): the
                # spec rides as raw pickle bytes, decoded HERE — the one
                # process that executes it. Only dep-free plain tasks
                # lease, so there is no actor ordering to gate.
                rt.task_queue.put(pickle.loads(msg[1]))
                continue
            if op == "exec":
                spec = msg[1]
                if (spec.actor_id is not None
                        and getattr(spec, "caller_seq", None) is not None
                        and (not rt.on_agent_node
                             or rt._peer_path is not None)):
                    # Head/agent-relayed frames race the worker peer
                    # plane for the same (caller, actor): restore
                    # submission order. Only workers that OWN a peer
                    # listener gate — the gate must be the single
                    # ordering point, so the agent delivers their frames
                    # ungated (and forwards seq_skips here). A listener-
                    # less agent-node worker's frames were already
                    # ordered by its agent's gate, and gating twice
                    # would stall every skip-released slot until the
                    # gap timeout.
                    rt.order_gate.submit(
                        spec, lambda s=spec: rt.task_queue.put(s))
                else:
                    rt.task_queue.put(spec)
            elif op == "seq_skip":
                rt.order_gate.skip(msg[1], msg[2], msg[3])
            elif op == "create_actor":
                actor_cfg["spec"] = msg[1]
                rt.task_queue.put(("__create_actor__", msg[1]))
            elif op == "cancel_task":
                # Best-effort: the executor drops the task if it has not
                # started yet (parity: CancelTask on the receiving worker).
                # Bounded — a cancel that lost the race to an already-
                # started call would otherwise leak its entry forever.
                if len(rt.cancelled_tasks) > 1024:
                    rt.cancelled_tasks.pop()
                rt.cancelled_tasks.add(msg[1])
            elif op == "drop_task":
                # Steal phase one from the scheduler. Under steal_lock
                # against the executor: if the task has begun, refuse the
                # drop (ack False — the head aborts the steal and this
                # execution stands); else mark it dropped so the executor
                # skips it WITHOUT a cancelled reply — a reply would poison
                # the re-dispatched task's return objects.
                with rt.steal_lock:
                    began = msg[1] in rt.begun_tasks
                    if not began:
                        if len(rt.dropped_tasks) > 1024:
                            rt.dropped_tasks.popitem()
                        rt.dropped_tasks[msg[1]] = (
                            rt.dropped_tasks.get(msg[1], 0) + 1)
                try:
                    rt.send(("drop_ack", msg[1], not began))
                except OSError:
                    pass
            elif op == "profile":
                # On-demand stack sampling (parity: dashboard reporter's
                # py-spy endpoint); runs on a side thread so the executor
                # keeps working while being observed.
                def _prof(token=msg[1], duration=msg[2], hz=msg[3]):
                    from ray_tpu.util.profiling import sample_stacks
                    try:
                        report = sample_stacks(duration, hz)
                    except Exception as e:  # noqa: BLE001
                        report = {"error": str(e)}
                    try:
                        rt.send(("profile_result", token, report))
                    except OSError:
                        pass

                threading.Thread(target=_prof, daemon=True).start()
            elif op == "shutdown":
                rt.shutdown.set()
                rt.task_queue.put(None)
            else:
                rt.handle_push(msg)

    threading.Thread(target=receiver, daemon=True, name="rtpu-recv").start()

    def create_actor(cspec):
        try:
            _ensure_accelerator_platform(getattr(cspec, "num_tpus", 0))
            cls = rt.functions[cspec.cls_id]
            args, kwargs = serialization.deserialize(cspec.payload, cspec.buffers)
            args, kwargs = _resolve_args(rt, args, kwargs)
            # Set before __init__ so get_current_placement_group() works
            # inside the constructor too.
            rt.actor_scheduling_strategy = cspec.scheduling_strategy
            # Actors keep their runtime_env for life (no __exit__).
            _RuntimeEnv(getattr(cspec, "runtime_env", None)).__enter__()
            rt.actor_instance = cls(*args, **kwargs)
            rt.actor_id = cspec.actor_id
            rt.send(("actor_ready", cspec.actor_id))
            return cspec
        except BaseException as e:  # noqa: BLE001
            err = TaskError.from_exception(e, f"{cspec.name}.__init__")
            payload, bufs, _ = serialization.serialize_value(err)
            rt.send(("actor_err", cspec.actor_id, payload, bufs))
            return None

    # Main executor loop. Plain workers and sync actors execute inline;
    # threaded actors fan out to a pool; async actors switch to asyncio.
    # Sync actor replies coalesce through the bounded-delay _ReplyBatcher.
    pool: concurrent.futures.ThreadPoolExecutor | None = None
    batcher = _ReplyBatcher(rt)
    while not rt.shutdown.is_set():
        item = rt.task_queue.get()
        if item is None:
            batcher.flush_now()
            break
        if isinstance(item, tuple) and item[0] == "__create_actor__":
            cspec = create_actor(item[1])
            if cspec is None:
                continue
            if cspec.is_async:
                _run_actor_async(rt, cspec.max_concurrency, batcher)
                break
            if cspec.max_concurrency and cspec.max_concurrency > 1:
                pool = concurrent.futures.ThreadPoolExecutor(cspec.max_concurrency)
            continue
        spec: TaskSpec = item
        with rt.steal_lock:
            n_drops = rt.dropped_tasks.get(spec.task_id, 0)
            if n_drops:
                if n_drops == 1:
                    del rt.dropped_tasks[spec.task_id]
                else:
                    rt.dropped_tasks[spec.task_id] = n_drops - 1
                dropped = True
            else:
                # Atomic with the drop check: once marked begun, a
                # drop_task will be refused (ack False) instead of racing
                # this execution.
                dropped = False
                if len(rt.begun_tasks) > 4096:
                    rt.begun_tasks.pop()
                rt.begun_tasks.add(spec.task_id)
        if dropped:
            continue
        if spec.task_id in rt.cancelled_tasks:
            rt.cancelled_tasks.discard(spec.task_id)
            _reply_cancelled(rt, spec)
            continue
        chaos.kill("worker.exec.kill")  # SIGKILL with the task accepted
        # but un-replied: the head's worker-death replay owns recovery
        if getattr(spec, "num_tpus", 0):
            _ensure_accelerator_platform(spec.num_tpus)
        if spec.actor_id is not None:
            fn = _actor_method(rt, spec)
        else:
            fn = rt.functions.get(spec.fn_id)
            if fn is None:
                err = TaskError.from_exception(
                    RuntimeError(f"function {spec.fn_id.hex()} not registered"),
                    spec.describe())
                _reply_result(rt, spec, "err", err)
                continue
        if getattr(spec, "streaming", False):
            _execute_streaming(rt, spec, fn)
            continue
        if pool is not None and spec.actor_id is not None:
            def run(sp=spec, f=fn):
                status, result = _execute(rt, sp, f)
                _reply_result(rt, sp, status, result)
            pool.submit(run)
        else:
            status, result = _execute(rt, spec, fn)
            # Plain tasks reply directly: the scheduler leases one task at
            # a time and waits for the done to re-idle this worker.
            _reply_result(rt, spec, status, result,
                          batcher=batcher if spec.actor_id is not None
                          else None)

    batcher.flush_now()
    rt.flush_task_events(force=True)  # last events/metrics out the door
    rt.flush_sends()  # the sender thread must drain before os._exit
    if rt._store is not None:
        # Graceful exits return the write-reservation tail; a SIGKILLed
        # worker strands at most one extent until the arena is unlinked.
        rt._store.close()
    os._exit(0)


if __name__ == "__main__":
    main()
