"""Per-node agent daemon: the raylet-equivalent for multi-node clusters.

Runs one per node (parity: `src/ray/raylet/main.cc:136`). Owns the node's
shared-memory object store (parity: plasma runs inside the raylet,
`store_runner.h:29`) and its worker pool (parity: `worker_pool.h:228` —
zygote prestart + on-demand growth), registers with the head over TCP
(parity: raylet registering with the GCS), relays worker<->head frames, and
serves cross-node object pulls over a peer port (parity: the object-manager
push/pull plane, `object_manager.h:119`).

Scheduling stays centralized at the head — the agent is deliberately a thin
data/lifecycle plane. On one machine the test harness
(`ray_tpu.cluster_utils.Cluster`) starts several agents to emulate a
multi-node cluster, mirroring the reference's `cluster_utils.Cluster:135`.
"""

from __future__ import annotations

import argparse
import json
import os
import selectors
import signal
import socket
import tempfile
import threading
import time
import traceback
import uuid

from ray_tpu.core import objxfer
from ray_tpu.core.config import Config, set_config
from ray_tpu.core.ids import ObjectID, WorkerID
from ray_tpu.core.object_store import SharedMemoryStore, default_store_size
from ray_tpu.core.runtime import (
    _Zygote,
    _reap_stale_stores,
    apply_pip_env,
    build_worker_env,
    spawn_worker_process,
)
from ray_tpu.core.transport import FrameBuffer, send_msg


class _AgentWorker:
    def __init__(self, worker_id: WorkerID, sock, proc):
        self.worker_id = worker_id
        self.sock = sock
        self.send_lock = threading.Lock()
        self.proc = proc
        self.buffer = FrameBuffer()


class NodeAgent:
    def __init__(self, head_addr: str, num_cpus=None, num_tpus=0,
                 resources=None, object_store_memory=None,
                 node_ip="127.0.0.1", node_id: bytes | None = None):
        cfg = Config.from_env()
        set_config(cfg)
        self.config = cfg
        self.node_id = node_id or os.urandom(8)
        self.session_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"node_{uuid.uuid4().hex[:12]}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)

        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else self.session_dir
        _reap_stale_stores(shm_dir)
        self.store_path = os.path.join(
            shm_dir, f"ray_tpu_{os.getpid()}_{uuid.uuid4().hex[:12]}")
        self.store = SharedMemoryStore(
            self.store_path, size=object_store_memory or default_store_size(cfg),
            num_slots=cfg.object_store_hash_slots, create=True)

        self.resources = {
            "CPU": float(num_cpus if num_cpus is not None
                         else (os.cpu_count() or 1)),
            "TPU": float(num_tpus or 0),
        }
        for k, v in (resources or {}).items():
            self.resources[k] = float(v)

        # Peer port: serves whole-object pulls to sibling agents and the
        # head — native C++ threads reading the arena directly (Python
        # fallback speaks the same binary protocol).
        self.peer_server = objxfer.start_peer_server(self.store, node_ip)
        self.peer_addr = (node_ip, self.peer_server.port)

        host, port = head_addr.rsplit(":", 1)
        self.head_host, self.head_port = host, int(port)
        self.head_sock = socket.create_connection((host, int(port)))
        self.head_lock = threading.Lock()
        self.head_buffer = FrameBuffer()
        self._reconnecting = False
        self._reconnect_lock = threading.Lock()
        self.worker_actor: dict[bytes, bytes] = {}  # wid -> hosted actor id
        self.worker_env_key: dict[bytes, str] = {}  # wid -> pip env pool
        self.workers: dict[bytes, _AgentWorker] = {}
        self._register()
        self.pool_size = max(1, cfg.num_workers or int(self.resources["CPU"]))
        self.max_workers = self.pool_size * 2 + 8
        self._shutdown = False
        self._selector = selectors.DefaultSelector()
        self._sel_lock = threading.Lock()
        self._selector.register(self.head_sock, selectors.EVENT_READ,
                                ("head", None))
        self.zygote = _Zygote(self.session_dir, self.store_path,
                              self._worker_env())

        threading.Thread(target=self._prestart, daemon=True).start()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    # ---------------- workers ----------------

    def _worker_env(self) -> dict:
        return build_worker_env(self.config, self.node_id.hex())

    def _prestart(self):
        for _ in range(self.pool_size):
            try:
                self._spawn_worker()
            except Exception:  # noqa: BLE001 — keep filling the pool
                traceback.print_exc()

    def _spawn_worker(self, pip: list | None = None):
        if self._shutdown:
            return
        worker_id = WorkerID.from_random()
        env, zygote, env_key = apply_pip_env(
            self._worker_env(), self.zygote, pip)
        parent, proc = spawn_worker_process(
            worker_id, self.store_path, env, zygote,
            self.session_dir)
        w = _AgentWorker(worker_id, parent, proc)
        if env_key:
            self.worker_env_key[worker_id.binary()] = env_key
        self.workers[worker_id.binary()] = w
        with self._sel_lock:
            self._selector.register(parent, selectors.EVENT_READ,
                                    ("worker", w))

    def _on_worker_eof(self, w: _AgentWorker):
        with self._sel_lock:
            try:
                self._selector.unregister(w.sock)
            except (KeyError, ValueError):
                pass
        try:
            w.sock.close()
        except OSError:
            pass
        if self.workers.pop(w.worker_id.binary(), None) is None:
            return
        self.worker_actor.pop(w.worker_id.binary(), None)
        self.worker_env_key.pop(w.worker_id.binary(), None)
        self._send_head(("worker_death", w.worker_id.binary()))
        if not self._shutdown and len(self.workers) < self.pool_size:
            threading.Thread(target=self._spawn_worker, daemon=True).start()

    # ---------------- head link ----------------

    def _register(self):
        """(Re-)introduce this node to the head, with a worker inventory so
        a restarted head can adopt surviving workers/actors (parity:
        raylets resyncing with a restarted GCS)."""
        inventory = [(wid, self.worker_actor.get(wid),
                      self.worker_env_key.get(wid))
                     for wid in list(self.workers)]
        send_msg(self.head_sock,
                 ("register_node", self.node_id, self.resources,
                  self.peer_addr, socket.gethostname(), os.getpid(),
                  inventory),
                 self.head_lock)

    def _send_head(self, msg):
        try:
            send_msg(self.head_sock, msg, self.head_lock)
        except OSError:
            self._reconnect_or_die()

    def _reconnect_or_die(self):
        """The head link dropped: retry for the configured grace (a head
        restart with persistence comes back on the same port), else die as
        before. Frames sent during the outage are dropped — workers' RPC
        futures time out and retry."""
        with self._reconnect_lock:
            if self._shutdown or self._reconnecting:
                return
            self._reconnecting = True
        try:
            with self._sel_lock:
                try:
                    self._selector.unregister(self.head_sock)
                except (KeyError, ValueError):
                    pass
            try:
                self.head_sock.close()
            except OSError:
                pass
            deadline = time.monotonic() + self.config.agent_reconnect_grace_s
            while not self._shutdown and time.monotonic() < deadline:
                try:
                    sock = socket.create_connection(
                        (self.head_host, self.head_port), timeout=2.0)
                except OSError:
                    time.sleep(0.5)
                    continue
                self.head_sock = sock
                self.head_buffer = FrameBuffer()
                try:
                    self._register()
                except OSError:
                    # Raced another drop: clean THIS socket fully before
                    # retrying, or its later EOF would tear down the next
                    # (healthy) link.
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                with self._sel_lock:
                    self._selector.register(sock, selectors.EVENT_READ,
                                            ("head", None))
                return
            self._die()
        finally:
            with self._reconnect_lock:
                self._reconnecting = False

    def _heartbeat_loop(self):
        period = self.config.health_check_period_ms / 1000.0
        while not self._shutdown:
            time.sleep(period)
            self._send_head(("heartbeat", self.node_id))

    def _handle_head_msg(self, msg):
        op = msg[0]
        if op == "to_worker":
            _, wid, inner = msg
            w = self.workers.get(wid)
            if w is not None:
                try:
                    send_msg(w.sock, inner, w.send_lock)
                except OSError:
                    pass
        elif op == "spawn_worker":
            pip = msg[1] if len(msg) > 1 else None
            if len(self.workers) < self.max_workers:
                threading.Thread(target=self._spawn_worker,
                                 kwargs={"pip": pip}, daemon=True).start()
        elif op == "kill_worker":
            w = self.workers.get(msg[1])
            if w is not None and w.proc is not None:
                try:
                    w.proc.kill()
                except ProcessLookupError:
                    pass
        elif op == "fetch":
            _, oid, src_addr, attempt = msg
            threading.Thread(target=self._fetch_object,
                             args=(oid, tuple(src_addr), attempt),
                             daemon=True).start()
        elif op == "free_obj":
            try:
                self.store.delete(ObjectID(msg[1]))
            except Exception:  # noqa: BLE001
                pass
        elif op == "node_ack":
            pass
        elif op == "shutdown_node":
            self._die()

    # ---------------- object plane ----------------

    def _fetch_object(self, oid: bytes, src_addr, attempt=None):
        """Pull `oid` from a peer's store into ours (parity: pull_manager)."""
        ok = False
        try:
            ok = objxfer.fetch_from_peer(self.store, src_addr, oid)
        except Exception:  # noqa: BLE001
            traceback.print_exc()
        self._send_head(("fetched", oid, ok, attempt))

    # ---------------- main loop ----------------

    def run(self):
        while not self._shutdown:
            with self._sel_lock:
                try:
                    events = self._selector.select(timeout=0.05)
                except OSError:
                    continue
            for key, _mask in events:
                kind, w = key.data
                try:
                    data = key.fileobj.recv(1 << 20)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    data = b""
                if kind == "head":
                    if not data:
                        self._reconnect_or_die()
                        if self._shutdown:
                            return
                        continue
                    self.head_buffer.feed(data)
                    for msg in self.head_buffer.frames():
                        try:
                            self._handle_head_msg(msg)
                        except Exception:
                            traceback.print_exc()
                else:  # worker
                    if not data:
                        self._on_worker_eof(w)
                        continue
                    w.buffer.feed(data)
                    for msg in w.buffer.frames():
                        if msg[0] == "actor_ready":
                            # Track which worker hosts which actor — the
                            # re-registration inventory needs it for
                            # head-restart adoption.
                            self.worker_actor[w.worker_id.binary()] = msg[1]
                        self._send_head(
                            ("wmsg", w.worker_id.binary(), msg))

    def _die(self):
        if self._shutdown:
            return
        self._shutdown = True
        for w in list(self.workers.values()):
            if w.proc is not None:
                try:
                    w.proc.kill()
                except ProcessLookupError:
                    pass
        if self.zygote is not None:
            self.zygote.close()
        try:
            # Peer server first: native threads read the arena mmap raw.
            self.peer_server.stop()
            self.store.close()
            self.store.unlink()
        except Exception:  # noqa: BLE001
            pass
        os._exit(0)


def main(argv=None):
    p = argparse.ArgumentParser(description="ray_tpu node agent (raylet)")
    p.add_argument("--head", required=True, help="head host:port")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=0)
    p.add_argument("--resources", type=str, default="{}",
                   help="extra resources as JSON")
    p.add_argument("--object-store-memory", type=int, default=0)
    p.add_argument("--node-ip", type=str, default="127.0.0.1")
    p.add_argument("--node-id", type=str, default="",
                   help="hex node id (assigned by the launcher; random if "
                        "empty)")
    args = p.parse_args(argv)
    agent = NodeAgent(
        args.head, num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources=json.loads(args.resources),
        object_store_memory=args.object_store_memory or None,
        node_ip=args.node_ip,
        node_id=bytes.fromhex(args.node_id) if args.node_id else None)

    def _sig(_s, _f):
        agent._die()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    agent.run()


if __name__ == "__main__":
    main()
