"""Per-node agent daemon: the raylet-equivalent for multi-node clusters.

Runs one per node (parity: `src/ray/raylet/main.cc:136`). Owns the node's
shared-memory object store (parity: plasma runs inside the raylet,
`store_runner.h:29`) and its worker pool (parity: `worker_pool.h:228` —
zygote prestart + on-demand growth), registers with the head over TCP
(parity: raylet registering with the GCS), relays worker<->head frames, and
serves cross-node object pulls over a peer port (parity: the object-manager
push/pull plane, `object_manager.h:119`).

Scheduling stays centralized at the head — the agent is deliberately a thin
data/lifecycle plane. On one machine the test harness
(`ray_tpu.cluster_utils.Cluster`) starts several agents to emulate a
multi-node cluster, mirroring the reference's `cluster_utils.Cluster:135`.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import pickle
import selectors
import signal
import socket
import threading
import time
import traceback
import uuid

from ray_tpu.core import chaos, objxfer, task_events
from ray_tpu.core.head_shards import SHARD_MAP_KEY, bucket_of
from ray_tpu.core.config import Config, set_config
from ray_tpu.core.retry import Backoff
from ray_tpu.core.ids import ObjectID, WorkerID
from ray_tpu.core.object_store import SharedMemoryStore, default_store_size
from ray_tpu.core.order_gate import OrderGate
from ray_tpu.core.runtime import (
    _Zygote,
    _reap_stale_stores,
    apply_pip_env,
    build_worker_env,
    spawn_worker_process,
)
from ray_tpu.core.transport import (FrameBuffer, enable_nodelay,
                                    encode_payload, send_many, send_msg)


class _AgentWorker:
    def __init__(self, worker_id: WorkerID, sock, proc,
                 language: str = "python"):
        self.worker_id = worker_id
        self.hex_id = worker_id.hex()  # stamped on node_done exec spans
        self.sock = sock
        self.send_lock = threading.Lock()
        self.proc = proc
        self.language = language
        if language == "cpp":
            # Non-Python workers speak protobuf WorkerFrames end to end
            # (core/worker_wire.py) — their channel never carries pickle.
            from ray_tpu.core.worker_wire import WorkerFrameBuffer
            self.buffer = WorkerFrameBuffer()
        else:
            self.buffer = FrameBuffer()
        # Lease frames stage here (appended under the agent's lease lock,
        # so reg_fn/exec ordering is the lock order) and drain under
        # flush_lock: two _pump_leases threads sending directly could
        # otherwise reorder a bare exec ahead of the reg_fn that its
        # fn_id registration rode in on.
        self.outbox: list = []
        self.flush_lock = threading.Lock()
        # UDS exec listener (worker peer plane) sniffed off the ready
        # frame: set => the WORKER owns the order gate for its actor, so
        # this agent delivers exec frames ungated and forwards seq_skips.
        self.peer_path: str | None = None
        # Native select-round bookkeeping (cpp/agent_core.cc): the pump
        # tag this worker's fd carries and its ledger index. None when
        # the agent runs the pure-Python loop.
        self.tag: int | None = None
        self.widx: int | None = None
        self.nat_fd: int | None = None


class _PeerConn:
    """One agent<->agent control channel (its own reader thread; frames
    are ordered per channel, which is what gives per-caller actor-call
    ordering on the direct path)."""

    def __init__(self, agent: "NodeAgent", sock, nid: bytes | None):
        self.agent = agent
        self.sock = sock
        self.nid = nid
        self.send_lock = threading.Lock()
        self.alive = True
        self.inflight: dict[bytes, tuple] = {}  # task_id -> (wid, spec)

    def send(self, msg):
        send_msg(self.sock, msg, self.send_lock)

    def close(self):
        """Retire the channel: shutdown (not close) so the blocked reader
        thread wakes with EOF and owns the actual close + eof cleanup —
        closing the fd out from under a live recv risks it landing on a
        reused descriptor."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def start(self):
        threading.Thread(target=self._read_loop, daemon=True,
                         name="rtpu-peer").start()

    def _read_loop(self):
        fb = FrameBuffer()
        while True:
            try:
                data = self.sock.recv(1 << 20)
            except OSError:
                data = b""
            if not data:
                self.alive = False
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.agent._on_peer_eof(self)
                return
            fb.feed(data)
            for msg in fb.frames():
                try:
                    self.agent._on_peer_frame(self, msg)
                except Exception:  # noqa: BLE001 — keep the channel alive
                    traceback.print_exc()


class NodeAgent:
    def __init__(self, head_addr: str, num_cpus=None, num_tpus=0,
                 resources=None, object_store_memory=None,
                 node_ip="127.0.0.1", node_id: bytes | None = None):
        cfg = Config.from_env()
        set_config(cfg)
        self.config = cfg
        self.node_id = node_id or os.urandom(8)
        # Task-event ring for THIS agent's emissions (spill hops, local
        # worker choice); drained onto the select-round head batch and
        # the heartbeat — frames this agent already sends.
        task_events.configure(cfg)
        self._tev = task_events.ring()
        self._tev_last_flush = 0.0
        if cfg.gc_freeze_init:
            import gc
            gc.freeze()  # same rationale as the head: full collections
            # must not re-scan the boot-time import universe
        from ray_tpu.core.session import new_session_dir
        self.session_dir = new_session_dir("node")

        shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else self.session_dir
        _reap_stale_stores(shm_dir)
        self.store_path = os.path.join(
            shm_dir, f"ray_tpu_{os.getpid()}_{uuid.uuid4().hex[:12]}")
        self.store = SharedMemoryStore(
            self.store_path, size=object_store_memory or default_store_size(cfg),
            num_slots=cfg.object_store_hash_slots, create=True,
            num_shards=cfg.object_store_shards)
        from ray_tpu.core.object_store import configure_store
        configure_store(self.store, cfg)
        # Serializes the heartbeat loop's orphan-reservation sweep against
        # _die()'s arena unmap (a sweep over freed shm segfaults).
        self._store_close_lock = threading.Lock()

        self.resources = {
            "CPU": float(num_cpus if num_cpus is not None
                         else (os.cpu_count() or 1)),
            "TPU": float(num_tpus or 0),
        }
        for k, v in (resources or {}).items():
            self.resources[k] = float(v)
        # Cross-language worker capacity: nodes that can spawn the C++
        # worker binary advertise the CPP capability resource; the head's
        # normal resource matching then routes language="cpp" tasks here
        # (each such task reserves CPP: 1).
        self.cpp_enabled = bool(cfg.cpp_worker_enable)
        self.cpp_pool = int(cfg.cpp_worker_pool
                            or max(1, int(self.resources["CPU"])))
        if self.cpp_enabled and "CPP" not in self.resources:
            self.resources["CPP"] = float(self.cpp_pool)
        # language="cpp" lease backlog (kept apart from _lease_q: cpp
        # leases dispatch only onto cpp workers and never spill — the
        # spill plane would need the peer to advertise CPP). Guarded by
        # _lease_lock like the python queue.
        self._cpp_q: collections.deque = collections.deque()
        self._cpp_spawns_pending = 0
        self._cpp_binary: str | None = None
        self._cpp_build_lock = threading.Lock()

        # Peer port: serves whole-object pulls to sibling agents and the
        # head — native C++ threads reading the arena directly (Python
        # fallback speaks the same binary protocol).
        self.peer_server = objxfer.start_peer_server(self.store, node_ip)
        self.peer_addr = (node_ip, self.peer_server.port)

        # Peer CONTROL listener: direct agent<->agent actor-call frames
        # bypass the head relay (parity: worker-to-worker gRPC,
        # actor_task_submitter.h:78 — hoisted to one channel per agent
        # pair; per-caller ordering rides the single TCP stream).
        self.ctrl_srv = socket.socket()
        self.ctrl_srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.ctrl_srv.bind((node_ip, 0))
        self.ctrl_srv.listen(64)
        self.ctrl_addr = (node_ip, self.ctrl_srv.getsockname()[1])
        self._peer_conns: dict[bytes, "_PeerConn"] = {}   # nid -> conn
        self._dial_pending: dict[bytes, list] = {}        # nid -> queued
        self._peer_lock = threading.Lock()
        # Executor-side routing of direct calls:
        # task_id -> (origin conn | None-if-local, origin_wid, spec,
        #             target_wid) — spec/target retained so a target-worker
        # death can fail the call back instead of orphaning the caller.
        self._routed: dict[bytes, tuple] = {}
        # Per-(caller worker, actor) in-order delivery gate: direct-path
        # frames (peer channel) and head-relayed frames race, so execs
        # carrying spec.caller_seq are buffered here until their turn
        # (shared with head-node workers — core/order_gate.py).
        self._order_gate = OrderGate()
        self._agent_req_lock = threading.Lock()
        self._agent_req_seq = 0
        self._agent_req_futs: dict[int, "object"] = {}
        # --- node-lease dispatch (the raylet local_task_manager role,
        # parity: local_task_manager.h:65) --- the head leases dep-free
        # plain tasks to the NODE; this agent owns worker choice, local
        # queueing, on-demand spawn, and batched completion reports, so
        # per-task completion work never touches the head's scheduling
        # lock (HEADPROF_r04's named ceiling).
        self._lease_lock = threading.Lock()
        self._lease_q: collections.deque = collections.deque()
        self._lease_inflight: dict[bytes, tuple] = {}  # tid -> (wid, spec)
        # (task_id, lease_seq) pairs this agent has accepted (bounded,
        # guarded by _lease_lock): the head's lease re-drive (a node_exec
        # resent because the grant frame was lost on the wire) dedups
        # here, so a re-drive racing the original delivery can never
        # double-queue an execution. A legitimate re-grant after
        # lease_return carries a bumped lease_seq and passes.
        self._lease_seen: "collections.OrderedDict[tuple, bool]" = (
            collections.OrderedDict())
        self._worker_load: dict[bytes, int] = {}       # outstanding execs
        self._worker_fns: dict[bytes, set] = {}        # wid -> fn_ids sent
        self._fn_blobs: dict[bytes, bytes] = {}        # agent fn cache
        self._spawns_pending = 0   # in-flight spawns (cap accounting)
        self._hb_version = 0
        # --- cluster-view cache + lease spillback (the syncer's downlink
        # half, parity: ray_syncer.h:20 broadcast + the raylet's scheduler
        # spillback, cluster_task_manager.cc:187) --- the head broadcasts
        # the versioned cluster view as per-agent deltas (cluster_view
        # frames); this agent uses it to forward surplus un-started leases
        # DIRECTLY to an under-loaded peer agent — one agent->agent hop,
        # zero per-task head involvement (the head learns via an async
        # lease_spilled notice). Guarded by _lease_lock.
        self._cluster_view: dict[bytes, dict] = {}  # nid -> view entry
        self._cview_version = 0
        # --- head-shard map (core/head_shards.py): rides the cluster-
        # view broadcast under a reserved pseudo-key. When present, this
        # agent ships task_events straight to the owning shard (lazily
        # dialed, cached channels); any shard failure falls back to the
        # head's task_events frame — never a lost event.
        self._shard_map: dict | None = None
        self._shard_socks: dict[int, tuple] = {}  # sid -> (sock, lock)
        self._shard_lock = threading.Lock()
        self._peer_fns: dict[bytes, set] = {}  # fn blobs sent per peer
        self._last_spill = 0.0
        # Event-driven uplink deltas: last (idle, backlog) pair pushed to
        # the head outside the heartbeat cadence, plus a rate limiter.
        self._last_pushed_view: tuple = ()
        self._last_view_push = 0.0

        host, port = head_addr.rsplit(":", 1)
        self.head_host, self.head_port = host, int(port)
        self.head_sock = socket.create_connection((host, int(port)))
        enable_nodelay(self.head_sock)
        self.head_lock = threading.Lock()
        self.head_buffer = FrameBuffer()
        self._reconnecting = False
        self._reconnect_lock = threading.Lock()
        self.worker_actor: dict[bytes, bytes] = {}  # wid -> hosted actor id
        self.worker_env_key: dict[bytes, str] = {}  # wid -> pip env pool
        self.workers: dict[bytes, _AgentWorker] = {}
        self._register()
        self.pool_size = max(1, cfg.num_workers or int(self.resources["CPU"]))
        self.max_workers = self.pool_size * 2 + 8
        self._shutdown = False
        self._selector = selectors.DefaultSelector()
        self._sel_lock = threading.Lock()
        self._selector.register(self.head_sock, selectors.EVENT_READ,
                                ("head", None))
        self.zygote = _Zygote(self.session_dir, self.store_path,
                              self._worker_env())

        # --- native select-round core (cpp/agent_core.cc) --- the frame
        # pump, lease queue/dedup/inflight ledger and hot-frame builds run
        # in C++ when `native_sched` is on and the module builds; any
        # failure degrades to the pure-Python loop below, never to an
        # error. Chaos-armed processes keep the native LEDGER but route
        # every send through send_msg so the seeded transport sites fire
        # exactly as scheduled (storm equivalence, not just speed).
        self._nat = None
        self._tag_worker: dict[int, _AgentWorker] = {}
        self._widx_worker: dict[int, _AgentWorker] = {}
        self._dispatch_plan_lock = threading.Lock()
        if cfg.native_sched:
            try:
                from ray_tpu._native.agent_core import HEAD_TAG, AgentCore
                nat = AgentCore()
                nat.add_fd(self.head_sock.fileno(), HEAD_TAG)
                self._nat = nat
            except Exception:  # noqa: BLE001 — pure-Python fallback
                traceback.print_exc()
                self._nat = None

        threading.Thread(target=self._prestart, daemon=True).start()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        threading.Thread(target=self._ctrl_accept_loop, daemon=True,
                         name="rtpu-peer-accept").start()

    # ---------------- workers ----------------

    def _worker_env(self) -> dict:
        return build_worker_env(self.config, self.node_id.hex())

    def _prestart(self):
        for _ in range(self.pool_size):
            try:
                self._spawn_worker()
            except Exception:  # noqa: BLE001 — keep filling the pool
                traceback.print_exc()

    def _spawn_worker(self, pip: list | None = None):
        if self._shutdown:
            return
        worker_id = WorkerID.from_random()
        env, zygote, env_key = apply_pip_env(
            self._worker_env(), self.zygote, pip)
        parent, proc = spawn_worker_process(
            worker_id, self.store_path, env, zygote,
            self.session_dir)
        w = _AgentWorker(worker_id, parent, proc)
        if env_key:
            self.worker_env_key[worker_id.binary()] = env_key
        self.workers[worker_id.binary()] = w
        with self._sel_lock:
            self._selector.register(parent, selectors.EVENT_READ,
                                    ("worker", w))
        self._nat_track_worker(w, eligible=not env_key)

    def _nat_track_worker(self, w: _AgentWorker, eligible: bool):
        """Register a fresh worker with the native pump + ledger (no-op in
        pure-Python mode). cpp workers ride the pump in raw mode (their
        protobuf WorkerFrame stream keeps its own framing)."""
        nat = self._nat
        if nat is None:
            return
        tag = nat.alloc_tag()
        w.tag = tag
        w.nat_fd = w.sock.fileno()
        w.widx = nat.worker_add(tag, w.nat_fd, w.worker_id.binary(),
                                w.hex_id,
                                eligible and w.language == "python")
        self._tag_worker[tag] = w
        self._widx_worker[w.widx] = w
        nat.add_fd(w.nat_fd, tag, raw=(w.language == "cpp"))

    def _on_worker_eof(self, w: _AgentWorker):
        with self._sel_lock:
            try:
                self._selector.unregister(w.sock)
            except (KeyError, ValueError):
                pass
        nat_failed = []
        if self._nat is not None and w.widx is not None:
            if w.nat_fd is not None:
                self._nat.del_fd(w.nat_fd)
            nat_failed = self._nat.fail_worker(w.widx)
            self._nat.worker_remove(w.widx)
            self._tag_worker.pop(w.tag, None)
            self._widx_worker.pop(w.widx, None)
        try:
            w.sock.close()
        except OSError:
            pass
        if self.workers.pop(w.worker_id.binary(), None) is None:
            return
        wid = w.worker_id.binary()
        self.worker_actor.pop(wid, None)
        self.worker_env_key.pop(wid, None)
        self._order_gate.drop_for_target(wid)
        # Leased tasks in flight on the dead worker: the HEAD runs the
        # retry policy (it owns retries_left); report and forget. Native
        # mode drains the C++ inflight table (raw spec bytes, unpickled
        # only here on the death path).
        lease_failed = [pickle.loads(spec) for _t, _f, _s, spec in nat_failed]
        with self._lease_lock:
            self._worker_load.pop(wid, None)
            self._worker_fns.pop(wid, None)
            for tid, (lw, spec) in list(self._lease_inflight.items()):
                if lw == wid:
                    del self._lease_inflight[tid]
                    lease_failed.append(spec)
        if lease_failed:
            self._send_head(("lease_fail", lease_failed))
        # Direct calls delivered to the dead worker must fail back to their
        # origin — the head never saw them, so no one else can.
        for task_id, route in list(self._routed.items()):
            conn, origin_wid, spec, target_wid = route
            if target_wid != wid:
                continue
            self._routed.pop(task_id, None)
            if conn is None:
                self._direct_fallback(origin_wid, spec, maybe_executed=True)
            else:
                try:
                    conn.send(("peer_fail", origin_wid, spec, True))
                except OSError:
                    pass
        self._send_head(("worker_death", wid))
        if w.language == "cpp":
            # cpp workers are on-demand: a death only respawns if backlog
            # still exists (the pump spawns against _cpp_q depth).
            self._pump_cpp_leases()
            return
        n_python = sum(1 for aw in self.workers.values()
                       if aw.language == "python")
        if not self._shutdown and n_python < self.pool_size:
            threading.Thread(target=self._spawn_worker, daemon=True).start()

    # ---------------- head link ----------------

    def _register(self):
        """(Re-)introduce this node to the head, with a worker inventory so
        a restarted head can adopt surviving workers/actors (parity:
        raylets resyncing with a restarted GCS). Each entry carries the
        worker's language (WorkerInventory.language) — a restarted head
        must not adopt a C++ worker into its Python pool."""
        inventory = [(wid, self.worker_actor.get(wid),
                      self.worker_env_key.get(wid), w.language)
                     for wid, w in list(self.workers.items())]
        # Object inventory: the arena outlives a head restart, so the new
        # head rebuilds its object directory from what each node still
        # holds — this is what lets journal-replayed tasks with object
        # deps resolve instead of hanging (parity: location resync via
        # ray_syncer after GCS reload, gcs_init_data.h).
        try:
            objects = self.store.list_object_ids()
        except Exception:  # noqa: BLE001 — inventory is best effort
            objects = []
        send_msg(self.head_sock,
                 ("register_node", self.node_id, self.resources,
                  self.peer_addr, socket.gethostname(), os.getpid(),
                  inventory, self.ctrl_addr, objects),
                 self.head_lock)

    def _head_request(self, what, arg, timeout=10.0):
        """Synchronous agent->head query (peer ctrl-address discovery)."""
        import concurrent.futures
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._agent_req_lock:
            self._agent_req_seq += 1
            req_id = self._agent_req_seq
            self._agent_req_futs[req_id] = fut
        self._send_head(("agent_req", req_id, what, arg))
        try:
            return fut.result(timeout)
        finally:
            self._agent_req_futs.pop(req_id, None)

    def _send_head(self, msg):
        try:
            send_msg(self.head_sock, msg, self.head_lock)
        except OSError:
            self._reconnect_or_die()

    def _reconnect_or_die(self):
        """The head link dropped: retry for the configured grace (a head
        restart with persistence comes back on the same port), else die as
        before. Frames sent during the outage are dropped — workers' RPC
        futures time out and retry."""
        with self._reconnect_lock:
            if self._shutdown or self._reconnecting:
                return
            self._reconnecting = True
        try:
            with self._sel_lock:
                try:
                    self._selector.unregister(self.head_sock)
                except (KeyError, ValueError):
                    pass
            if self._nat is not None:
                try:
                    self._nat.del_fd(self.head_sock.fileno())
                except OSError:
                    pass
            try:
                self.head_sock.close()
            except OSError:
                pass
            # Jittered capped-exponential retry against the grace deadline
            # (core/retry.py): N agents re-dialing one restarted head no
            # longer fire in lockstep every 500ms.
            bo = Backoff(deadline_s=self.config.agent_reconnect_grace_s)
            while not self._shutdown and not bo.expired():
                try:
                    sock = socket.create_connection(
                        (self.head_host, self.head_port), timeout=2.0)
                except OSError:
                    if not bo.sleep():
                        break
                    continue
                enable_nodelay(sock)
                # racecheck: ok thread-escape single-reconnector by the
                # _reconnecting latch; concurrent senders reading the
                # stale binding get OSError and re-enter this path, the
                # select loop re-registers on the next round
                self.head_sock = sock
                # racecheck: ok thread-escape same latch as head_sock
                self.head_buffer = FrameBuffer()
                try:
                    self._register()
                except OSError:
                    # Raced another drop: clean THIS socket fully before
                    # retrying, or its later EOF would tear down the next
                    # (healthy) link.
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                with self._sel_lock:
                    self._selector.register(sock, selectors.EVENT_READ,
                                            ("head", None))
                if self._nat is not None:
                    from ray_tpu._native.agent_core import HEAD_TAG
                    self._nat.add_fd(sock.fileno(), HEAD_TAG)
                return
            self._die()
        finally:
            with self._reconnect_lock:
                self._reconnecting = False

    def _heartbeat_loop(self):
        period = self.config.health_check_period_ms / 1000.0
        reclaim_every = self.config.orphan_reclaim_interval_s
        last_reclaim = time.monotonic()
        while not self._shutdown:
            time.sleep(period)
            chaos.kill("agent.sigkill")  # deterministic agent death on
            # the Nth heartbeat tick (role-targeted SIGKILL)
            try:
                self._send_head(("heartbeat", self.node_id,
                                 self._load_view()))
                fr = self._tev_frame(force=True)
                if fr is not None:
                    fr = self._ship_tev_shards(fr)
                if fr is not None:
                    # Cadence floor: surplus ring contents that no worker
                    # drain flushed this period still reach the head.
                    self._send_head(fr)
                self._order_gate.sweep()
                # Periodic spill probe: backlog that formed while no view
                # delta arrived (broadcasts only carry CHANGES) still
                # drains toward idle peers within a heartbeat.
                self._maybe_spill_leases()
                if (reclaim_every > 0
                        and time.monotonic() - last_reclaim >= reclaim_every):
                    # Dead-client reservation sweep: a worker SIGKILLed
                    # between reserve and publish strands its extent (and
                    # inflates rsv_unused) until this repairs it. Under
                    # the close gate — _die() unmaps the arena.
                    last_reclaim = time.monotonic()
                    with self._store_close_lock:
                        if not self._shutdown:
                            self.store.reclaim_orphans()
            except Exception:  # noqa: BLE001 — a dead heartbeat thread
                traceback.print_exc()  # would get this node declared dead

    def _load_view(self) -> dict:
        """Versioned local-load delta riding heartbeats (the
        ray_syncer.h:20 resource-view role): the head reads idle/backlog
        without ever locking this node's dispatch state. The version
        bump rides _lease_lock WITH the snapshot it stamps: the
        heartbeat loop and the select round's push-delta both call here,
        and an unlocked `+= 1` could mint duplicate versions — the
        head's cursor logic would then discard the NEWER view as stale."""
        nat = self._nat
        if nat is not None:
            # The ledger is native: idle/backlog/inflight read straight
            # from the C++ tables (cpp leases stay on the Python dicts).
            with self._lease_lock:
                self._hb_version += 1
                return {"v": self._hb_version, "idle": nat.idle(),
                        "backlog": int(nat.backlog()),
                        "inflight": (int(nat.inflight())
                                     + len(self._lease_inflight))}
        with self._lease_lock:
            self._hb_version += 1
            idle = sum(1 for wid, w in list(self.workers.items())
                       if w.language == "python"
                       and not self._worker_load.get(wid)
                       and wid not in self.worker_actor
                       and not self.worker_env_key.get(wid))
            return {"v": self._hb_version, "idle": idle,
                    "backlog": len(self._lease_q),
                    "inflight": len(self._lease_inflight)}

    def _maybe_push_load_delta(self):
        """Event-driven uplink delta (the syncer push-on-change): when
        this agent's (idle, backlog) pair materially changes, report it
        immediately instead of waiting out the heartbeat period — peers
        then see idle capacity within a broadcast tick and can spill
        toward it while their backlog still exists. Rate-limited; the
        periodic heartbeat remains the liveness floor."""
        if not self.config.lease_spillback:
            return
        now = time.monotonic()
        if now - self._last_view_push < 0.05:
            return
        view = self._load_view()
        key = (view["idle"], view["backlog"])
        if key == self._last_pushed_view:
            return
        self._last_view_push = now
        self._last_pushed_view = key
        self._send_head(("heartbeat", self.node_id, view))

    def _to_worker(self, wid: bytes, inner):
        w = self.workers.get(wid)
        if w is None:
            return
        # Track head-assigned work per worker so lease dispatch avoids
        # busy workers (decremented by the done sniff in run()).
        n_execs = (1 if inner[0] == "exec"
                   else sum(1 for f in inner[1] if f[0] == "exec")
                   if inner[0] == "batch" else 0)
        if n_execs:
            if self._nat is not None and w.widx is not None:
                self._nat.load_add(w.widx, n_execs)
            else:
                with self._lease_lock:
                    self._worker_load[wid] = (
                        self._worker_load.get(wid, 0) + n_execs)
        if (inner[0] == "exec"
                and getattr(inner[1], "caller_seq", None) is not None
                and w.peer_path is None):
            # Head-relayed actor call from a caller that also uses
            # the direct path: hold for per-caller order. A drop
            # (worker death while buffered) needs no handler — the
            # head replays its inflight specs on worker_death.
            # peer_path workers gate THEMSELVES (their UDS peer frames
            # never pass through this agent, so the worker's gate is the
            # only place both transports converge) — deliver ungated.
            def deliver(w=w, inner=inner):
                try:
                    send_msg(w.sock, inner, w.send_lock)
                except OSError:
                    pass

            self._exec_in_order(inner[1], wid, deliver)
            return
        try:
            send_msg(w.sock, inner, w.send_lock)
        except OSError:
            pass

    def _dispatch_depth_locked(self, backlog: int) -> int:
        """Per-worker pipeline depth for this pump pass (caller holds
        _lease_lock): shallow while a spillable peer has room, full
        otherwise — the same heuristic as the Python pump."""
        depth = self.config.max_tasks_in_flight_per_worker
        if (self.config.lease_spillback and backlog
                and backlog > self._spill_keep_locked()
                and self._view_room_locked()):
            depth = min(depth, 2)
        return depth

    def _pump_leases_native(self):
        """Native dispatch: the C++ planner pops leases onto idle workers
        and BUILDS the reg_fn/exec_raw frames; Python performs the sends
        under the existing per-worker locks (and, when chaos is armed,
        re-expands the batch into per-frame send_msg calls so every
        seeded transport site fires exactly as in the Python loop)."""
        nat = self._nat
        with self._lease_lock:
            depth = self._dispatch_depth_locked(int(nat.backlog()))
        armed = chaos._armed is not None
        record = self._tev.enabled
        # Planning and the drec drain stay together under a small lock
        # (dispatch records are per-call scratch); the SENDS happen
        # outside it — ordering across concurrent pumps is already
        # guaranteed by the native per-worker outbox (appends under the
        # ledger mutex, atomic take under the worker's flush lock), the
        # same staged-outbox contract as the Python pump.
        with self._dispatch_plan_lock:
            widxs = nat.dispatch(depth, record)
            recs = nat.dispatch_records() if record else ()
        if record:
            ring = self._tev
            for tid, widx, attempt, name in recs:
                w = self._widx_worker.get(widx)
                ring.emit(tid, attempt, "NODE_DISPATCHED",
                          (name, None),
                          {"worker": w.hex_id if w else ""})
        for widx in widxs:
            w = self._widx_worker.get(widx)
            if w is None:
                continue
            try:
                with w.flush_lock:
                    buf = nat.take_outbox(widx)
                    if not len(buf):
                        continue
                    if not armed:
                        with w.send_lock:
                            w.sock.sendall(buf)
                    else:
                        # Chaos-armed: replay the prebuilt batch one
                        # frame at a time through send_msg — drop/
                        # trunc/delay sites hit individual frames,
                        # matching the Python loop's storm behavior.
                        fb = FrameBuffer()
                        fb.feed(bytes(buf))
                        for m in fb.frames():
                            send_msg(w.sock, m, w.send_lock)
            except OSError:
                pass  # _on_worker_eof lease-fails the inflight entries
        with self._lease_lock:
            spawn = (nat.backlog() > 0
                     and (len(self.workers) + self._spawns_pending)
                     < self.max_workers)
            if spawn:
                self._spawns_pending += 1
        if spawn:
            threading.Thread(target=self._spawn_counted,
                             daemon=True).start()
        self._maybe_spill_leases()

    def _pump_leases(self):
        """Dispatch queued leases onto locally-idle workers; spawn more
        workers (up to the cap) when backlog outruns the pool — worker
        choice and pool growth are NODE decisions here, the
        local_task_manager.h:65 split."""
        if self._nat is not None:
            return self._pump_leases_native()
        per_worker: dict = {}
        spawn = False
        depth = self.config.max_tasks_in_flight_per_worker
        with self._lease_lock:
            if (self.config.lease_spillback and self._lease_q
                    and len(self._lease_q) > self._spill_keep_locked()
                    and self._view_room_locked()):
                # Surplus beyond the local floor while a peer has idle
                # capacity: don't bury it in depth-K worker pipelines
                # (committed frames can't be clawed back) — dispatch
                # shallow and leave the surplus in _lease_q where the
                # spill pass below can forward it peer-to-peer. Under
                # cluster-wide saturation (no idle peers) the full
                # pipeline depth stands, which is where depth was
                # measured to matter.
                depth = min(depth, 2)
            if self._lease_q:
                # Depth-K per worker (parity:
                # max_tasks_in_flight_per_worker lease reuse): a worker
                # executing back-to-back keeps its reply batcher
                # batching and costs this agent one wakeup per BATCH,
                # not per task — depth-1 dispatch measured 10-20x
                # slower at 16 emulated agents (per-task agent
                # round-trips plus un-batched done frames).
                for wid, w in list(self.workers.items()):
                    if not self._lease_q:
                        break
                    if (w.language != "python"
                            or wid in self.worker_actor
                            or self.worker_env_key.get(wid)):
                        continue
                    frames = []
                    while (self._lease_q
                           and self._worker_load.get(wid, 0) < depth):
                        spec = self._lease_q.popleft()
                        self._lease_inflight[spec.task_id] = (wid, spec)
                        if self._tev.enabled:
                            task_events.emit_task(
                                spec, "NODE_DISPATCHED",
                                data={"worker": wid.hex()})
                        self._worker_load[wid] = (
                            self._worker_load.get(wid, 0) + 1)
                        fns = self._worker_fns.setdefault(wid, set())
                        if spec.fn_id and spec.fn_id not in fns:
                            blob = self._fn_blobs.get(spec.fn_id)
                            if blob is not None:
                                frames.append(
                                    ("reg_fn", spec.fn_id, blob))
                            fns.add(spec.fn_id)
                        frames.append(("exec", spec))
                    if frames:
                        # Stage under the lease lock: outbox order == the
                        # order fn registrations were decided in, so a
                        # concurrent pump's bare exec for the same fn_id
                        # can never drain ahead of its reg_fn.
                        w.outbox.extend(frames)
                        per_worker[wid] = w
                spawn = (bool(self._lease_q)
                         and (len(self.workers) + self._spawns_pending)
                         < self.max_workers)
                if spawn:
                    self._spawns_pending += 1
        for w in per_worker.values():
            try:
                with w.flush_lock:
                    with self._lease_lock:
                        frames, w.outbox = w.outbox, []
                    if not frames:
                        continue
                    send_msg(w.sock,
                             frames[0] if len(frames) == 1
                             else ("batch", frames), w.send_lock)
            except OSError:
                pass  # _on_worker_eof lease-fails the inflight entries
        if spawn:
            threading.Thread(target=self._spawn_counted,
                             daemon=True).start()
        self._maybe_spill_leases()

    def _spawn_counted(self):
        """_spawn_worker with the pending-spawn counter released — the
        cap check must see in-flight spawns or a frame burst during one
        spawn's latency window forks far past max_workers."""
        try:
            self._spawn_worker()
        finally:
            with self._lease_lock:
                self._spawns_pending = max(0, self._spawns_pending - 1)

    # ---------------- cross-language (cpp) workers ----------------
    #
    # Parity: the reference's non-Python worker runtimes (a C++ process
    # driven by task_executor.cc over core_worker.proto). The agent spawns
    # cpp/raytpu_worker.cc on demand (compiled through the
    # _native/build.py content-hash g++ cache — no build-system step),
    # hands it one socketpair end plus the node's shm arena path, and
    # dispatches language="cpp" leases as protobuf WorkerFrames
    # (core/worker_wire.py). No frame the cpp worker reads or writes
    # carries pickle; args/returns that go through the arena use the
    # tagged-object layout (object_store.TAGGED_META).

    def _cpp_worker_binary(self) -> str:
        override = self.config.cpp_worker_binary
        if override:
            return override
        with self._cpp_build_lock:
            if self._cpp_binary is None:
                from ray_tpu._native import build as _nb
                from ray_tpu._native.build import build_binary
                native_dir = os.path.dirname(os.path.abspath(_nb.__file__))
                repo = os.path.dirname(os.path.dirname(native_dir))
                # staticcheck: ok blocking-under-lock — the build lock
                # exists to hold concurrent spawns THROUGH one compile
                # (cache stampede); only cpp spawn threads contend it.
                self._cpp_binary = build_binary(
                    "raytpu_worker",
                    sources=(os.path.join(repo, "cpp", "raytpu_worker.cc"),
                             os.path.join(native_dir, "object_store.cpp")),
                    include_dirs=(os.path.join(repo, "cpp"),))
            return self._cpp_binary

    def _spawn_cpp_worker(self):
        """Compile (cached) + exec one C++ worker; registered in the same
        selector/worker table as Python workers so death, kill_worker and
        lease bookkeeping take the existing paths."""
        try:
            binary = self._cpp_worker_binary()
            import socket as socket_mod
            import subprocess
            worker_id = WorkerID.from_random()
            parent, child = socket_mod.socketpair(
                socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
            log_path = os.path.join(self.session_dir, "logs",
                                    f"cppworker-{worker_id.hex()[:8]}.out")
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            # Close the parent's log-fd copy after the spawn (Popen dups
            # it into the child) — an inline open() leaked one fd per
            # cpp-worker spawn for the agent's lifetime.
            logf = open(log_path, "ab")
            try:
                proc = subprocess.Popen(
                    [binary, self.store_path, worker_id.hex(),
                     str(child.fileno())],
                    pass_fds=[child.fileno()], close_fds=True,
                    stdout=logf, stderr=subprocess.STDOUT)
            finally:
                logf.close()
            child.close()
            w = _AgentWorker(worker_id, parent, proc, language="cpp")
            self.workers[worker_id.binary()] = w
            with self._sel_lock:
                self._selector.register(parent, selectors.EVENT_READ,
                                        ("worker", w))
            self._nat_track_worker(w, eligible=False)
        except Exception:  # noqa: BLE001 — a failed spawn must not wedge
            traceback.print_exc()  # the agent; leases fail back via eof
        finally:
            with self._lease_lock:
                self._cpp_spawns_pending = max(
                    0, self._cpp_spawns_pending - 1)
        self._pump_cpp_leases()

    _CPP_DEPTH = 2  # pipelined execs per cpp worker (FIFO channel)

    def _pump_cpp_leases(self):
        """Dispatch queued cpp leases onto cpp workers; spawn more (up to
        cpp_pool) while backlog outruns them. Dep staging: a lease whose
        arena deps are not local yet is handed to a fetch thread and
        re-queued when its objects land."""
        if not self.cpp_enabled or self._shutdown:
            return
        dispatch = []   # (worker, spec)
        stage = []      # (spec, missing oids)
        spawn = False
        with self._lease_lock:
            cpp_workers = [w for w in self.workers.values()
                           if w.language == "cpp"]
            q = self._cpp_q
            held = []
            while q:
                spec = q.popleft()
                missing = [oid for oid in (spec.dependencies or [])
                           if not self.store.contains(ObjectID(oid))]
                if missing:
                    stage.append((spec, missing))
                    continue
                target = None
                for w in cpp_workers:
                    if (self._worker_load.get(w.worker_id.binary(), 0)
                            < self._CPP_DEPTH):
                        target = w
                        break
                if target is None:
                    held.append(spec)
                    break
                wid = target.worker_id.binary()
                self._lease_inflight[spec.task_id] = (wid, spec)
                self._worker_load[wid] = self._worker_load.get(wid, 0) + 1
                if self._tev.enabled:
                    task_events.emit_task(spec, "NODE_DISPATCHED",
                                          data={"worker": wid.hex()})
                dispatch.append((target, spec))
            held.extend(q)
            q.clear()
            q.extend(held)
            spawn = (bool(q)
                     and (len(cpp_workers) + self._cpp_spawns_pending)
                     < self.cpp_pool)
            if spawn:
                self._cpp_spawns_pending += 1
        for w, spec in dispatch:
            try:
                from ray_tpu.core import worker_wire
                frame = worker_wire.encode_exec(spec)
                with w.send_lock:
                    w.sock.sendall(frame)
            except (OSError, ValueError):
                # eof handling lease-fails the inflight entry; an
                # encode refusal (non-neutral payload) fails it now.
                with self._lease_lock:
                    gone = self._lease_inflight.pop(spec.task_id, None)
                    wid = w.worker_id.binary()
                    self._worker_load[wid] = max(
                        0, self._worker_load.get(wid, 0) - 1)
                if gone is not None:
                    self._send_head(("lease_fail", [spec]))
        for spec, missing in stage:
            threading.Thread(target=self._stage_cpp_deps,
                             args=(spec, missing), daemon=True,
                             name="rtpu-cpp-stage").start()
        if spawn:
            threading.Thread(target=self._spawn_cpp_worker,
                             daemon=True, name="rtpu-cpp-spawn").start()

    def _stage_cpp_deps(self, spec, missing: list):
        """Pull a cpp lease's arena deps from their owning nodes before
        dispatch — the cpp worker only reads the LOCAL arena (it has no
        object-plane RPC surface; parity role: the raylet fetching task
        args into plasma before assignment)."""
        ok = True
        for oid in missing:
            if self.store.contains(ObjectID(oid)):
                continue
            try:
                addr = self._head_request("object_src", oid)
                if not addr or not objxfer.fetch_from_peer(
                        self.store, tuple(addr), oid):
                    ok = False
            except Exception:  # noqa: BLE001 — report as a lease failure
                traceback.print_exc()
                ok = False
            if not ok:
                break
        if not ok:
            self._send_head(("lease_fail", [spec]))
            return
        with self._lease_lock:
            self._cpp_q.appendleft(spec)
        self._pump_cpp_leases()

    def _on_cpp_frames(self, w: _AgentWorker, data: bytes):
        """Inbound protobuf frames from one cpp worker (hello/done)."""
        w.buffer.feed(data)
        done_entries = []
        for frame in w.buffer.frames():
            which = frame.WhichOneof("msg")
            if which == "hello":
                self._pump_cpp_leases()  # fresh capacity: feed it
            elif which == "done":
                e = self._on_cpp_done(w, frame.done)
                if e is not None:
                    done_entries.append(e)
        if done_entries:
            self._send_head(("node_done", done_entries))
            self._pump_cpp_leases()

    def _on_cpp_done(self, w: _AgentWorker, done):
        """One cpp task completion -> a node_done entry. Returns are
        arena ids (tagged objects, status 'shm'); errors become TaskError
        payloads HERE, at the language boundary — the worker<->agent
        frame itself stays pickle-free."""
        wid = w.worker_id.binary()
        with self._lease_lock:
            spec = None
            popped = self._lease_inflight.pop(done.task_id, None)
            if popped is not None:
                spec = popped[1]
            self._worker_load[wid] = max(
                0, self._worker_load.get(wid, 0) - 1)
        if spec is None:
            return None  # stale done (lease already failed elsewhere)
        from ray_tpu.core import serialization
        from ray_tpu.core.status import RayTpuError, TaskError
        outs = []
        for o in done.outs:
            if o.status == "shm":
                outs.append((o.object_id, "shm", None, None))
            else:
                msg = (o.error.data.decode("utf-8", "replace")
                       if o.error.data else "cpp task failed")
                err = TaskError.from_exception(
                    RayTpuError(f"cpp:{spec.name}: {msg}"),
                    spec.describe())
                payload, bufs, _ = serialization.serialize_value(err)
                outs.append((o.object_id, "err", payload, bufs))
        tev = (done.attempt, done.exec_start, done.args_ready,
               done.exec_done, done.seal) if done.exec_start else None
        return (done.task_id, outs, tev, w.hex_id)

    # ---------------- lease spillback (agent->agent) ----------------
    #
    # Parity: the raylet's scheduler spillback (cluster_task_manager.cc:
    # 187), decentralized: the head's cluster-view broadcast tells every
    # agent where idle capacity is, and a saturated agent forwards its
    # surplus un-started leases straight to an under-loaded peer over the
    # existing agent<->agent ctrl channel — the head is informed
    # asynchronously (lease_spilled) and never sits on the per-task path.

    def _spill_keep_locked(self) -> int:
        """Un-started backlog this agent keeps local (the spill floor).
        Scaled by the INTENDED pool size, not live workers: burst-spawned
        extras above the pool are transient, and during worker boot a
        near-empty pool must read as 'capacity arriving', not as a floor
        of zero that overspills the whole queue."""
        return (self.config.lease_spill_backlog_per_worker
                * max(1, self.pool_size))

    def _view_room_locked(self) -> bool:
        """Does the cached cluster view show a spillable peer?"""
        for nid, e in self._cluster_view.items():
            if (nid != self.node_id and e.get("state") == "ALIVE"
                    and e.get("ctrl")
                    and int(e.get("idle", 0)) > int(e.get("backlog", 0))):
                return True
        return False

    def _on_node_exec_raw(self, entries):
        """Ingest a raw-spec lease batch outside the native fast loop
        (chaos-armed rounds, walker bails, or native off entirely)."""
        nat = self._nat
        if nat is not None:
            for ent in entries:
                tid, fn, seq, blob, sb = ent[:5]
                attempt = ent[5] if len(ent) > 5 else 0
                name = ent[6] if len(ent) > 6 else None
                if blob is not None and fn is not None:
                    nat.fn_blob(fn, blob)
                if nat.seen(tid, seq or 0):
                    continue
                nat.push(tid, fn, seq or 0, sb, attempt, name)
            self._pump_leases()
            return
        # Pure-Python fallback: decode the specs (off the lease lock),
        # then take the object path.
        decoded = [(ent[1], ent[3], pickle.loads(ent[4]))
                   for ent in entries]
        with self._lease_lock:
            for fn, blob, spec in decoded:
                if blob is not None and fn is not None:
                    self._fn_blobs[fn] = blob
                if self._lease_dup_locked(spec):
                    continue
                self._lease_q.append(spec)
        self._pump_leases()

    def _maybe_spill_leases_native(self):
        """Native-ledger spill pass: selection logic mirrors the Python
        path, but surplus leases are STOLEN from the C++ queue tail and
        their specs unpickled here (the one cold path that needs the
        object form — hops/seq live inside the spec)."""
        cfg = self.config
        nat = self._nat
        now = time.monotonic()
        plan = []
        with self._lease_lock:
            if now - self._last_spill < 0.05:
                return
            surplus = int(nat.backlog()) - self._spill_keep_locked()
            if surplus <= 0:
                return
            peers = []
            for nid, e in self._cluster_view.items():
                if (nid == self.node_id or e.get("state") != "ALIVE"
                        or not e.get("ctrl")):
                    continue
                room = int(e.get("idle", 0)) - int(e.get("backlog", 0))
                if room > 0:
                    peers.append((room, nid, e))
            if not peers:
                return
            self._last_spill = now
            peers.sort(key=lambda t: -t[0])
            total = min(surplus, sum(room for room, _n, _e in peers))
            stolen = nat.steal_tail(total)
        # Spec decode off the lease lock (steal_tail already removed the
        # entries atomically under the native mutex, so nothing else can
        # dispatch them meanwhile).
        cand = [pickle.loads(spec) for _t, _f, _s, spec in stolen]
        with self._lease_lock:
            hop_capped = []
            ci = 0
            for room, nid, e in peers:
                take = min(room, len(cand) - ci)
                specs = []
                while take > 0 and ci < len(cand):
                    spec = cand[ci]
                    ci += 1
                    hops = spec.spill_hops or 0
                    if hops >= cfg.lease_spill_max_hops:
                        hop_capped.append(spec)
                        continue
                    spec.spill_hops = hops + 1
                    if self._tev.enabled:
                        task_events.emit_task(
                            spec, "SPILL_SENT",
                            data={"to": nid.hex(), "hop": spec.spill_hops,
                                  "lease_seq": spec.lease_seq})
                    specs.append(spec)
                    take -= 1
                if not specs:
                    continue
                e["backlog"] = int(e.get("backlog", 0)) + len(specs)
                sent_fns = self._peer_fns.get(nid) or ()
                new_fns = set()
                triples = []
                for spec in specs:
                    blob = None
                    if (spec.fn_id and spec.fn_id not in sent_fns
                            and spec.fn_id not in new_fns):
                        blob = nat.get_fn_blob(spec.fn_id)
                        if blob is not None:
                            new_fns.add(spec.fn_id)
                    triples.append((spec.fn_id, blob, spec))
                plan.append((nid, triples, new_fns))
            # Hop-capped (must run here) and unplaced surplus go back to
            # the queue tail, exactly where the Python path leaves them.
            for spec in hop_capped + cand[ci:]:
                nat.push(spec.task_id, spec.fn_id, spec.lease_seq or 0,
                         encode_payload(spec),
                         task_events.attempt_of(spec), spec.name)
        for nid, triples, new_fns in plan:
            if chaos.site("agent.spill_notice.lose"):
                pass  # injected notice loss (see the Python path)
            else:
                self._send_head(("lease_spilled",
                                 [(t[2].task_id, t[2].lease_seq,
                                   t[2].spill_hops, nid) for t in triples]))
            threading.Thread(target=self._spill_to_peer,
                             args=(nid, triples, new_fns), daemon=True,
                             name="rtpu-spill").start()

    def _maybe_spill_leases(self):
        """Forward surplus un-started leases to under-loaded peers.
        Selection runs under the lease lock; dialing/sending happens on a
        side thread (the agent's main loop must never block on a peer's
        socket). Hop-capped per spec (lease_spill_max_hops) so leases
        cannot ping-pong between loaded agents."""
        cfg = self.config
        if not cfg.lease_spillback or self._shutdown:
            return
        if self._nat is not None:
            return self._maybe_spill_leases_native()
        now = time.monotonic()
        plan = []  # (nid, [(fn_id, blob, spec), ...], new fn_ids)
        with self._lease_lock:
            if now - self._last_spill < 0.05:
                return  # pump storms: one selection per view tick is plenty
            surplus = len(self._lease_q) - self._spill_keep_locked()
            if surplus <= 0:
                return
            peers = []  # (spare capacity, nid, entry) — most room first
            for nid, e in self._cluster_view.items():
                if (nid == self.node_id or e.get("state") != "ALIVE"
                        or not e.get("ctrl")):
                    continue
                room = int(e.get("idle", 0)) - int(e.get("backlog", 0))
                if room > 0:
                    peers.append((room, nid, e))
            if not peers:
                return
            self._last_spill = now
            peers.sort(key=lambda t: -t[0])
            hop_capped = []
            for room, nid, e in peers:
                if surplus <= 0:
                    break
                take = min(surplus, room)
                specs = []
                while take > 0 and self._lease_q:
                    # Newest first: the oldest entries keep their local
                    # dispatch order (they are next to execute here).
                    spec = self._lease_q.pop()
                    hops = spec.spill_hops or 0
                    if hops >= cfg.lease_spill_max_hops:
                        hop_capped.append(spec)
                        continue
                    spec.spill_hops = hops + 1
                    if self._tev.enabled:
                        task_events.emit_task(
                            spec, "SPILL_SENT",
                            data={"to": nid.hex(), "hop": spec.spill_hops,
                                  "lease_seq": spec.lease_seq})
                    specs.append(spec)
                    take -= 1
                    surplus -= 1
                if not specs:
                    continue
                # Optimistic view update: the peer's backlog just grew by
                # what we are sending — without this every pump pass until
                # the next broadcast would dump on the same peer.
                e["backlog"] = int(e.get("backlog", 0)) + len(specs)
                # Blob selection is optimistic only WITHIN this batch
                # (one batch never carries the same blob twice);
                # _peer_fns itself is credited by _spill_to_peer after
                # the send SUCCEEDS — crediting here would let a failed
                # delivery suppress the blob on every future spill to
                # that peer, wedging the (peer, fn) pair into a
                # permanent reject->requeue churn loop.
                sent_fns = self._peer_fns.get(nid) or ()
                new_fns = set()
                triples = []
                for spec in specs:
                    blob = None
                    if (spec.fn_id and spec.fn_id not in sent_fns
                            and spec.fn_id not in new_fns):
                        blob = self._fn_blobs.get(spec.fn_id)
                        if blob is not None:
                            new_fns.add(spec.fn_id)
                    triples.append((spec.fn_id, blob, spec))
                plan.append((nid, triples, new_fns))
            for spec in hop_capped:  # must execute here: back of the queue
                self._lease_q.append(spec)
        for nid, triples, new_fns in plan:
            # Notice to the head FIRST (async bookkeeping — it re-points
            # node.leases so peer-death replay stays correct), then the
            # one agent->agent hop. Each move carries the lease grant
            # generation (lease_seq) and this hop's position in the spill
            # chain (spill_hops) so the head can drop stale notices
            # instead of re-pointing a lease that was re-granted, or
            # applying a multi-hop chain's frames out of order.
            if chaos.site("agent.spill_notice.lose"):
                pass  # injected notice loss: the head's lease-pop
                # fallbacks + the peer's lease_return path must keep
                # completions/death replay correct without it
            else:
                self._send_head(("lease_spilled",
                                 [(t[2].task_id, t[2].lease_seq,
                                   t[2].spill_hops, nid) for t in triples]))
            threading.Thread(target=self._spill_to_peer,
                             args=(nid, triples, new_fns), daemon=True,
                             name="rtpu-spill").start()

    def _spill_to_peer(self, nid: bytes, triples: list, new_fns: set):
        """Side thread: deliver spilled leases over the peer ctrl channel;
        an unreachable peer hands them back to the head (re-queued
        verbatim — they never started anywhere, no retry consumed).
        _peer_fns is credited only once the send succeeds; a failed send
        drops the peer's whole blob record (the channel died — assume
        nothing about what it still holds). An unpublished channel (a
        direct-call dial owned publication, or we lost a publish race)
        is retired after this one-shot use instead of leaking its fd and
        reader thread."""
        conn = self._peer_ctrl_conn(nid)
        if conn is not None:
            try:
                conn.send(("lease_spill", self.node_id, triples))
                if new_fns:
                    with self._lease_lock:
                        self._peer_fns.setdefault(nid, set()).update(new_fns)
                return
            except OSError:
                with self._lease_lock:
                    self._peer_fns.pop(nid, None)
            finally:
                with self._peer_lock:
                    published = self._peer_conns.get(nid) is conn
                if not published:
                    conn.close()
        self._send_head(("lease_return", [t[2] for t in triples]))

    def _peer_ctrl_conn(self, nid: bytes):
        """Cached agent<->agent ctrl channel, dialed via the cluster
        view's address (no head round trip). Blocking — side threads
        only. The fresh channel is published for reuse UNLESS a direct-
        call dial is mid-flight for the same peer (_dial_and_flush owns
        publication then: its queued calls must drain first to keep
        per-caller ordering). Callers must close() a returned channel
        that did not get published (they can tell by comparing against
        _peer_conns) once done with it — an unpublished channel nobody
        retires leaks its fd and reader thread."""
        with self._peer_lock:
            conn = self._peer_conns.get(nid)
            if conn is not None and conn.alive:
                return conn
        conn = self._dial_peer(nid)
        if conn is None:
            return None
        redundant = None
        with self._peer_lock:
            cur = self._peer_conns.get(nid)
            if cur is not None and cur.alive:
                redundant, conn = conn, cur  # raced another dial: reuse it
            elif nid not in self._dial_pending:
                self._peer_conns[nid] = conn
        if redundant is not None:
            redundant.close()
        return conn

    def _on_lease_spill(self, origin_nid: bytes, triples: list):
        """Executor side of a spill. Back-pressure: once our own
        un-started backlog reaches the spill floor, refuse the overflow
        by returning it to the head (re-queued, no retry consumed)
        instead of accepting work we could only re-spill."""
        reject = []
        accepted = False
        nat = self._nat
        with self._lease_lock:
            keep = self._spill_keep_locked()
            for fn_id, blob, spec in triples:
                if blob is not None:
                    if nat is not None:
                        nat.fn_blob(fn_id, blob)
                    else:
                        self._fn_blobs[fn_id] = blob
                backlog = (int(nat.backlog()) if nat is not None
                           else len(self._lease_q))
                have_fn = (not spec.fn_id
                           or (nat.has_fn_blob(spec.fn_id)
                               if nat is not None
                               else spec.fn_id in self._fn_blobs))
                if backlog >= keep or not have_fn:
                    if self._tev.enabled:
                        task_events.emit_task(
                            spec, "SPILL_REJECTED",
                            data={"from": origin_nid.hex(),
                                  "hop": spec.spill_hops or 0})
                    reject.append(spec)
                else:
                    if self._tev.enabled:
                        task_events.emit_task(
                            spec, "SPILL_RECEIVED",
                            data={"from": origin_nid.hex(),
                                  "hop": spec.spill_hops or 0})
                    if nat is not None:
                        if nat.seen(spec.task_id, spec.lease_seq or 0):
                            continue  # re-driven grant chased the spill
                        nat.push(spec.task_id, spec.fn_id,
                                 spec.lease_seq or 0, encode_payload(spec),
                                 task_events.attempt_of(spec), spec.name)
                    else:
                        if self._lease_dup_locked(spec):
                            continue  # already queued here (re-driven
                            # grant that chased the spill to this node)
                        self._lease_q.append(spec)
                    accepted = True
        if reject:
            self._send_head(("lease_return", reject))
        if accepted:
            self._pump_leases()

    def _lease_dup_locked(self, spec) -> bool:
        """Seen-set check+record for one accepted lease (caller holds
        _lease_lock). True => this exact grant generation was already
        accepted on this node and the copy must be dropped."""
        key = (spec.task_id, spec.lease_seq or 0)
        if key in self._lease_seen:
            return True
        self._lease_seen[key] = True
        while len(self._lease_seen) > 8192:
            self._lease_seen.popitem(last=False)
        return False

    def _sniff_lease_dones(self, w: _AgentWorker, msg,
                           collector: list | None = None) -> object | None:
        """Consume completions of node-leased tasks locally (they flow to
        the head as batched node_done frames, NOT as per-worker relays).
        Returns the message to relay for mixed batches (head-path entries
        untouched), or None when fully consumed. With `collector`, leased
        entries append there instead of sending — the select round flushes
        completions from EVERY ready worker as one node_done frame and
        pumps leases once (the same coalescing node_done already applied
        per-worker, lifted across the round)."""
        wid = w.worker_id.binary()
        entries = ([msg[1:]] if msg[0] == "done" else list(msg[1]))
        leased, rest = [], []
        nat = self._nat
        if nat is not None:
            # The inflight table is native; a miss is a head-path done
            # whose load was credited via _to_worker's load_add.
            for e in entries:
                if nat.inflight_pop(e[0]) >= 0:
                    leased.append((e[0], e[2]) if len(e) < 4
                                  else (e[0], e[2], e[3], w.hex_id))
                else:
                    rest.append(e)
                    if w.widx is not None:
                        nat.load_add(w.widx, -1)
        else:
            with self._lease_lock:
                for e in entries:
                    if self._lease_inflight.pop(e[0], None) is not None:
                        # (task_id, outs[, exec-span record, worker hex])
                        # — the piggybacked exec record keeps riding the
                        # node_done batch toward the head.
                        leased.append((e[0], e[2]) if len(e) < 4
                                      else (e[0], e[2], e[3], w.hex_id))
                    else:
                        rest.append(e)
                    load = self._worker_load.get(wid, 0)
                    self._worker_load[wid] = max(0, load - 1)
        if not leased:
            return msg
        if collector is not None:
            collector.extend(leased)
        else:
            self._send_head(("node_done", leased))
            self._pump_leases()
        if not rest:
            return None
        return (("done",) + tuple(rest[0]) if len(rest) == 1
                else ("done_batch", rest))

    def _handle_head_msg(self, msg):
        op = msg[0]
        if op == "to_worker":
            self._to_worker(msg[1], msg[2])
        elif op == "relay_batch":
            # One head sendall fanning dispatches to several local workers
            # (the head's per-node batching under many-agent load).
            for wid, inner in msg[1]:
                self._to_worker(wid, inner)
        elif op == "batch":
            # Listener-thread out-batch from the head: several control
            # frames coalesced into one sendall.
            for inner in msg[1]:
                self._handle_head_msg(inner)
        elif op == "node_exec":
            # Node lease batch: WE pick the workers (raylet-local
            # dispatch); blobs ride along on first sight of a function.
            # language="cpp" leases route to their own queue — they only
            # ever dispatch onto cpp workers, over the protobuf plane.
            any_cpp = False
            nat = self._nat
            if nat is not None:
                # Object-form grants (head fallback frames, lease
                # watchdog re-drives) feed the NATIVE ledger: dedup
                # against the same seen table the raw path uses, then
                # re-pickle the spec into the native queue.
                for fn_id, blob, spec in msg[1]:
                    if blob is not None and fn_id is not None:
                        nat.fn_blob(fn_id, blob)
                    if nat.seen(spec.task_id, spec.lease_seq or 0):
                        continue  # re-drive of a grant we DID get
                    if getattr(spec, "language", None) == "cpp":
                        with self._lease_lock:
                            self._cpp_q.append(spec)
                        any_cpp = True
                    else:
                        nat.push(spec.task_id, spec.fn_id,
                                 spec.lease_seq or 0, encode_payload(spec),
                                 task_events.attempt_of(spec), spec.name)
            else:
                with self._lease_lock:
                    for fn_id, blob, spec in msg[1]:
                        if blob is not None:
                            self._fn_blobs[fn_id] = blob
                        if self._lease_dup_locked(spec):
                            continue  # head re-drive of a grant we DID get
                        if getattr(spec, "language", None) == "cpp":
                            self._cpp_q.append(spec)
                            any_cpp = True
                        else:
                            self._lease_q.append(spec)
            self._pump_leases()
            if any_cpp:
                self._pump_cpp_leases()
            self._maybe_push_load_delta()
        elif op == "node_exec_raw":
            # Native-plane lease batch: specs ride as raw pickle bytes
            # with (tid, fn, lease_seq, blob, spec, attempt, name)
            # sideband — consumed in C++ on the native loop; this
            # handler is the chaos-armed / fallback ingest.
            self._on_node_exec_raw(msg[1])
        elif op == "cluster_view":
            # Head broadcast of the versioned cluster resource view: a
            # DELTA relative to this agent's head-side cursor (entries
            # that changed since the last frame we were sent). Fresh
            # information about idle peers may unblock a spill.
            _, version, entries = msg
            smap = None
            with self._lease_lock:
                self._cview_version = version
                for nid, e in entries:
                    if nid == SHARD_MAP_KEY:
                        smap = e.get("smap")  # reserved pseudo-entry
                        continue
                    self._cluster_view[nid] = e
            if smap is not None:
                self._adopt_shard_map(smap)
            self._maybe_spill_leases()
        elif op == "lease_reclaim":
            # Head reclaims un-started backlog for idle nodes elsewhere.
            returned = []
            if self._nat is not None:
                returned = [pickle.loads(spec) for _t, _f, _s, spec
                            in self._nat.steal_tail(int(msg[1]))]
            else:
                with self._lease_lock:
                    for _ in range(int(msg[1])):
                        if not self._lease_q:
                            break
                        returned.append(self._lease_q.pop())
            if returned:
                self._send_head(("lease_return", returned))
        elif op == "seq_skip":
            _, owner, aid, seq = msg
            tw = None
            for wid, hosted in self.worker_actor.items():
                if hosted == aid:
                    tw = self.workers.get(wid)
                    break
            if tw is not None and tw.peer_path:
                # The hosting worker owns the order gate (peer plane):
                # the skip must land there, not on this agent's gate.
                try:
                    send_msg(tw.sock, msg, tw.send_lock)
                except OSError:
                    pass  # worker gone; its gate died with it
            else:
                self._skip_order_slot(owner, aid, seq)
        elif op == "spawn_worker":
            pip = msg[1] if len(msg) > 1 else None
            if len(self.workers) < self.max_workers:
                threading.Thread(target=self._spawn_worker,
                                 kwargs={"pip": pip}, daemon=True).start()
        elif op == "kill_worker":
            w = self.workers.get(msg[1])
            if w is not None and w.proc is not None:
                try:
                    w.proc.kill()
                except ProcessLookupError:
                    pass
        elif op == "fetch":
            _, oid, src_addr, attempt = msg
            threading.Thread(target=self._fetch_object,
                             args=(oid, tuple(src_addr), attempt),
                             daemon=True).start()
        elif op == "fetch_many":
            # Vectored pull: one batched objxfer round for a same-source
            # group (the exchange reduce half's many small pieces).
            _, entries, src_addr = msg
            threading.Thread(target=self._fetch_objects_many,
                             args=(entries, tuple(src_addr)),
                             daemon=True, name="rtpu-fetch-many").start()
        elif op == "free_obj":
            try:
                self.store.delete(ObjectID(msg[1]))
            except Exception:  # noqa: BLE001
                pass
        elif op == "agent_resp":
            fut = self._agent_req_futs.get(msg[1])
            if fut is not None and not fut.done():
                fut.set_result(msg[2])
        elif op == "node_ack":
            pass
        elif op == "shutdown_node":
            self._die()

    # ---------------- direct agent<->agent actor calls ----------------

    def _ctrl_accept_loop(self):
        while not self._shutdown:
            try:
                sock, _addr = self.ctrl_srv.accept()
            except OSError:
                return
            enable_nodelay(sock)
            _PeerConn(self, sock, nid=None).start()

    def _dial_peer(self, nid: bytes):
        """Dial a peer agent's ctrl port WITHOUT publishing the channel —
        the dial thread publishes only after draining its pending queue,
        keeping per-caller ordering across the dial window.

        The address comes from the broadcast cluster view when it has the
        peer (zero head round trips — the decentralization the broadcast
        plane exists for); the synchronous head query is the fallback for
        peers the view has not carried yet."""
        from ray_tpu.core.transport import dial
        if chaos.site("agent.peer_dial.fail"):
            return None  # injected unreachable peer: callers fall back
            # through the head (or lease_return the spill batch)
        sock = None
        with self._lease_lock:
            e = self._cluster_view.get(nid) or {}
            addr = e.get("ctrl") if e.get("state") == "ALIVE" else None
        if addr:
            try:
                sock = dial(addr)
            except OSError:
                sock = None  # stale view entry: ask the head
        if sock is None:
            try:
                addr = self._head_request("node_ctrl_addr", nid)
                if not addr:
                    return None
                sock = dial(addr)
            except Exception:  # noqa: BLE001 — fall back to head
                return None
        conn = _PeerConn(self, sock, nid=nid)
        try:
            conn.send(("peer_hello", self.node_id))
        except OSError:
            # Peer died between connect and hello: close the orphan fd
            # (no reader thread owns it yet) and report "unreachable" —
            # an escaping OSError would kill the dial thread and leave
            # _dial_and_flush's _dial_pending entry wedged forever.
            try:
                sock.close()
            except OSError:
                pass
            return None
        conn.start()
        return conn

    def _route_direct(self, w: _AgentWorker, msg):
        """A local worker asked for a direct actor call: deliver to the
        target worker on this node or over the peer channel; on any miss,
        fall back to the head path and tell the caller to re-resolve.

        Runs on the agent's main loop thread: it must NEVER block on the
        head (the reply would be read by this very loop). Cached channels
        send inline; a missing channel queues the call and dials on a side
        thread, flushing the queue in order once connected."""
        _, target_nid, target_wid, spec = msg
        origin_wid = w.worker_id.binary()
        if target_nid == self.node_id:
            tw = self.workers.get(target_wid)
            if tw is None:
                self._direct_fallback(origin_wid, spec)
                return

            def deliver():
                self._routed[spec.task_id] = (
                    None, origin_wid, spec, target_wid)
                try:
                    send_msg(tw.sock, ("exec", spec), tw.send_lock)
                except OSError:
                    self._routed.pop(spec.task_id, None)
                    self._direct_fallback(origin_wid, spec)

            if tw.peer_path:
                deliver()  # the worker's own gate orders this frame
            else:
                self._exec_in_order(
                    spec, target_wid, deliver,
                    on_drop=lambda: self._direct_fallback(origin_wid, spec))
            return
        with self._peer_lock:
            conn = self._peer_conns.get(target_nid)
            if conn is None or not conn.alive:
                pend = self._dial_pending.get(target_nid)
                if pend is not None:
                    pend.append((origin_wid, target_wid, spec))
                    return
                self._dial_pending[target_nid] = [
                    (origin_wid, target_wid, spec)]
                threading.Thread(target=self._dial_and_flush,
                                 args=(target_nid,), daemon=True).start()
                return
        self._peer_send(conn, origin_wid, target_wid, spec)

    # ------------- per-caller actor-call ordering (executor side) -------------
    # The gate itself lives in core/order_gate.py (shared with head-node
    # pooled workers, which face the same two-transport race on the
    # worker<->worker peer plane).

    def _exec_in_order(self, spec, target_wid: bytes, deliver, on_drop=None):
        self._order_gate.submit(spec, deliver, on_drop=on_drop,
                                target=target_wid)

    def _skip_order_slot(self, owner: bytes, actor_id: bytes, seq: int):
        self._order_gate.skip(owner, actor_id, seq)

    def _peer_send(self, conn: "_PeerConn", origin_wid, target_wid, spec):
        conn.inflight[spec.task_id] = (origin_wid, spec)
        try:
            conn.send(("peer_exec", target_wid, spec, self.node_id,
                       origin_wid))
        except OSError:
            conn.inflight.pop(spec.task_id, None)
            self._direct_fallback(origin_wid, spec)

    def _dial_and_flush(self, target_nid: bytes):
        """Side thread: resolve + dial the peer, then flush the queued
        calls in submission order. The channel is published only once the
        queue is drained — a new call racing the flush keeps appending to
        _dial_pending (the entry stays present until the final pass), so
        nothing can jump ahead of older queued calls."""
        conn = self._dial_peer(target_nid)
        while True:
            with self._peer_lock:
                pend = self._dial_pending.get(target_nid) or []
                if not pend:
                    self._dial_pending.pop(target_nid, None)
                    if conn is not None and conn.alive:
                        self._peer_conns[target_nid] = conn
                    break
                self._dial_pending[target_nid] = []
            for origin_wid, target_wid, spec in pend:
                if conn is not None and conn.alive:
                    self._peer_send(conn, origin_wid, target_wid, spec)
                else:
                    self._direct_fallback(origin_wid, spec)

    def _direct_fallback(self, origin_wid: bytes, spec,
                         maybe_executed: bool = False):
        """Stale/unreachable target: submit through the head (correct,
        slower) and poison the caller's location cache.

        maybe_executed=True means the exec may have reached the actor
        (channel died after delivery): resubmitting would break at-most-once
        semantics, so the call only retries when the user allowed actor-task
        retries — otherwise its returns fail with the ambiguity spelled
        out (matching the head path's actor-death behavior)."""
        if maybe_executed and (spec.retries_left or 0) <= 0:
            self._fail_direct_call(origin_wid, spec)
        else:
            if maybe_executed:
                spec.retries_left -= 1
            self._send_head(("wmsg", origin_wid, ("submit", spec)))
        w = self.workers.get(origin_wid)
        if w is not None:
            try:
                send_msg(w.sock, ("actor_moved", spec.actor_id),
                         w.send_lock)
            except OSError:
                pass

    def _fail_direct_call(self, origin_wid: bytes, spec):
        """Resolve the caller's returns with an error (no retry budget)."""
        from ray_tpu.core import serialization
        from ray_tpu.core.status import ActorDiedError
        err = ActorDiedError(
            msg=f"direct actor call {spec.describe()} lost its channel "
            "mid-flight; it may or may not have executed (set "
            "max_task_retries to allow replay)")
        try:
            payload, bufs, _ = serialization.serialize_value(err)
        except Exception:  # noqa: BLE001
            return
        w = self.workers.get(origin_wid)
        if w is None:
            return
        for rid in spec.return_ids or []:
            try:
                send_msg(w.sock, ("obj", rid, "err", payload, bufs),
                         w.send_lock)
            except OSError:
                return

    def _deliver_direct_done(self, origin_wid: bytes, done_msg):
        """Resolve the caller's futures locally: inline/err outs become obj
        pushes into the caller's cache; shm-tier outs resolve through the
        normal head pull on first get."""
        w = self.workers.get(origin_wid)
        if w is None:
            return
        for rid, status, payload, bufs in done_msg[3]:
            if status in ("inline", "err"):
                try:
                    send_msg(w.sock, ("obj", rid, status, payload, bufs),
                             w.send_lock)
                except OSError:
                    return

    def _on_peer_frame(self, conn: "_PeerConn", msg):
        op = msg[0]
        if op == "peer_hello":
            conn.nid = msg[1]
            with self._peer_lock:
                self._peer_conns.setdefault(msg[1], conn)
        elif op == "peer_exec":
            _, wid, spec, origin_nid, origin_wid = msg
            tw = self.workers.get(wid)
            if tw is None:
                conn.send(("peer_fail", origin_wid, spec))
                return

            def deliver(tw=tw, wid=wid, spec=spec, origin_wid=origin_wid):
                self._routed[spec.task_id] = (conn, origin_wid, spec, wid)
                try:
                    send_msg(tw.sock, ("exec", spec), tw.send_lock)
                except OSError:
                    self._routed.pop(spec.task_id, None)
                    try:
                        conn.send(("peer_fail", origin_wid, spec))
                    except OSError:
                        pass

            def on_drop(spec=spec, origin_wid=origin_wid):
                try:
                    conn.send(("peer_fail", origin_wid, spec))
                except OSError:
                    pass

            if tw.peer_path:
                deliver()  # the worker's own gate orders this frame
            else:
                self._exec_in_order(spec, wid, deliver, on_drop=on_drop)
        elif op == "lease_spill":
            # Surplus leases forwarded by a saturated peer agent (the
            # decentralized spillback hop — the head was only notified).
            _, origin_nid, triples = msg
            self._on_lease_spill(origin_nid, triples)
        elif op == "peer_done":
            _, origin_wid, done_msg = msg
            conn.inflight.pop(done_msg[1], None)
            self._deliver_direct_done(origin_wid, done_msg)
        elif op == "peer_fail":
            _, origin_wid, spec = msg[:3]
            maybe_executed = bool(msg[3]) if len(msg) > 3 else False
            conn.inflight.pop(spec.task_id, None)
            self._direct_fallback(origin_wid, spec,
                                  maybe_executed=maybe_executed)

    def _on_peer_eof(self, conn: "_PeerConn"):
        published = False
        with self._peer_lock:
            if conn.nid is not None and self._peer_conns.get(
                    conn.nid) is conn:
                self._peer_conns.pop(conn.nid, None)
                published = True
        if published:
            # The peer LINK died for an unknown reason: forget which fn
            # blobs that peer holds — the next spill resends them (cheap)
            # rather than betting un-started work on stale bookkeeping.
            # (One-shot channels skip this: their deliveries succeeded,
            # and the blobs live in the peer's process-level cache.)
            with self._lease_lock:
                self._peer_fns.pop(conn.nid, None)
        # Calls in flight on the dead channel MAY have executed (the exec
        # frame was sent): only retry-permitted calls replay via the head.
        for task_id, (origin_wid, spec) in list(conn.inflight.items()):
            conn.inflight.pop(task_id, None)
            self._direct_fallback(origin_wid, spec, maybe_executed=True)

    def _maybe_route_done(self, w: _AgentWorker, msg) -> None:
        """Executor-side: a done for a direct-routed task also flows back
        over its peer channel (the head copy keeps the directory/metrics
        truthful)."""
        entries = ([msg[1:]] if msg[0] == "done"
                   else [e for e in msg[1]])
        for e in entries:
            task_id = e[0]
            route = self._routed.pop(task_id, None)
            if route is None:
                continue
            conn, origin_wid = route[0], route[1]
            done_msg = ("done", task_id, e[1], e[2])
            if conn is None:
                self._deliver_direct_done(origin_wid, done_msg)
            else:
                try:
                    conn.send(("peer_done", origin_wid, done_msg))
                except OSError:
                    pass

    # ---------------- object plane ----------------

    def _fetch_object(self, oid: bytes, src_addr, attempt=None):
        """Pull `oid` from a peer's store into ours (parity: pull_manager)."""
        ok = False
        try:
            ok = objxfer.fetch_from_peer(self.store, src_addr, oid)
        except Exception:  # noqa: BLE001
            traceback.print_exc()
        self._send_head(("fetched", oid, ok, attempt))

    def _fetch_objects_many(self, entries: list, src_addr):
        """Pull a same-source batch [(oid, attempt), ...] over ONE objxfer
        connection round and reply with a single fetched_many frame."""
        results: dict = {}
        try:
            results = objxfer.fetch_many_from_peer(
                self.store, src_addr, [oid for oid, _att in entries])
        except Exception:  # noqa: BLE001
            traceback.print_exc()
        self._send_head(("fetched_many",
                         [(oid, bool(results.get(oid)), att)
                          for oid, att in entries]))

    # ---------------- main loop ----------------

    def _handle_worker_msg(self, w: _AgentWorker, msg, out_frames: list,
                           lease_dones: list):
        """One decoded Python-worker frame (shared by the Python select
        loop and the native pump's slow path)."""
        op0 = msg[0]
        if op0 == "actor_ready":
            # Track which worker hosts which actor — the
            # re-registration inventory needs it for head-restart
            # adoption (and the native ledger stops leasing to it).
            self.worker_actor[w.worker_id.binary()] = msg[1]
            if self._nat is not None and w.widx is not None:
                self._nat.worker_eligible(w.widx, False)
        elif op0 == "direct_actor":
            # Direct-call fast path: never touches the head.
            try:
                self._route_direct(w, msg)
            except Exception:
                traceback.print_exc()
            return
        elif op0 in ("done", "done_batch"):
            if self._routed:
                try:
                    self._maybe_route_done(w, msg)
                except Exception:
                    traceback.print_exc()
            try:
                msg = self._sniff_lease_dones(w, msg,
                                              collector=lease_dones)
            except Exception:
                traceback.print_exc()
            if msg is None:
                return  # fully leased: rides node_done
        elif op0 == "ready":
            if len(msg) > 4 and msg[4]:
                w.peer_path = msg[4]
            self._pump_leases()  # fresh worker: feed it
        out_frames.append(("wmsg", w.worker_id.binary(), msg))

    def run(self):
        if self._nat is not None:
            return self._run_native()
        while not self._shutdown:
            with self._sel_lock:
                try:
                    events = self._selector.select(timeout=0.05)
                except OSError:
                    continue
            if self._order_gate.buffered:
                self._order_gate.flush_expired()
            for key, _mask in events:
                kind, w = key.data
                try:
                    data = key.fileobj.recv(1 << 20)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    data = b""
                if kind == "head":
                    if not data:
                        self._reconnect_or_die()
                        if self._shutdown:
                            return
                        continue
                    self.head_buffer.feed(data)
                    for msg in self.head_buffer.frames():
                        try:
                            self._handle_head_msg(msg)
                        except Exception:
                            traceback.print_exc()
                else:  # worker
                    if not data:
                        self._on_worker_eof(w)
                        continue
                    if w.language == "cpp":
                        # Protobuf worker plane: decoded apart from the
                        # pickle framing (and a non-proto frame raises).
                        try:
                            self._on_cpp_frames(w, data)
                        except Exception:
                            traceback.print_exc()
                        continue
                    # Frames that arrived together in this ONE recv are a
                    # zero-latency batch: their head-bound relays coalesce
                    # into one vectored sendmsg (framing preserved — the
                    # head's FrameBuffer splits them back) and their leased
                    # completions into one node_done + one lease pump.
                    # Batching WIDER than a drain (a whole select round)
                    # measurably stalls the done -> node_done -> refill
                    # cycle the lease plane clocks on (16-agent run:
                    # 4x fewer tasks/s round-batched vs per-drain).
                    w.buffer.feed(data)
                    out_frames: list = []
                    lease_dones: list = []
                    for msg in w.buffer.frames():
                        self._handle_worker_msg(w, msg, out_frames,
                                                lease_dones)
                    self._flush_head_batch(out_frames, lease_dones)

    def _run_native(self):
        """The select round on the native pump: C++ owns readiness, frame
        split, hot-frame consumption (lease grants in, leased dones out)
        and dispatch planning; Python handles the cold frames and performs
        the sends. Chaos-armed rounds skip native consumption so every
        frame takes the Python path and its seeded sites."""
        from ray_tpu.core.transport import _decode_proto
        from ray_tpu._native.agent_core import (HEAD_TAG, KIND_EOF,
                                                KIND_PROTO, KIND_RAW)
        nat = self._nat
        while not self._shutdown:
            try:
                n = nat.poll(50)
            except OSError:
                continue
            if self._order_gate.buffered:
                self._order_gate.flush_expired()
            if n <= 0:
                continue
            nat.split()
            consumed = 0
            if chaos._armed is None and not self._routed:
                consumed = nat.consume_hot(HEAD_TAG)
            out_frames: list = []
            lease_dones: list = []
            head_eof = False
            dead_workers: list = []
            for tag, kind, _ptag, payload, bufs, _whole in nat.frames():
                try:
                    if kind == KIND_EOF:
                        if tag == HEAD_TAG:
                            head_eof = True
                        else:
                            w = self._tag_worker.get(tag)
                            if w is not None:
                                dead_workers.append(w)
                        continue
                    if tag == HEAD_TAG:
                        msg = (_decode_proto(bytes(payload))
                               if kind == KIND_PROTO
                               else pickle.loads(payload, buffers=bufs))
                        self._handle_head_msg(msg)
                        continue
                    w = self._tag_worker.get(tag)
                    if w is None:
                        continue
                    if kind == KIND_RAW:
                        self._on_cpp_frames(w, bytes(payload))
                        continue
                    msg = (_decode_proto(bytes(payload))
                           if kind == KIND_PROTO
                           else pickle.loads(payload, buffers=bufs))
                    self._handle_worker_msg(w, msg, out_frames,
                                            lease_dones)
                except Exception:
                    traceback.print_exc()
            self._flush_head_batch(out_frames, lease_dones)
            if consumed:
                # The round's node_done_raw batch (raw done frames, one
                # frame per completing worker) — built natively, sent
                # under the same head lock as every other head write.
                nd = nat.take_node_done()
                if len(nd):
                    try:
                        with self.head_lock:
                            self.head_sock.sendall(nd)
                    except OSError:
                        head_eof = True
                self._pump_leases()
                self._maybe_push_load_delta()
            nat.round_end()  # frame views die here
            for w in dead_workers:
                self._on_worker_eof(w)
            if head_eof:
                self._reconnect_or_die()
                if self._shutdown:
                    return

    def _adopt_shard_map(self, smap: dict):
        """Adopt a newer shard map from the view broadcast (epoch-gated:
        re-slices and respawns bump it; a stale frame must not resurrect
        a dead shard's channel). Cached channels drop wholesale — ports
        move on respawn, and redialing a live shard is cheap."""
        with self._shard_lock:
            cur = self._shard_map
            if cur is not None and smap.get("epoch", 0) <= cur.get("epoch", 0):
                return
            self._shard_map = smap
            stale = list(self._shard_socks.values())
            self._shard_socks = {}
        for sock, _lk in stale:
            try:
                sock.close()
            except OSError:
                pass

    def _shard_send(self, sid: int, msg) -> bool:
        """Best-effort send on the (lazily dialed, cached) channel to one
        head shard; False tells the caller to fall back to the head."""
        from ray_tpu.core.transport import dial
        with self._shard_lock:
            ent = self._shard_socks.get(sid)
            smap = self._shard_map
        if ent is None:
            addr = next(((h, p) for s, h, p in (smap or {}).get("shards", ())
                         if s == sid), None)
            if addr is None:
                return False
            try:
                sock = dial(addr, timeout=2.0)
            except OSError:
                return False
            with self._shard_lock:
                ent = self._shard_socks.setdefault(
                    sid, (sock, threading.Lock()))
            if ent[0] is not sock:
                try:
                    sock.close()  # lost the install race; use the winner
                except OSError:
                    pass
        sock, lk = ent
        try:
            send_msg(sock, msg, lk)
            return True
        except OSError:
            with self._shard_lock:
                if self._shard_socks.get(sid) is ent:
                    self._shard_socks.pop(sid, None)
            try:
                sock.close()
            except OSError:
                pass
            return False

    def _ship_tev_shards(self, fr):
        """Route a ("task_events", batch, dropped) frame to the owning
        head shards by task-id bucket; returns the residue frame for the
        head (the whole frame when no shard map is adopted, plus any
        events whose shard send failed — shard death downgrades to the
        pre-shard head path, never to a lost event)."""
        with self._shard_lock:
            smap = self._shard_map
        if smap is None:
            return fr
        _, batch, dropped = fr
        buckets = smap["buckets"]
        per: dict[int, list] = {}
        for ev in batch:
            tid = ev[0] if ev and isinstance(ev[0], bytes) else b""
            per.setdefault(buckets[bucket_of(tid)], []).append(ev)
        residue: list = []
        for sid, evs in per.items():
            if not self._shard_send(
                    sid, ("tev_ingest", self.node_id, evs, 0)):
                residue.extend(evs)
        if residue or dropped:
            return ("task_events", residue, dropped)
        return None

    def _tev_frame(self, force: bool = False):
        """A ("task_events", batch, dropped) frame when a flush is due,
        else None. Riding the select-round batch / heartbeat means the
        pipeline never adds a wakeup or connection of its own."""
        tev = self._tev
        if not (tev.enabled and (tev.events or tev.dropped)):
            return None
        now = time.monotonic()
        if (not force and (now - self._tev_last_flush) * 1000.0
                < self.config.task_events_flush_ms):
            return None
        # racecheck: ok thread-escape pacing heuristic only: select round
        # and heartbeat both stamp it; a torn check costs one extra flush
        # of an already-thread-safe ring, never a lost event
        self._tev_last_flush = now
        batch, dropped = tev.drain()
        if not batch and not dropped:
            return None
        return ("task_events", batch, dropped)

    def _flush_head_batch(self, out_frames: list, lease_dones: list):
        """One worker drain's head-bound traffic: a single frame (or one
        coalesced sendmsg batch) plus at most one lease pump."""
        if lease_dones:
            out_frames.append(("node_done", lease_dones))
        fr = self._tev_frame()
        if fr is not None:
            fr = self._ship_tev_shards(fr)
        if fr is not None:
            out_frames.append(fr)
        if out_frames:
            try:
                if len(out_frames) == 1:
                    send_msg(self.head_sock, out_frames[0], self.head_lock)
                else:
                    send_many(self.head_sock, out_frames, self.head_lock)
            except OSError:
                self._reconnect_or_die()
        if lease_dones:
            self._pump_leases()
        self._maybe_push_load_delta()

    def _die(self):
        if self._shutdown:
            return
        self._shutdown = True
        for w in list(self.workers.values()):
            if w.proc is not None:
                try:
                    w.proc.kill()
                except ProcessLookupError:
                    pass
        if self.zygote is not None:
            self.zygote.close()
        with self._shard_lock:
            shard_socks = list(self._shard_socks.values())
        for sock, _lk in shard_socks:
            try:
                sock.close()
            except OSError:
                pass
        try:
            self.ctrl_srv.close()
        except OSError:
            pass
        try:
            # Peer server first: native threads read the arena mmap raw;
            # close gate second: the heartbeat orphan sweep walks it too.
            self.peer_server.stop()
            with self._store_close_lock:
                self.store.close()
                self.store.unlink()
        except Exception:  # noqa: BLE001
            pass
        os._exit(0)


def main(argv=None):
    p = argparse.ArgumentParser(description="ray_tpu node agent (raylet)")
    p.add_argument("--head", required=True, help="head host:port")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=0)
    p.add_argument("--resources", type=str, default="{}",
                   help="extra resources as JSON")
    p.add_argument("--object-store-memory", type=int, default=0)
    p.add_argument("--node-ip", type=str, default="127.0.0.1")
    p.add_argument("--watch-parent", type=int, default=0,
                   help="self-terminate when this pid exits (the raylet "
                        "parent-death watch)")
    p.add_argument("--node-id", type=str, default="",
                   help="hex node id (assigned by the launcher; random if "
                        "empty)")
    args = p.parse_args(argv)
    if args.watch_parent:
        from ray_tpu.cli import _watch_parent
        _watch_parent(args.watch_parent)
    agent = NodeAgent(
        args.head, num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources=json.loads(args.resources),
        object_store_memory=args.object_store_memory or None,
        node_ip=args.node_ip,
        node_id=bytes.fromhex(args.node_id) if args.node_id else None)

    def _sig(_s, _f):
        agent._die()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    agent.run()


if __name__ == "__main__":
    main()
