"""Central config table with environment override.

Parity: reference `src/ray/common/ray_config_def.h` (RAY_CONFIG X-macro table,
223 flags, overridable via `RAY_<name>` env vars) and
`python/ray/_private/ray_constants.py`. Here the table is a typed dict; every
entry can be overridden with `RAY_TPU_<NAME>` in the environment or a
`_system_config` dict passed to `ray_tpu.init()`, and the resolved table is
inherited by spawned worker processes through the environment.
"""

from __future__ import annotations

import json
import os
from typing import Any

ENV_PREFIX = "RAY_TPU_"

# name -> (type, default, help)
_CONFIG_DEFS: dict[str, tuple[type, Any, str]] = {
    # --- object store ---
    "object_store_memory_bytes": (int, 0, "shm arena size; 0 = auto (30% RAM, capped)"),
    "object_store_auto_cap_bytes": (int, 20 * 2**30, "cap for auto-sized arena"),
    "object_store_hash_slots": (int, 1 << 16, "object index slots in shm"),
    "object_store_shards": (int, 0, "lock shards in the shm store (index + "
                            "allocator split per-shard); 0 = auto "
                            "(power of two in [8, 16])"),
    "max_inline_object_bytes": (int, 100 * 1024, "results <= this are returned inline"),
    "max_inline_arg_bytes": (int, 256 * 1024, "task/actor-call args whose "
                             "pickle-5 buffers exceed this ship through the "
                             "shm arena (create/seal + ref in the frame) "
                             "instead of riding the socket frame; smaller "
                             "args stay inline to keep the no-arg latency "
                             "floor"),
    "object_spill_dir": (str, "", "directory for spilled objects; '' = <session>/spill"),
    "object_spill_threshold": (float, 0.8, "spill when arena usage exceeds this"),
    "put_reservation_min_bytes": (int, 4 << 20, "puts at least this large "
                                  "take the per-client write-reservation "
                                  "path (carve once under the shard/global "
                                  "lock, fill lock-free, publish sealed); "
                                  "0 disables the plane"),
    "put_reservation_bytes": (int, 0, "write-reservation extent size per "
                              "client; 0 = auto (min(256MB, arena/16)). "
                              "Bigger extents amortize the global carve + "
                              "spill check over more puts but strand more "
                              "headroom per idle client"),
    "objxfer_conn_cache_size": (int, 4, "idle persistent pull connections "
                                "cached per peer address (the objxfer "
                                "client reuses one connection per pull "
                                "instead of dialing); 0 = close after "
                                "every pull"),
    "objxfer_streams": (int, 4, "connections a single large cross-node "
                        "object pull is striped over (range requests on "
                        "cached connections); 1 = whole-object pulls"),
    "objxfer_stream_min_bytes": (int, 32 << 20, "objects smaller than this "
                                 "always pull on one connection"),
    # --- data plane (Arrow blocks in the arena) ---
    "data_block_arrow": (bool, True, "pyarrow.Table values seal into the "
                         "arena as tagged Arrow IPC objects (format "
                         "'arrow': the writer streams the IPC encoding "
                         "straight into a write reservation, readers "
                         "re-hydrate zero-copy over the mapped arena); "
                         "off = blocks ride the pickle path like any "
                         "other value"),
    "vectored_arg_fetch_min": (int, 2, "a task whose args carry at least "
                               "this many locally-missing ObjectRefs "
                               "subscribes to all of them in ONE wait_objs "
                               "frame, and the head groups same-source "
                               "pulls into one batched objxfer round "
                               "(fetch_many) instead of N serial gets; "
                               "0 disables vectored fetch"),
    # --- compiled-graph channels (parity: the NCCL-channel data plane
    #     under the reference's compiled graphs) ---
    "dag_channel_type": (str, "tensor", "compiled-graph channel encoding: "
                         "'tensor' stages array leaves straight into shm "
                         "(no pickle on tensor bytes; zero-copy reads), "
                         "'pickle' is the legacy whole-value frame"),
    "tensor_channel_inline_bytes": (int, 4096, "array leaves smaller than "
                                    "this ride the tensor frame's sidecar "
                                    "pickle instead of the binary leaf "
                                    "plane (descriptor overhead isn't "
                                    "worth it below ~a page)"),
    # --- workers / scheduling ---
    "worker_jax_platform": (str, "cpu", "jax backend for pooled workers; "
                            "tasks with num_tpus>0 re-latch onto the host "
                            "platform ('' = inherit the driver's)"),
    "num_workers": (int, 0, "worker pool size; 0 = num_cpus"),
    "gc_gen0_threshold": (int, 20000, "python gc gen-0 threshold in head/"
                          "workers; default 700 triggers a collection (and "
                          "jax's gc callback) every ~70 control messages"),
    "gc_freeze_init": (bool, True, "gc.freeze() the boot-time object "
                       "universe (jax + imports, ~1M objects) in head/"
                       "zygote/agent processes: full collections stop "
                       "re-scanning it (a gen-2 pass over the jax universe "
                       "ran 100ms+ and showed up as bimodal task-storm "
                       "rates), and zygote-forked workers keep those pages "
                       "COW-shared. Cost: cyclic garbage created BEFORE "
                       "init leaks (refcounted objects still free "
                       "normally)"),
    "worker_startup_timeout_s": (float, 60.0, "time to wait for a worker to boot"),
    "worker_idle_timeout_s": (float, 300.0, "idle workers above pool size are reaped"),
    "max_pending_lease_requests": (int, 10, "in-flight lease requests per scheduling key"),
    "max_tasks_in_flight_per_worker": (int, 8, "same-key tasks pipelined "
                                      "onto one busy worker (depth-K "
                                      "dispatch; 1 disables pipelining)"),
    "task_max_retries_default": (int, 3, "default retries for idempotent tasks"),
    "actor_max_restarts_default": (int, 0, "default actor restarts"),
    # --- cross-language workers (parity: the reference's C++ worker
    #     runtime, cpp/src/ray/runtime/task/task_executor.cc +
    #     core_worker.proto:457 — a non-Python process that registers,
    #     leases, executes and returns tasks over the neutral exec plane) ---
    "cpp_worker_enable": (bool, True, "node agents advertise the CPP "
                          "capability resource and spawn the C++ worker "
                          "binary on demand for language='cpp' tasks "
                          "(compiled through the _native/build.py "
                          "content-hash g++ cache on first use)"),
    "cpp_worker_binary": (str, "", "path to a prebuilt raytpu_worker "
                          "binary; '' = compile cpp/raytpu_worker.cc + "
                          "_native/object_store.cpp on first spawn"),
    "cpp_worker_pool": (int, 0, "max C++ workers per node agent; "
                        "0 = the node's CPU count"),
    # --- cluster-view broadcast + lease spillback (parity:
    #     ray_syncer.h:20 broadcast half + cluster_task_manager.cc:187
    #     scheduler spillback — decentralized agent->agent rebalancing) ---
    "cluster_view_broadcast_ms": (int, 100, "head broadcasts the versioned "
                                  "cluster resource view to node agents at "
                                  "this interval; per-agent version cursors "
                                  "make every frame a delta (an agent only "
                                  "receives entries that changed since its "
                                  "cursor); 0 disables the broadcast plane"),
    "lease_spillback": (bool, True, "a node agent whose un-started lease "
                        "backlog exceeds its capacity forwards leases "
                        "directly to an under-loaded peer agent (one "
                        "agent->agent hop; the head is informed "
                        "asynchronously via a lease_spilled delta)"),
    "lease_spill_backlog_per_worker": (int, 2, "spillback backlog "
                                       "threshold: spill only while the "
                                       "agent's un-started lease queue "
                                       "exceeds this many tasks per local "
                                       "worker (the kept-local floor)"),
    "lease_spill_max_hops": (int, 2, "max agent->agent hops a lease may "
                             "take before it must execute where it is "
                             "(ping-pong guard; each spill consumes one)"),
    # --- lineage reconstruction (parity: object_recovery_manager.h:43,
    #     task_manager.h:216 lineage resubmission) ---
    "max_object_reconstructions": (int, 3, "times a task is re-executed to "
                                   "recover its lost plasma-tier outputs"),
    "lineage_cache_entries": (int, 50000, "max finished-task specs retained "
                              "for reconstruction; 0 disables lineage"),
    # --- memory / OOM (parity: memory_monitor.h + worker killing policy) ---
    "memory_monitor_refresh_ms": (int, 0, "OOM monitor interval; 0 = off"),
    "memory_usage_threshold": (float, 0.95, "kill a worker above this usage"),
    # --- control plane ---
    "health_check_period_ms": (int, 1000, "node health-check interval"),
    "fetch_retry_timeout_s": (float, 10.0, "re-drive a cross-node object "
                              "fetch with no reply after this long "
                              "(<=0 disables; 3 retries then lost)"),
    "async_actor_executor_shards": (int, 0, "event-loop shards per async "
                                    "actor (each a thread running its own "
                                    "asyncio loop; idle shards steal queued "
                                    "calls from busy ones). 0 = auto "
                                    "(min(4, cores/2), floor 1). >1 runs "
                                    "coroutines of ONE actor on several "
                                    "threads — method bodies that mutate "
                                    "instance state between awaits should "
                                    "pin shards to 1"),
    "async_actor_default_max_concurrency": (int, 1000, "max_concurrency "
                                            "for async actors that don't "
                                            "set one (parity: the "
                                            "reference's async-actor "
                                            "default)"),
    "direct_actor_calls": (bool, True, "worker->actor calls between agent "
                           "nodes ride direct agent<->agent channels, "
                           "bypassing the head relay"),
    "worker_direct_calls": (bool, True, "same-node worker->worker actor "
                            "calls ride a direct unix-socket peer plane "
                            "(2 hops instead of 4), bypassing the head on "
                            "head nodes and the agent relay on agent "
                            "nodes (call AND reply; the agent only sees "
                            "async task-event/bookkeeping traffic)"),
    "health_check_failure_threshold": (int, 5, "missed checks before a node is dead"),
    "gcs_port": (int, 0, "GCS TCP port; 0 = pick free port"),
    # --- head fault tolerance (parity: redis_store_client.h:111 +
    #     gcs_init_data.h reload; raylet reconnect/resync) ---
    "head_persistence_path": (str, "", "journal file for head tables "
                              "(kv/fns/actors/pgs/tasks); '' = volatile"),
    "agent_reconnect_grace_s": (float, 15.0, "node agent retries the head "
                                "connection this long before dying"),
    "head_restart_adopt_grace_s": (float, 10.0, "restored actors wait this "
                                   "long for their old worker to be "
                                   "re-registered before respawning"),
    "head_wal": (bool, True, "when head_persistence_path is set, extend "
                 "the journal from the durable tables to the full "
                 "control-plane WAL: in-flight lease grants, object-"
                 "directory locations, PG reservations and serve stream "
                 "cursors (the state a head.kill chaos SIGKILL must "
                 "replay). False keeps PR-8's tables-only journal"),
    # --- head shards (parity: the reference GCS's service split; object
    #     directory + task-event ingest shard by id space, lease policy
    #     stays on the head — core/head_shards.py) ---
    "head_shards": (int, 0, "spawn N head-shard subprocesses owning "
                    "disjoint id-space slices of the object directory "
                    "(durable per-shard WAL mirror) and task-event "
                    "ingest; the shard map rides the cluster-view "
                    "broadcast and agents ship task_events straight to "
                    "the owning shard. 0 = single-head (no shards)"),
    # --- fault injection (test leverage, parity: rpc_chaos.h) ---
    "testing_rpc_failure": (str, "", "'method=max_failures' comma list; drops messages"),
    "testing_delay_us": (str, "", "'method=min:max' comma list; injects delays"),
    # --- chaos plane (core/chaos.py: deterministic seeded fault
    #     injection at named hot-path seams) ---
    "chaos_schedule": (str, "", "comma list of 'site:spec' arming named "
                       "injection sites (chaos.REGISTERED_SITES): spec is "
                       "a 1-based hit count (fire exactly once on that "
                       "hit) or a probability in (0,1) applied per hit; "
                       "site may be an fnmatch glob ('transport.*:0.01'). "
                       "Same chaos_seed => identical per-site fire "
                       "sequence. '' disables (zero overhead)"),
    "chaos_seed": (int, 0, "seed for the chaos plane's per-site RNGs; a "
                   "fixed seed makes a chaos storm replayable"),
    # --- unified retry/backoff policy (core/retry.py Backoff: capped
    #     exponential + jitter against a deadline — the one cadence every
    #     core retry loop sleeps through) ---
    "retry_backoff_base_s": (float, 0.05, "first retry interval"),
    "retry_backoff_cap_s": (float, 2.0, "retry interval ceiling"),
    "retry_backoff_jitter": (float, 0.2, "fractional jitter (+/-) applied "
                             "to every interval — desynchronizes N "
                             "processes re-dialing one restarted peer"),
    "peer_dial_timeout_s": (float, 5.0, "connect timeout for ctrl-plane "
                            "dials (agent<->agent channels, spill hops)"),
    "lease_redrive_timeout_s": (float, 10.0, "head re-sends a granted "
                                "lease whose node reports ITSELF idle "
                                "(no backlog, nothing in flight) this "
                                "long after the grant — recovers a "
                                "node_exec frame lost on the wire; "
                                "agents dedup re-sent (task, lease_seq) "
                                "pairs so a re-drive can never "
                                "double-queue. <=0 disables"),
    "native_sched": (bool, True, "run the scheduling hot loop's select-"
                     "round core in C++ (cpp/agent_core.cc): the agent's "
                     "frame pump, lease queue/dedup/dispatch bookkeeping "
                     "and hot-frame builds go native, and the head grants "
                     "leases as raw spec bytes (node_exec_raw) consumed "
                     "without a Python unpickle. Pure-Python fallback "
                     "(off, or a failed native build) is behaviorally "
                     "identical; chaos-armed processes route every send "
                     "through the Python chaos sites either way"),
    "native_head": (bool, True, "run the HEAD's listener select round in "
                    "C++ too (cpp/head_core.cc), finishing the scheduling "
                    "plane's native split: the node-listener frame pump, "
                    "in-place node_done_raw parse + (task_id, lease_seq) "
                    "completion ledger, and native node_exec_raw grant "
                    "builds into per-node outboxes go native, while "
                    "Python keeps all policy (placement, spill, placement "
                    "groups, dep gating, retries) and every cold path "
                    "(lease_return/lease_spilled/reclaim/redrive/cpp "
                    "leases) keeps its object-form frames. Pure-Python "
                    "fallback (off, or a failed native build) is "
                    "behaviorally identical; chaos-armed processes skip "
                    "native consumption and route every send through the "
                    "Python chaos sites either way"),
    "put_extent_affinity": (bool, True, "store_reserve prefers free-list "
                            "ranges this pid owned before (per-pid extent "
                            "hints recorded when reservations retire): "
                            "refilled write extents land on pages already "
                            "in the writer's page table instead of cold "
                            "ones — the r06-measured 8.4->2.1 GB/s "
                            "multi-writer collapse"),
    "put_extent_pretouch": (bool, True, "pre-fault a freshly carved "
                            "reservation extent's pages at reserve time "
                            "(MADV_POPULATE_WRITE, manual touch "
                            "fallback) so the bump-fill memcpys never "
                            "minor-fault mid-copy"),
    "objxfer_stream_fail_limit": (int, 3, "after this many striped-pull "
                                  "range failures against one peer "
                                  "address, pulls from it degrade to "
                                  "single-stream until a striped pull "
                                  "completes clean"),
    "orphan_reclaim_interval_s": (float, 5.0, "store owners (head, node "
                                  "agents) sweep the arena's write-"
                                  "reservation records for dead-pid "
                                  "owners at this cadence, returning "
                                  "leaked extents and repairing "
                                  "rsv_unused (a client SIGKILLed "
                                  "between reserve and publish strands "
                                  "its extent otherwise). <=0 disables "
                                  "the periodic sweep (pressure-path "
                                  "sweeps still run)"),
    # --- elastic training plane (train/trainer.py + train/checkpoint.py:
    #     crash-consistent sharded checkpoints, gang re-mesh on worker
    #     death; parity: Train FailureConfig/worker-group restart) ---
    "train_poll_timeout_s": (float, 600.0, "controller-side deadline for "
                             "one worker-group poll() round trip; a "
                             "worker that is wedged-not-dead (poll never "
                             "returns) is declared hung after this long "
                             "and handled by the FailurePolicy instead "
                             "of stalling the run"),
    "train_progress_timeout_s": (float, 0.0, "hung-GANG watchdog: if NO "
                                 "rank reports progress (a report or a "
                                 "finish) for this long while polls still "
                                 "answer, the group is declared hung and "
                                 "restarted by the FailurePolicy. 0 "
                                 "disables (polls answering + steps "
                                 "legitimately slow is the common case)"),
    "train_restart_wait_s": (float, 5.0, "elastic restart capacity-settle "
                             "deadline: a gang restart waits up to this "
                             "long (sleeping through the retry_backoff_* "
                             "cadence) for the dead gang's resources to "
                             "release before sizing the new world"),
    "train_ckpt_arena": (bool, True, "checkpoint shards are additionally "
                         "sealed as tagged arena objects (put_tagged) so "
                         "a restarted gang can restore over striped "
                         "objxfer pulls from surviving peers; the "
                         "committed on-disk manifest stays the source of "
                         "truth (arena restore is best-effort)"),
    # --- multi-tenancy (core/jobs.py job ledger: quotas + weighted-DRF
    #     fair-share at the head's lease grant; parity: DRF NSDI '11 +
    #     Borg quota semantics over the reference's JobID attribution) ---
    "fair_share": (bool, True, "the head's grant loop picks the next "
                   "lease in weighted dominant-resource-fairness order "
                   "over the live cluster view, so a task-storm job "
                   "queues behind its share instead of monopolizing the "
                   "pump; off = FIFO over scheduling keys (the "
                   "multi_tenant bench's A/B collapse mode)"),
    "job_quota_cpu": (float, 0.0, "default per-job CPU ceiling enforced "
                      "at lease grant (a task that would push the job's "
                      "charged CPUs over this stays queued); 0 = "
                      "unlimited. Per-job overrides ride "
                      "submit_job(quota=...)"),
    "job_quota_tpu": (float, 0.0, "default per-job TPU-chip ceiling "
                      "enforced at lease grant; 0 = unlimited"),
    "job_quota_object_store_bytes": (int, 0, "default per-job object-"
                                     "store footprint ceiling; a job "
                                     "beyond it has ITS coldest objects "
                                     "spilled to disk (per-job blast "
                                     "radius) instead of cluster-wide "
                                     "eviction pressure; 0 = unlimited"),
    "job_default_weight": (float, 1.0, "DRF weight for jobs that don't "
                           "set one (share = dominant usage fraction / "
                           "weight; heavier jobs are granted more)"),
    "task_events_max_per_job": (int, 0, "head-side TaskEventStorage "
                                "per-job retention cap: settled attempts "
                                "of a job beyond it are evicted (drop-"
                                "accounted in dropped_per_job) even when "
                                "the global task_events_max_tasks bound "
                                "has room; 0 = no per-job cap"),
    # --- autoscaler policy core (autoscaler/policy.py: quota-aware
    #     demand -> slice-shaped node types) ---
    "autoscaler_quota_demand": (bool, True, "queued-beyond-quota leases "
                                "count as autoscaler demand (scale up "
                                "rather than starve an over-quota "
                                "tenant; its quota still caps what it "
                                "may hold, so the new capacity serves "
                                "the other tenants it was crowding)"),
    "autoscaler_shed_window_s": (float, 30.0, "trailing window over "
                                 "which serve shed events "
                                 "(ray_tpu_serve_shed_total) are rated "
                                 "for scale-up demand"),
    "autoscaler_shed_rate_threshold": (float, 1.0, "sheds/second over "
                                       "the window that convert into "
                                       "one serve-replica-shaped "
                                       "scale-up bundle"),
    # --- observability ---
    "event_stats": (bool, False, "record per-handler event-loop stats"),
    "export_events": (bool, False, "append task/actor/node state "
                      "transitions as JSONL under <session>/export_events"),
    "task_events": (bool, True, "task-event pipeline (parity: "
                    "task_event_buffer.h:225 + gcs_task_manager.h:94): "
                    "every process stamps timestamped task state "
                    "transitions (submit, lease grant, spill hops, "
                    "dispatch, exec sub-spans, output seal, channel/"
                    "objxfer transfers) into a per-process drop-oldest "
                    "ring, shipped to the head on frames the agents/"
                    "workers already send; powers ray_tpu.timeline(), "
                    "util.state.summary_tasks(), /api/timeline and the "
                    "per-stage latency histograms at /metrics. Off = "
                    "near-zero cost (one flag check per site)"),
    "task_events_buffer_size": (int, 10000, "per-process task-event ring "
                                "capacity (drop-oldest; drops counted "
                                "and exported at /metrics)"),
    "task_events_flush_ms": (int, 200, "emitters flush their ring at "
                             "most this often, piggybacked on frames "
                             "they already send (worker reply channel, "
                             "agent select-round batch/heartbeat)"),
    "task_events_max_tasks": (int, 10000, "head-side TaskEventStorage "
                              "bound: merged task attempts retained; "
                              "eviction prefers settled attempts of the "
                              "largest job (gcs_task_manager.h:94 "
                              "parity) and is drop-accounted"),
    "metrics_report_interval_ms": (int, 10000, "metrics flush interval"),
    # --- logging ---
    "log_dir": (str, "", "session log dir; '' = <session>/logs"),
    "log_to_driver": (bool, True, "stream worker log lines to the driver "
                      "stdout (parity: log_monitor.py + log_to_driver)"),
}


def _coerce(ty: type, raw: str):
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if ty is int:
        return int(raw)
    if ty is float:
        return float(raw)
    return raw


class Config:
    """Resolved config. Priority: explicit system_config > env > default."""

    def __init__(self, system_config: dict[str, Any] | None = None):
        self._values: dict[str, Any] = {}
        overrides = dict(system_config or {})
        for name, (ty, default, _help) in _CONFIG_DEFS.items():
            if name in overrides:
                self._values[name] = overrides.pop(name)
            else:
                raw = os.environ.get(ENV_PREFIX + name.upper())
                self._values[name] = _coerce(ty, raw) if raw is not None else default
        if overrides:
            raise ValueError(f"unknown config keys: {sorted(overrides)}")

    def __getattr__(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def to_dict(self) -> dict[str, Any]:
        return dict(self._values)

    def to_env(self) -> dict[str, str]:
        """Serialize for inheritance by child processes."""
        return {ENV_PREFIX + "SYSTEM_CONFIG": json.dumps(self._values)}

    @classmethod
    def from_env(cls) -> "Config":
        raw = os.environ.get(ENV_PREFIX + "SYSTEM_CONFIG")
        return cls(json.loads(raw)) if raw else cls()


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.from_env()
    return _global_config


def set_config(cfg: Config):
    global _global_config
    _global_config = cfg
    # Chaos-injection specs live in the config; invalidate the cached injector.
    try:
        from ray_tpu.core import transport
        transport._chaos = None
    except ImportError:
        pass
    # Arm (or disarm) the named-site chaos plane from the resolved config
    # — every process that adopts a config re-derives its site table, so
    # the schedule propagates to workers/agents through the environment.
    try:
        from ray_tpu.core import chaos
        chaos.configure_from(cfg)
    except ImportError:
        pass
