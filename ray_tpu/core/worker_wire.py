"""Protobuf wire for the agent <-> non-Python-worker exec plane.

Parity: the reference's core worker RPC surface as seen by its C++/Java
worker runtimes (`core_worker.proto:457` PushTask/returns +
`cpp/src/ray/runtime/task/task_executor.cc`). A `language="cpp"` worker
speaks length-prefixed protobuf frames on its agent socket — the SAME
outer framing as every other channel (`<Q len><I nbufs>` with the nbufs
MSB proto flag, transport.py) — but the payload is a `raytpu.WorkerFrame`
instead of an AgentFrame, and NO pickle ever rides the channel: dispatch
carries a `raytpu.TaskSpec` whose payload is a tagged `TaskArgs`, returns
come back as arena object ids (sealed tagged — object_store.TAGGED_META).

The checked-in protoc bindings predate these messages (this build env
ships no protoc — see raytpu.proto), so the message classes are built at
import time from hand-authored `FileDescriptorProto`s against the same
descriptor pool the generated module uses. The C++ side hand-rolls the
matching varint codec (cpp/pb/raytpu.pb.h); raytpu.proto documents the
schema for the next regen.
"""

from __future__ import annotations

import struct
import threading

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

import ray_tpu.protocol.raytpu_pb2 as pb  # noqa: F401 — loads raytpu.proto

_F = descriptor_pb2.FieldDescriptorProto


def _msg(f, name, fields):
    """Add one message: fields = [(name, number, type, type_name|None,
    repeated)]."""
    m = f.message_type.add()
    m.name = name
    for fname, num, ftype, tname, rep in fields:
        fd = m.field.add()
        fd.name = fname
        fd.number = num
        fd.type = ftype
        fd.label = (_F.LABEL_REPEATED if rep else _F.LABEL_OPTIONAL)
        if tname:
            fd.type_name = tname
    return m


def _build():
    pool = descriptor_pool.Default()
    f = descriptor_pb2.FileDescriptorProto()
    f.name = "ray_tpu/protocol/raytpu_worker.proto"
    f.package = "raytpu"
    f.syntax = "proto3"
    f.dependency.append("ray_tpu/protocol/raytpu.proto")
    _msg(f, "WorkerHello", [
        ("worker_id", 1, _F.TYPE_BYTES, None, False),
        ("pid", 2, _F.TYPE_INT64, None, False),
        ("language", 3, _F.TYPE_STRING, None, False),
        ("symbols", 4, _F.TYPE_STRING, None, True),
    ])
    _msg(f, "WorkerExec", [
        ("spec", 1, _F.TYPE_MESSAGE, ".raytpu.TaskSpec", False),
    ])
    _msg(f, "WorkerOut", [
        ("object_id", 1, _F.TYPE_BYTES, None, False),
        ("status", 2, _F.TYPE_STRING, None, False),  # "shm" | "err"
        ("error", 3, _F.TYPE_MESSAGE, ".raytpu.Value", False),
    ])
    _msg(f, "WorkerDone", [
        ("task_id", 1, _F.TYPE_BYTES, None, False),
        ("outs", 2, _F.TYPE_MESSAGE, ".raytpu.WorkerOut", True),
        # Piggybacked exec record (the Python worker's done-frame tuple):
        # (attempt, exec_start, args_ready, exec_done, seal).
        ("attempt", 3, _F.TYPE_INT64, None, False),
        ("exec_start", 4, _F.TYPE_DOUBLE, None, False),
        ("args_ready", 5, _F.TYPE_DOUBLE, None, False),
        ("exec_done", 6, _F.TYPE_DOUBLE, None, False),
        ("seal", 7, _F.TYPE_DOUBLE, None, False),
    ])
    _msg(f, "WorkerShutdown", [])
    wf = _msg(f, "WorkerFrame", [
        ("hello", 1, _F.TYPE_MESSAGE, ".raytpu.WorkerHello", False),
        ("exec", 2, _F.TYPE_MESSAGE, ".raytpu.WorkerExec", False),
        ("done", 3, _F.TYPE_MESSAGE, ".raytpu.WorkerDone", False),
        ("shutdown", 4, _F.TYPE_MESSAGE, ".raytpu.WorkerShutdown", False),
    ])
    oo = wf.oneof_decl.add()
    oo.name = "msg"
    for fd in wf.field:
        fd.oneof_index = 0
    try:
        pool.Add(f)
    except Exception:  # noqa: BLE001 — already added (module re-import)
        pass

    def cls(name):
        return message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"raytpu.{name}"))

    return {n: cls(n) for n in ("WorkerHello", "WorkerExec", "WorkerOut",
                                "WorkerDone", "WorkerShutdown",
                                "WorkerFrame")}


_CLASSES = _build()
WorkerFrame = _CLASSES["WorkerFrame"]

# Outer framing shared with transport.py: <Q payload_len><I nbufs> with
# the nbufs MSB marking a protobuf payload. EVERY frame on a cpp-worker
# channel carries the flag — the C++ worker rejects anything else (its
# half of the no-pickle plane assertion).
_HDR = struct.Struct("<Q")
_NBUF = struct.Struct("<I")
_PROTO_FLAG = 0x80000000


def frame_bytes(payload: bytes) -> bytes:
    return _HDR.pack(len(payload)) + _NBUF.pack(_PROTO_FLAG) + payload


def send_frame(sock, msg, lock: threading.Lock | None = None):
    data = frame_bytes(msg.SerializeToString())
    if lock:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def spec_to_pb(spec):
    """Python TaskSpec -> raytpu.TaskSpec for the cpp worker plane.

    Requires the language-neutral payload form (payload_format="proto"
    with a serialized TaskArgs): anything else would smuggle pickle onto
    the plane, so it fails loudly at the sender."""
    if getattr(spec, "payload_format", None) != "proto":
        raise ValueError(
            f"task {spec.describe()} is language={spec.language!r} but its "
            "payload is not a tagged TaskArgs (payload_format != 'proto'); "
            "the cpp worker plane asserts no-pickle")
    m = pb.TaskSpec()
    m.task_id = spec.task_id
    m.name = spec.name or ""
    m.payload.data = spec.payload
    m.payload.format = "task_args"
    for rid in spec.return_ids or []:
        m.return_ids.append(rid)
    m.num_cpus = float(spec.num_cpus or 0)
    m.max_retries = int(spec.max_retries or 0)
    m.retries_left = int(spec.retries_left or 0)
    return m


def encode_exec(spec) -> bytes:
    f = WorkerFrame()
    f.exec.spec.CopyFrom(spec_to_pb(spec))
    return frame_bytes(f.SerializeToString())


def encode_shutdown() -> bytes:
    f = WorkerFrame()
    f.shutdown.SetInParent()
    return frame_bytes(f.SerializeToString())


class WorkerFrameBuffer:
    """Incremental decoder for a cpp worker's channel: same outer framing
    as transport.FrameBuffer, but payloads parse as WorkerFrame (and a
    frame WITHOUT the proto flag is a protocol violation, not a pickle)."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes):
        self._buf.extend(data)

    def frames(self) -> list:
        out = []
        pre = _HDR.size + _NBUF.size
        while len(self._buf) >= pre:
            (n,) = _HDR.unpack_from(self._buf, 0)
            (nbufs,) = _NBUF.unpack_from(self._buf, _HDR.size)
            if not nbufs & _PROTO_FLAG:
                raise ValueError(
                    "cpp worker sent a non-protobuf frame (no-pickle plane "
                    "violation)")
            if len(self._buf) < pre + n:
                break
            payload = bytes(self._buf[pre:pre + n])
            del self._buf[:pre + n]
            f = WorkerFrame()
            f.ParseFromString(payload)
            out.append(f)
        return out
