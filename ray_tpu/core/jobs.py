"""Job ledger: multi-tenant attribution, quotas, and weighted-DRF shares.

Parity: the reference's job table (`gcs_job_manager.h` — every driver gets
a JobID and every task carries it) crossed with two scheduling papers the
ISSUE names as the policy source: Dominant Resource Fairness (Ghodsi et
al., NSDI '11 — pick the next grant from the job with the smallest
dominant share) and Borg (Verma et al., EuroSys '15 — quota as an
admission-time ceiling, not a reservation). TPU chips are the expected
dominant resource on this cluster, so shares are computed over the live
cluster totals including `TPU`.

The ledger is head-local state guarded by its own lock, deliberately kept
as small lock-scoped methods: tools/racecheck binds them directly in the
`job_ledger` protocol model to explore concurrent grant / settle /
stop-job interleavings. Two invariants the model checks live here:

  * a job's charged usage never exceeds its quota (charge() is the only
    admission point and checks under the lock);
  * no task is charged twice (`inflight` is keyed by task_id; a second
    charge for a live task_id is refused, which is what makes the head's
    grant paths safe to race against requeue/retry).

Attribution flows: JobSupervisor registers a job and stamps
`RAY_TPU_JOB_ID` into the entrypoint's environment; drivers fall back to
the DEFAULT_JOB; workers inherit the job of the task they are executing
(nested submissions stay attributed); `.options(_job_id=...)` pins it
explicitly (tests/bench drive multiple tenants from one process this way).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

DEFAULT_JOB = "driver"

# Resources a quota can bound. Object-store bytes are accounted separately
# (per-put, not per-task) under the same record.
_QUOTA_KEYS = ("CPU", "TPU")


class JobRecord:
    __slots__ = ("job_id", "weight", "quota", "object_quota", "usage",
                 "inflight", "objects", "object_bytes", "spilled_bytes",
                 "over_quota_waits", "stopped", "submitted", "finished")

    def __init__(self, job_id: str, weight: float, quota: dict,
                 object_quota: int):
        self.job_id = job_id
        self.weight = max(float(weight), 1e-9)
        self.quota = {k: float(v) for k, v in (quota or {}).items()}
        self.object_quota = int(object_quota)
        self.usage = {k: 0.0 for k in _QUOTA_KEYS}
        self.inflight: dict[bytes, dict] = {}  # task_id -> charged req
        self.objects: OrderedDict[bytes, int] = OrderedDict()  # oid -> nbytes
        self.object_bytes = 0
        self.spilled_bytes = 0
        self.over_quota_waits = 0
        self.stopped = False
        self.submitted = 0
        self.finished = 0

    def dominant_share(self, totals: dict) -> float:
        """Weighted dominant share over the live cluster view (DRF):
        max over resources of usage/total, divided by the job weight."""
        share = 0.0
        for k, used in self.usage.items():
            total = totals.get(k, 0.0)
            if total > 0 and used > 0:
                share = max(share, used / total)
        return share / self.weight


class JobLedger:
    """Head-side per-job accounting. Every method takes the ledger lock
    for its whole body — callers never hold it across this boundary (the
    head's Runtime.lock is always taken FIRST when both are needed)."""

    def __init__(self, default_quota: dict | None = None,
                 default_object_quota: int = 0,
                 default_weight: float = 1.0):
        self.lock = threading.Lock()
        self.jobs: dict[str, JobRecord] = {}
        # oid -> owning job: the free path only knows the oid, and a scan
        # over every job's object table per free would make _free_object
        # O(jobs) on the head's hot release loop.
        self._obj_job: dict[bytes, str] = {}
        self._default_quota = dict(default_quota or {})
        self._default_object_quota = int(default_object_quota)
        self._default_weight = float(default_weight)

    # ---- registration / lifecycle ----

    def register(self, job_id: str, weight: float | None = None,
                 quota: dict | None = None,
                 object_quota: int | None = None) -> None:
        """Register (or re-arm) a job. Idempotent; re-registering a
        stopped id revives it (a resubmitted job reuses its name)."""
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                rec = self._new_record(job_id)
                self.jobs[job_id] = rec
            if weight is not None:
                rec.weight = max(float(weight), 1e-9)
            if quota is not None:
                rec.quota = {k: float(v) for k, v in quota.items()}
            if object_quota is not None:
                rec.object_quota = int(object_quota)
            rec.stopped = False

    def _new_record(self, job_id: str) -> JobRecord:
        return JobRecord(job_id, self._default_weight,
                         dict(self._default_quota),
                         self._default_object_quota)

    def _ensure_locked(self, job_id: str) -> JobRecord:
        rec = self.jobs.get(job_id)
        if rec is None:
            rec = self._new_record(job_id)
            self.jobs[job_id] = rec
        return rec

    def stop(self, job_id: str) -> bool:
        """Mark stopped: future charges are refused. The head separately
        drains queued specs and releases the job's live leases/objects."""
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is None or rec.stopped:
                return False
            rec.stopped = True
            return True

    def is_stopped(self, job_id: str) -> bool:
        with self.lock:
            rec = self.jobs.get(job_id)
            return rec is not None and rec.stopped

    def multi_tenant(self) -> bool:
        """More than one live (non-stopped) tenant registered. The grant
        loop uses this to switch off single-tenant fast paths whose
        grants bypass the DRF order (worker pipelining)."""
        with self.lock:
            return sum(1 for j in self.jobs.values()
                       if not j.stopped) > 1

    # ---- task admission (the quota gate) ----

    def charge(self, job_id: str, task_id: bytes, req: dict) -> bool:
        """Admit one grant. False = refuse: job stopped, task already
        charged (double-grant guard), or the charge would push any
        quota'd resource over its ceiling. The refused key stays queued;
        the caller counts it as over-quota demand for the autoscaler."""
        with self.lock:
            rec = self._ensure_locked(job_id)
            if rec.stopped:
                return False
            if task_id in rec.inflight:
                return False
            for k, limit in rec.quota.items():
                if limit <= 0:
                    continue  # 0 = unlimited
                if rec.usage.get(k, 0.0) + req.get(k, 0.0) > limit + 1e-9:
                    rec.over_quota_waits += 1
                    return False
            charged = {k: float(v) for k, v in req.items()
                       if k in rec.usage and v}
            for k, v in charged.items():
                rec.usage[k] += v
            rec.inflight[task_id] = charged
            return True

    def would_admit(self, job_id: str, req: dict) -> bool:
        """Read-only admission probe: would charge() accept this request
        right now? No usage mutation, no over-quota counter bump — the
        autoscaler policy uses it to split queued demand into
        \"waiting on cluster capacity\" (scale-up signal) versus
        \"waiting on its own quota\" (adding nodes would not help)."""
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                return True
            if rec.stopped:
                return False
            for k, limit in rec.quota.items():
                if limit <= 0:
                    continue
                if rec.usage.get(k, 0.0) + req.get(k, 0.0) > limit + 1e-9:
                    return False
            return True

    def settle(self, job_id: str, task_id: bytes) -> None:
        """Release one grant's charge (completion, failure, requeue,
        node death). Idempotent — every lease/assignment pop funnel calls
        it and some tasks travel both paths across retries."""
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                return
            charged = rec.inflight.pop(task_id, None)
            if not charged:
                return
            for k, v in charged.items():
                rec.usage[k] = max(0.0, rec.usage.get(k, 0.0) - v)

    def note_submitted(self, job_id: str) -> None:
        with self.lock:
            self._ensure_locked(job_id).submitted += 1

    def note_finished(self, job_id: str) -> None:
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is not None:
                rec.finished += 1

    # ---- fair-share ordering ----

    def order(self, job_ids, totals: dict) -> list[str]:
        """Weighted-DRF order: smallest dominant share first (ties break
        on job id for determinism). Unknown ids sort as zero-share."""
        with self.lock:
            def share(jid):
                rec = self.jobs.get(jid)
                return rec.dominant_share(totals) if rec else 0.0
            return sorted(job_ids, key=lambda j: (share(j), j))

    def dominant_share(self, job_id: str, totals: dict) -> float:
        with self.lock:
            rec = self.jobs.get(job_id)
            return rec.dominant_share(totals) if rec else 0.0

    # ---- object plane (per-job blast radius) ----

    def charge_object(self, job_id: str, oid: bytes, nbytes: int) -> None:
        """Attribute a sealed object; insertion order is put order, so
        iteration yields the job's coldest objects first."""
        with self.lock:
            rec = self._ensure_locked(job_id)
            if oid not in rec.objects:
                rec.objects[oid] = int(nbytes)
                rec.object_bytes += int(nbytes)
                self._obj_job[oid] = job_id

    def release_object(self, oid: bytes, job_id: str | None = None) -> None:
        """Drop an object's attribution (free path). The owning job is
        resolved from the reverse map when the caller only has the oid."""
        with self.lock:
            jid = job_id if job_id is not None else self._obj_job.get(oid)
            if jid is None:
                return
            rec = self.jobs.get(jid)
            self._obj_job.pop(oid, None)
            if rec is None:
                return
            nbytes = rec.objects.pop(oid, None)
            if nbytes:
                rec.object_bytes = max(0, rec.object_bytes - nbytes)

    def note_spilled(self, job_id: str, nbytes: int) -> None:
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is not None:
                rec.spilled_bytes += int(nbytes)

    def object_overage(self, job_id: str) -> int:
        """Bytes this job holds beyond its object-store quota (0 when
        unlimited or within quota) — the spill trigger for the per-job
        blast-radius path."""
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is None or rec.object_quota <= 0:
                return 0
            return max(0, rec.object_bytes - rec.object_quota)

    def over_quota_objects(self) -> list[tuple[str, int]]:
        """Every (job_id, overage bytes) past its object quota, biggest
        offender first — the head's pressure spiller drains these before
        touching within-quota tenants' objects."""
        with self.lock:
            out = [(jid, rec.object_bytes - rec.object_quota)
                   for jid, rec in self.jobs.items()
                   if rec.object_quota > 0
                   and rec.object_bytes > rec.object_quota]
            out.sort(key=lambda t: -t[1])
            return out

    def coldest_objects(self, job_id: str, limit: int = 64) -> list[bytes]:
        with self.lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                return []
            return [oid for oid, _ in list(rec.objects.items())[:limit]]

    def owner_of_object(self, oid: bytes) -> str | None:
        with self.lock:
            return self._obj_job.get(oid)

    # ---- introspection ----

    def snapshot(self, totals: dict | None = None) -> list[dict]:
        """Per-job view for /api/jobs: dominant share, quota usage,
        blast-radius counters."""
        totals = totals or {}
        with self.lock:
            out = []
            for jid in sorted(self.jobs):
                rec = self.jobs[jid]
                out.append({
                    "job_id": jid,
                    "weight": rec.weight,
                    "stopped": rec.stopped,
                    "dominant_share": round(rec.dominant_share(totals), 4),
                    "usage": {k: v for k, v in rec.usage.items() if v},
                    "quota": {k: v for k, v in rec.quota.items() if v > 0},
                    "inflight_tasks": len(rec.inflight),
                    "submitted": rec.submitted,
                    "finished": rec.finished,
                    "over_quota_waits": rec.over_quota_waits,
                    "object_bytes": rec.object_bytes,
                    "object_quota": rec.object_quota,
                    "spilled_bytes": rec.spilled_bytes,
                })
            return out

    def usage_of(self, job_id: str) -> dict:
        with self.lock:
            rec = self.jobs.get(job_id)
            return dict(rec.usage) if rec else {}


def ledger_from_config(cfg) -> JobLedger:
    quota = {}
    if getattr(cfg, "job_quota_cpu", 0.0) > 0:
        quota["CPU"] = cfg.job_quota_cpu
    if getattr(cfg, "job_quota_tpu", 0.0) > 0:
        quota["TPU"] = cfg.job_quota_tpu
    return JobLedger(
        default_quota=quota,
        default_object_quota=getattr(cfg, "job_quota_object_store_bytes", 0),
        default_weight=getattr(cfg, "job_default_weight", 1.0))


def current_job_id(opts: dict | None = None, rt=None) -> str:
    """Resolve the submitting job for a new TaskSpec. Priority:
    explicit `.options(_job_id=...)` pin > the job of the task this
    worker is currently executing (nested submissions inherit) >
    `RAY_TPU_JOB_ID` (stamped by JobSupervisor into entrypoint
    subprocesses) > the default driver job."""
    if opts:
        jid = opts.get("_job_id")
        if jid:
            return str(jid)
    spec = getattr(rt, "current_task", None) if rt is not None else None
    jid = getattr(spec, "job_id", None)
    if jid:
        return jid
    return os.environ.get("RAY_TPU_JOB_ID") or DEFAULT_JOB


def hostile_tick(submit, put=None, burst: int = 32,
                 put_bytes: int = 1 << 20) -> bool:
    """One tick of the replayable hostile tenant: when the armed
    `job.hostile` chaos site fires, unleash a task-storm burst (`submit`
    called `burst` times) and one giant put (`put(put_bytes)`). The bench
    and tests pass job-attributed closures; the chaos schedule + seed
    decide WHEN the storm hits, which is what makes the multi_tenant
    bench's hostile tenant replay identically run to run."""
    from ray_tpu.core import chaos
    if not chaos.site("job.hostile"):
        return False
    for _ in range(burst):
        submit()
    if put is not None:
        put(put_bytes)
    return True
