"""Session directory layout + garbage collection.

Parity: reference `python/ray/_private/node.py:179` — sessions live under
a dedicated root (`/tmp/ray/session_<date>_<pid>`), never under a
directory named after the importable package. Round-4 verdict found
`/tmp/ray_tpu` (the old root) shadowing `import ray_tpu` for any script
whose sys.path includes /tmp, plus thousands of un-GC'd `node_*` dirs;
this module fixes both:

- root is `$TMPDIR/ray_tpu_sessions/` (distinct from the package name)
- every dir is `{kind}_{YYYY-MM-DD_HH-MM-SS}_{pid}_{rand}` so a later
  process can tell whether the owner is still alive
- `gc_stale_sessions()` runs on every `new_session_dir()` call (i.e. on
  every `ray_tpu.init()` / NodeAgent boot) and removes dirs whose owner
  pid is dead, plus anything older than `RAY_TPU_SESSION_TTL_H` hours
  (default 24) regardless — the reference GCs the same way on `ray start`.
- the legacy `/tmp/ray_tpu` litter (node_*/session_* dirs from old
  builds) is swept too, so upgraded installs heal themselves.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
import uuid

SESSIONS_ROOT = os.environ.get(
    "RAY_TPU_SESSIONS_ROOT",
    os.path.join(tempfile.gettempdir(), "ray_tpu_sessions"))

# Old root (pre round 5) whose name shadowed the package. We only GC it;
# nothing new is ever created there.
_LEGACY_ROOT = os.path.join(tempfile.gettempdir(), "ray_tpu")

_TTL_S = float(os.environ.get("RAY_TPU_SESSION_TTL_H", "24")) * 3600.0


def new_session_dir(kind: str = "session") -> str:
    """Create and return a fresh session directory (with logs/ inside).

    kind is "session" for head runtimes, "node" for node agents.
    """
    gc_stale_sessions()
    stamp = time.strftime("%Y-%m-%d_%H-%M-%S")
    d = os.path.join(
        SESSIONS_ROOT,
        f"{kind}_{stamp}_{os.getpid()}_{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(d, "logs"), exist_ok=True)
    return d


def _owner_pid(name: str) -> int | None:
    """Pull the owner pid out of `{kind}_{date}_{time}_{pid}_{rand}`."""
    parts = name.split("_")
    if len(parts) >= 4:
        try:
            return int(parts[-2])
        except ValueError:
            return None
    return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # alive, someone else's
    return True


def gc_stale_sessions(now: float | None = None) -> int:
    """Remove session dirs whose owner died, or older than the TTL.

    Returns the number of directories removed. Never raises — session GC
    must not be able to fail an init().
    """
    now = now if now is not None else time.time()
    removed = 0
    try:
        for root, legacy in ((SESSIONS_ROOT, False), (_LEGACY_ROOT, True)):
            if not os.path.isdir(root):
                continue
            for name in os.listdir(root):
                path = os.path.join(root, name)
                if not os.path.isdir(path):
                    # Legacy root also holds cluster address/pid files —
                    # leave plain files alone.
                    continue
                if not (name.startswith("node_")
                        or name.startswith("session_")):
                    continue  # address/pid files, pip_envs cache, etc.
                try:
                    age = now - os.stat(path).st_mtime
                except OSError:
                    continue
                pid = _owner_pid(name)
                if pid is not None:
                    # A live owner keeps its dir no matter how old — a
                    # >24h head must not lose its session out from under
                    # it. Dead owner: reap immediately.
                    stale = not _pid_alive(pid)
                else:
                    # No pid in the name (legacy layout): litter unless
                    # it might belong to a still-running old-build
                    # cluster — give those an hour, others the TTL.
                    stale = age > (3600 if legacy else _TTL_S)
                if stale:
                    shutil.rmtree(path, ignore_errors=True)
                    removed += 1
    except OSError:
        pass
    return removed
