"""Head state persistence: a pluggable store behind the control plane.

Parity: reference GCS storage tier — `gcs/store_client/store_client.h`
(pluggable), `redis_store_client.h:111` (durable backend), reload via
`gcs_server/gcs_init_data.h`. Here the durable backend is an append-only
pickle journal on the filesystem (one record per mutation, replayed on
restart); the in-memory backend is a no-op for heads that opt out.

Tables journaled by the head (see runtime.py):
  kv     — internal KV (includes job table entries)
  fn     — exported function/class blobs (needed to re-dispatch)
  actor  — actor creation specs keyed by actor id
  named  — actor name -> actor id
  pg     — placement group specs
  task   — queued/in-flight normal task specs (removed on completion)

Restart flow: a new head with the same persistence dir replays the journal,
restores KV/functions/PGs, re-queues pending tasks, and marks persisted
actors RESTARTING; node agents reconnect (agent-side grace loop) and
re-register with a worker inventory, which ADOPTS still-running actor
workers back into ALIVE without restarting them.
"""

from __future__ import annotations

import os
import pickle
import threading


class NullStore:
    """Persistence disabled (the default)."""

    def append(self, table: str, key: bytes, value) -> None:
        pass

    def delete(self, table: str, key: bytes) -> None:
        pass

    def load(self) -> dict:
        return {}

    def close(self) -> None:
        pass


class SqliteStore:
    """Transactional persistence tier (parity: the reference's
    RedisStoreClient role, `redis_store_client.h:111` — a durable store a
    RESTARTED-ELSEWHERE head can reload, minus the network server: SQLite
    on shared storage gives the same restart-anywhere capability with
    zero extra processes). Selected by a path ending in `.db`/`.sqlite`
    or a `sqlite://` prefix.

    Unlike the journal, writes are transactional upserts — no torn-tail
    handling, no compaction; `load()` is a table scan."""

    def __init__(self, path: str):
        import sqlite3
        if path.startswith("sqlite://"):
            path = path[len("sqlite://"):]
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            " tbl TEXT NOT NULL, key BLOB NOT NULL, value BLOB,"
            " PRIMARY KEY (tbl, key))")
        # WAL + synchronous=NORMAL: no fsync per commit — durability
        # target is head-process death, not power loss (the journal's
        # documented posture); FULL would put a disk flush on every task
        # submission.
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.commit()

    def append(self, table: str, key: bytes, value) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO kv (tbl, key, value) VALUES (?, ?, ?) "
                "ON CONFLICT(tbl, key) DO UPDATE SET value=excluded.value",
                (table, key, pickle.dumps(value,
                                          protocol=pickle.HIGHEST_PROTOCOL)))
            self._db.commit()

    def delete(self, table: str, key: bytes) -> None:
        with self._lock:
            self._db.execute("DELETE FROM kv WHERE tbl=? AND key=?",
                             (table, key))
            self._db.commit()

    def load(self) -> dict:
        tables: dict[str, dict] = {}
        with self._lock:
            for tbl, key, value in self._db.execute(
                    "SELECT tbl, key, value FROM kv"):
                try:
                    tables.setdefault(tbl, {})[key] = pickle.loads(value)
                except Exception:  # noqa: BLE001 — skip corrupt record
                    continue
        return tables

    def close(self) -> None:
        with self._lock:
            try:
                self._db.close()
            except Exception:  # noqa: BLE001
                pass


def make_store(path: str | None):
    """Persistence backend for `path`: None -> NullStore; sqlite for
    `.db`/`.sqlite`/`sqlite://` paths; the append-only journal otherwise
    (parity: the reference's pluggable StoreClient,
    `store_client/store_client.h`)."""
    if not path:
        return NullStore()
    if (path.startswith("sqlite://") or path.endswith(".db")
            or path.endswith(".sqlite")):
        raw = path[len("sqlite://"):] if path.startswith("sqlite://") \
            else path
        try:  # a pre-existing JOURNAL at a .db path keeps its format —
            with open(raw, "rb") as f:  # never corrupt prior state
                if not f.read(16).startswith(b"SQLite format 3"):
                    return FileStore(raw)
        except FileNotFoundError:
            pass
        return SqliteStore(path)
    return FileStore(path)


class FileStore:
    """Append-only journal of (table, key, value|None) pickle records.

    Writes are buffered by the OS (no fsync per record — the durability
    target is head-process death, not power loss, matching the reference's
    default Redis persistence posture). `load()` replays in order; a later
    record for the same (table, key) wins; value None is a tombstone.
    Replaying also compacts: the journal is rewritten with only live
    records so restart cost stays bounded across generations.
    """

    # In-place compaction triggers once this many bytes accumulate since
    # the last compaction — keeps a long-lived head's journal bounded by
    # its live state, not its mutation history.
    COMPACT_THRESHOLD = 64 << 20

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._since_compact = 0
        self._compacting = False
        self._f = open(path, "ab")  # noqa: SIM115 — lifetime = head lifetime

    def append(self, table: str, key: bytes, value) -> None:
        rec = pickle.dumps((table, key, value),
                           protocol=pickle.HIGHEST_PROTOCOL)
        compact = False
        with self._lock:
            self._f.write(len(rec).to_bytes(8, "little") + rec)
            self._f.flush()
            self._since_compact += len(rec) + 8
            if (self._since_compact >= self.COMPACT_THRESHOLD
                    and not self._compacting):
                self._since_compact = 0
                self._compacting = True
                compact = True
        if compact:
            # Off the caller's (control-plane) thread; appenders only stall
            # on the store lock for the rewrite itself.
            threading.Thread(target=self._compact_locked,
                             daemon=True).start()

    def _compact_locked(self):
        try:
            with self._lock:
                tables = self._replay_locked()
                self._rewrite_locked(tables)
        finally:
            self._compacting = False

    def delete(self, table: str, key: bytes) -> None:
        self.append(table, key, None)

    def load(self) -> dict:
        """Replay -> {table: {key: value}}, then compact the journal.
        (Boot-time path; concurrent appends are excluded by the lock.)"""
        with self._lock:
            tables = self._replay_locked()
            self._rewrite_locked(tables)
        return tables

    def _replay_locked(self) -> dict:
        tables: dict[str, dict] = {}
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return tables
        off = 0
        while off + 8 <= len(data):
            n = int.from_bytes(data[off:off + 8], "little")
            off += 8
            if off + n > len(data):
                break  # torn tail record (head died mid-write): drop it
            try:
                table, key, value = pickle.loads(data[off:off + n])
            except Exception:  # noqa: BLE001 — skip corrupt record
                off += n
                continue
            off += n
            t = tables.setdefault(table, {})
            if value is None:
                t.pop(key, None)
            else:
                t[key] = value
        return tables

    def _rewrite_locked(self, tables: dict) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for table, entries in tables.items():
                for key, value in entries.items():
                    rec = pickle.dumps(
                        (table, key, value),
                        protocol=pickle.HIGHEST_PROTOCOL)
                    f.write(len(rec).to_bytes(8, "little") + rec)
        os.replace(tmp, self.path)
        self._f.close()
        self._f = open(self.path, "ab")  # noqa: SIM115

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass
