"""Log monitor: stream worker stdout/stderr to the driver.

Parity: reference `python/ray/_private/log_monitor.py` — a per-node tailer
publishing worker log lines so the driver prints them (`log_to_driver`).
Here the head-side monitor tails `<session>/logs/worker-*.out` (head-node
workers; remote nodes keep their own log dirs) and prints new lines
prefixed with the worker id, reference-style `(worker-xxxx) ...`.
"""

from __future__ import annotations

import glob
import os
import sys
import threading
import time


class LogMonitor:
    def __init__(self, logs_dir: str, poll_interval_s: float = 0.25,
                 out=None):
        self.logs_dir = logs_dir
        self.poll = poll_interval_s
        self.out = out or sys.stdout
        self._offsets: dict[str, int] = {}
        self._partial: dict[str, bytes] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-log-monitor")

    def start(self):
        # Existing content predates this driver: start at EOF per file.
        for path in glob.glob(os.path.join(self.logs_dir, "worker-*.out")):
            try:
                self._offsets[path] = os.path.getsize(path)
            except OSError:
                pass
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._scan()
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass
            time.sleep(self.poll)
        self._scan()  # final drain

    def _scan(self):
        for path in glob.glob(os.path.join(self.logs_dir, "worker-*.out")):
            off = self._offsets.get(path, 0)
            try:
                size = os.path.getsize(path)
                if size <= off:
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read(size - off)
            except OSError:
                continue
            self._offsets[path] = off + len(data)
            tag = os.path.basename(path)[len("worker-"):-len(".out")]
            # Split at the BYTE level and decode whole lines only — a
            # multi-byte character straddling two reads must not be
            # decoded in halves.
            raw = self._partial.pop(path, b"") + data
            lines = raw.split(b"\n")
            if not raw.endswith(b"\n"):
                self._partial[path] = lines.pop()
            for line in lines:
                if line:
                    try:
                        self.out.write(
                            f"(worker-{tag}) "
                            f"{line.decode(errors='replace')}\n")
                    except (OSError, ValueError):
                        return
        try:
            self.out.flush()
        except (OSError, ValueError):
            pass
