"""Head shards: horizontal scale-out of the control plane's
embarrassingly-shardable state.

Parity: the reference GCS's service split (PAPER.md L4 — ~39k LoC, 10
gRPC services over a pluggable `store_client`) and the Ownership paper's
observation (NSDI'21) that object metadata and event ingest shard
cleanly by id space while lease POLICY does not. Here the head proper
keeps lease policy and stays the object-directory authority for the
fast path; N shard subprocesses own disjoint id-space slices of

  * the durable object-directory mirror (oid -> node locations, WAL'd
    per shard so a head restart re-seeds its directory from shard
    snapshots before any agent has reconnected), and
  * task-event ingest (agents ship their `task_events` rings straight
    to the owning shard — the head's per-event merge cost leaves the
    storm's critical path; the head drains lazily on query).

Id space is carved into `N_BUCKETS` fixed buckets (first id byte);
`buckets[i]` names the owning shard, so re-slicing after a shard death
is one list rewrite, epoch-stamped.  The shard map rides the existing
cluster-view broadcast as a reserved pseudo-entry (`SHARD_MAP_KEY`),
so distribution, delta encoding and the cursor-reset full catch-up are
inherited rather than re-built.

Failure story: every shard journals its directory slice through
`core/persistence.py` (same WAL tier as the head tables). A shard
SIGKILL is detected by the manager's health pass, its buckets re-slice
onto survivors (epoch+1, no double-ownership: shards reject stale
epochs), the process respawns with the same WAL path, replays, and
takes its buckets back (epoch+2). The head's in-memory directory stays
the resolution authority throughout, so lookups never block on a dead
shard.

Wire frames (pickle framing over core/transport, documented in
raytpu.proto and pinned in tools/staticcheck/wire_drift.py):

  ("shard_hello", shard_id)                      -> ("shard_ready", ...)
  ("shard_assign", epoch, buckets)               epoch-gated ownership
  ("dir_add", [(oid, nid), ...])                 WAL commit, then merge
  ("dir_drop", [oid, ...])                       tombstone entries
  ("tev_ingest", node_id, batch, dropped)        task-event slice ingest
  ("tev_drain", req_id) -> ("tev_batch", req_id, batches)
  ("shard_snapshot", req_id)
      -> ("shard_state", req_id, epoch, {oid: [nid]}, tev_pending)
"""

from __future__ import annotations

import collections
import os
import socket
import subprocess
import sys
import threading
import time
import traceback

from ray_tpu.core import chaos
from ray_tpu.core.transport import (
    dial,
    enable_nodelay,
    free_tcp_port,
    recv_msg,
    send_msg,
)

# Fixed bucket count: the re-slice unit. 64 buckets over <=8 shards keeps
# every re-slice near-balanced without consistent-hashing machinery.
N_BUCKETS = 64

# Reserved cluster-view key the shard map rides under. Agents treat it as
# the shard map, never as a node: every existing view consumer already
# filters on state == "ALIVE" / a ctrl address, which this entry lacks.
SHARD_MAP_KEY = b"\x00smap"


def bucket_of(id_bytes: bytes) -> int:
    """Owning bucket of a task/object id (first byte; ids are urandom)."""
    return (id_bytes[0] if id_bytes else 0) % N_BUCKETS


class ShardState:
    """The shard process's protocol core, separated from its sockets so
    the racecheck interleaving explorer can bind these exact methods.

    Invariants (machine-checked by the `shard_reslice` model):
      * a dir entry is COMMITTED once its WAL append returned — it must
        survive kill + `replay_wal` (append-before-merge ordering);
      * ownership is epoch-gated: `apply_assign` with a stale epoch is a
        no-op, so a re-slice racing a late assign can never leave one
        bucket owned under two epochs at once.
    """

    def __init__(self, shard_id: int, store):
        self.shard_id = shard_id
        self.lock = threading.Lock()
        self.epoch = -1
        self.buckets: frozenset[int] = frozenset()
        self.dir: dict[bytes, set] = {}  # oid -> {node_id}
        self.tev: collections.deque = collections.deque(maxlen=4096)
        self.tev_dropped = 0
        self._store = store  # persistence store (the shard's WAL)

    def apply_assign(self, epoch: int, buckets) -> bool:
        """Adopt a bucket assignment; stale epochs are rejected."""
        with self.lock:
            if epoch <= self.epoch:
                return False
            self.epoch = epoch
            self.buckets = frozenset(buckets)
            return True

    def dir_merge(self, pairs) -> int:
        """Merge (oid, node_id) locations. WAL append FIRST: once the
        append returns the entry is committed and must survive SIGKILL;
        merging first would ack state the journal can still lose."""
        n = 0
        for oid, nid in pairs:
            with self.lock:
                locs = self.dir.get(oid)
                new = set(locs) if locs else set()
                new.add(nid)
                self._store.append("dir", oid, sorted(new))
                self.dir[oid] = new
                n += 1
        return n

    def dir_drop(self, oids) -> None:
        for oid in oids:
            with self.lock:
                if self.dir.pop(oid, None) is not None:
                    self._store.delete("dir", oid)

    def dir_snapshot(self) -> dict:
        with self.lock:
            return {oid: sorted(locs) for oid, locs in self.dir.items()}

    def tev_ingest(self, node_id, batch, dropped: int) -> None:
        with self.lock:
            if len(self.tev) == self.tev.maxlen:
                self.tev_dropped += 1
            self.tev.append((node_id, batch, dropped))

    def tev_drain(self) -> list:
        with self.lock:
            out = list(self.tev)
            self.tev.clear()
            return out

    def replay_wal(self) -> int:
        """Reload the directory slice from the WAL (boot / respawn)."""
        tables = self._store.load()
        with self.lock:
            for oid, locs in tables.get("dir", {}).items():
                self.dir[oid] = set(locs)
            return len(self.dir)


class ShardServer:
    """Socket shell around ShardState: one accept loop, one serve thread
    per connection (head manager + every agent that ships tev frames)."""

    def __init__(self, shard_id: int, port: int, wal_path: str | None):
        from ray_tpu.core.persistence import make_store
        self.state = ShardState(shard_id, make_store(wal_path))
        self.state.replay_wal()
        self._shutdown = False
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", port))
        self.srv.listen(128)
        self.port = self.srv.getsockname()[1]

    def serve_forever(self):
        threads = []
        try:
            while not self._shutdown:
                try:
                    sock, _addr = self.srv.accept()
                except OSError:
                    break
                enable_nodelay(sock)
                t = threading.Thread(target=self._serve_conn, args=(sock,),
                                     daemon=True, name="rtpu-shard-conn")
                t.start()
                threads.append(t)
        finally:
            try:
                self.srv.close()
            except OSError:
                pass

    def _serve_conn(self, sock: socket.socket):
        st = self.state
        lock = threading.Lock()
        try:
            while not self._shutdown:
                try:
                    msg = recv_msg(sock)
                except (OSError, EOFError):
                    return
                if msg is None:
                    return
                op = msg[0]
                if op == "dir_add":
                    # Crash-consistency probe: the kill seam sits between
                    # arrival and WAL commit — an entry that died here was
                    # never acked committed, one that survived replays.
                    chaos.kill("shard.kill")
                    st.dir_merge(msg[1])
                elif op == "tev_ingest":
                    chaos.kill("shard.kill")
                    st.tev_ingest(msg[1], msg[2], msg[3])
                elif op == "dir_drop":
                    st.dir_drop(msg[1])
                elif op == "shard_assign":
                    st.apply_assign(msg[1], msg[2])
                elif op == "tev_drain":
                    send_msg(sock, ("tev_batch", msg[1], st.tev_drain()),
                             lock)
                elif op == "shard_snapshot":
                    send_msg(sock, ("shard_state", msg[1], st.epoch,
                                    st.dir_snapshot(), len(st.tev)), lock)
                elif op == "shard_hello":
                    send_msg(sock, ("shard_ready", st.shard_id,
                                    len(st.dir), len(st.tev)), lock)
                elif op == "shard_shutdown":
                    self._shutdown = True
                    try:
                        self.srv.close()
                    except OSError:
                        pass
                    return
        finally:
            try:
                sock.close()
            except OSError:
                pass


def _watch_parent_loop(ppid: int):
    while True:
        try:
            os.kill(ppid, 0)
        except OSError:
            os._exit(0)  # head died: no orphaned shard processes
        time.sleep(1.0)


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(prog="ray_tpu.core.head_shards")
    p.add_argument("--shard-id", type=int, required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--wal", default="")
    p.add_argument("--watch-parent", type=int, default=0)
    args = p.parse_args(argv)
    from ray_tpu.core.config import get_config
    chaos.configure_from(get_config())
    if args.watch_parent:
        threading.Thread(target=_watch_parent_loop,
                         args=(args.watch_parent,), daemon=True,
                         name="rtpu-shard-watch").start()
    srv = ShardServer(args.shard_id, args.port, args.wal or None)
    print(f"SHARD_READY {srv.port}", flush=True)
    srv.serve_forever()


class _ShardLink:
    """Manager-side channel to one shard process."""

    __slots__ = ("shard_id", "port", "proc", "sock", "send_lock",
                 "wal")

    def __init__(self, shard_id: int, port: int, proc, wal: str | None):
        self.shard_id = shard_id
        self.port = port
        self.proc = proc
        self.sock: socket.socket | None = None
        self.send_lock = threading.Lock()
        self.wal = wal

    def connect(self, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                self.sock = dial(("127.0.0.1", self.port), timeout=2.0)
                send_msg(self.sock, ("shard_hello", self.shard_id),
                         self.send_lock)
                msg = recv_msg(self.sock)
                if msg and msg[0] == "shard_ready":
                    return msg
            except OSError as e:
                last = e
                time.sleep(0.1)
        raise OSError(f"shard {self.shard_id} never came up: {last}")

    def send(self, msg):
        if self.sock is None:
            raise OSError("shard link closed")
        send_msg(self.sock, msg, self.send_lock)

    def request(self, msg):
        """Synchronous round trip. The link is single-reader (the
        manager), so holding the send lock across send+recv IS the
        protocol: it serializes whole round trips on the channel."""
        if self.sock is None:
            raise OSError("shard link closed")
        with self.send_lock:
            send_msg(self.sock, msg)
            return recv_msg(self.sock)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class ShardManager:
    """Head-side owner of the shard fleet: spawn, assignment epochs,
    health/re-slice, the async dir mirror, and lazy tev drains."""

    def __init__(self, n_shards: int, wal_base: str | None,
                 chaos_env: dict | None = None):
        self.n_shards = max(1, int(n_shards))
        self.wal_base = wal_base
        self.lock = threading.Lock()
        self.epoch = 0
        self.links: dict[int, _ShardLink] = {}
        # buckets[i] -> shard id owning bucket i (exactly one owner).
        self.buckets: list[int] = [i % self.n_shards
                                   for i in range(N_BUCKETS)]
        self._env = {**os.environ, **(chaos_env or {})}
        self._dirq: collections.deque = collections.deque()
        self._dirq_cv = threading.Condition()
        self._shutdown = False
        for sid in range(self.n_shards):
            self._spawn_locked(sid)
        self.epoch = 1
        self._assign_all_locked()
        threading.Thread(target=self._dir_flush_loop, daemon=True,
                         name="rtpu-shard-dirflush").start()

    # -------- spawn / assignment --------

    def _wal_path(self, sid: int) -> str | None:
        return f"{self.wal_base}.shard{sid}" if self.wal_base else None

    def _spawn_locked(self, sid: int):
        port = free_tcp_port()
        wal = self._wal_path(sid)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.head_shards",
             "--shard-id", str(sid), "--port", str(port),
             "--wal", wal or "",
             "--watch-parent", str(os.getpid())],
            env=self._env, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        link = _ShardLink(sid, port, proc, wal)
        link.connect()
        self.links[sid] = link

    def _assign_all_locked(self):
        owned: dict[int, list] = {sid: [] for sid in self.links}
        for b, sid in enumerate(self.buckets):
            owned.setdefault(sid, []).append(b)
        for sid, link in self.links.items():
            try:
                link.send(("shard_assign", self.epoch, owned.get(sid, [])))
            except OSError:
                pass  # health pass owns dead-shard handling

    def _reslice_locked(self, dead_sid: int) -> list:
        """Rehome the dead shard's buckets onto survivors, round-robin.
        Pure assignment math (no I/O, no state writes — the caller
        commits the returned list to self.buckets under self.lock) so
        the racecheck model can bind it: post-state must keep EXACTLY
        ONE owner per bucket."""
        survivors = sorted(sid for sid in self.links if sid != dead_sid)
        out = list(self.buckets)
        if not survivors:
            return out
        it = 0
        for b, sid in enumerate(out):
            if sid == dead_sid:
                out[b] = survivors[it % len(survivors)]
                it += 1
        return out

    # -------- the map the cluster view carries --------

    def shard_map(self) -> dict:
        with self.lock:
            return {
                "epoch": self.epoch,
                "shards": tuple((sid, "127.0.0.1", link.port)
                                for sid, link in sorted(self.links.items())),
                "buckets": tuple(self.buckets),
            }

    def owner_of(self, id_bytes: bytes) -> int:
        with self.lock:
            return self.buckets[bucket_of(id_bytes)]

    # -------- async dir mirror --------

    def dir_add(self, oid: bytes, nid: bytes):
        """Queue one location for the background mirror flush — callers
        sit on the head's completion hot path and must not block on a
        shard socket."""
        with self._dirq_cv:
            self._dirq.append(("add", oid, nid))
            self._dirq_cv.notify()

    def dir_discard(self, oid: bytes):
        with self._dirq_cv:
            self._dirq.append(("drop", oid, None))
            self._dirq_cv.notify()

    def _dir_flush_loop(self):
        while not self._shutdown:
            with self._dirq_cv:
                while not self._dirq and not self._shutdown:
                    self._dirq_cv.wait(timeout=1.0)
                batch = list(self._dirq)
                self._dirq.clear()
            if not batch:
                continue
            adds: dict[int, list] = {}
            drops: dict[int, list] = {}
            with self.lock:
                buckets = list(self.buckets)
                links = dict(self.links)
            for kind, oid, nid in batch:
                sid = buckets[bucket_of(oid)]
                if kind == "add":
                    adds.setdefault(sid, []).append((oid, nid))
                else:
                    drops.setdefault(sid, []).append(oid)
            for sid in set(adds) | set(drops):
                link = links.get(sid)
                if link is None:
                    continue
                try:
                    if sid in adds:
                        link.send(("dir_add", adds[sid]))
                    if sid in drops:
                        link.send(("dir_drop", drops[sid]))
                except OSError:
                    # Dead shard: requeue for after the heal pass — the
                    # mirror must not silently drop locations.
                    with self._dirq_cv:
                        self._dirq.extend(
                            ("add", o, n) for o, n in adds.get(sid, []))
                        self._dirq.extend(
                            ("drop", o, None) for o in drops.get(sid, []))
                    time.sleep(0.2)

    # -------- health / failover --------

    def check_and_heal(self) -> bool:
        """One health pass: respawn dead shards (WAL replay brings their
        committed slice back), re-slice around the dead window, then hand
        buckets back. Returns True when the shard map changed."""
        changed = False
        with self.lock:
            dead = [sid for sid, link in self.links.items()
                    if not link.alive()]
            for sid in dead:
                changed = True
                self.links[sid].close()
                self.epoch += 1
                self.buckets = self._reslice_locked(sid)
                self._assign_all_locked()
                try:
                    self._spawn_locked(sid)
                except OSError:
                    traceback.print_exc()
                    self.links.pop(sid, None)
                    continue
                # Respawned + replayed: hand its buckets back.
                self.epoch += 1
                self.buckets = [sid if orig == sid else cur
                                for orig, cur in zip(
                                    [i % self.n_shards
                                     for i in range(N_BUCKETS)],
                                    self.buckets)]
                self._assign_all_locked()
        return changed

    # -------- queries --------

    def snapshot_all(self) -> dict:
        """Merged {oid: [node_id]} across shards — the head-restart
        directory re-seed (each shard replays its WAL on boot)."""
        merged: dict[bytes, list] = {}
        with self.lock:
            links = dict(self.links)
        for _sid, link in links.items():
            try:
                msg = link.request(("shard_snapshot", 0))
            except (OSError, EOFError):
                continue  # dead shard: its slice returns after the heal
            if msg and msg[0] == "shard_state":
                merged.update(msg[3])
        return merged

    def drain_tev(self) -> list:
        """[(node_id, batch, dropped)] accumulated across shards since
        the last drain (the lazy pull behind sync_task_store)."""
        out: list = []
        with self.lock:
            links = dict(self.links)
        for _sid, link in links.items():
            try:
                msg = link.request(("tev_drain", 0))
            except (OSError, EOFError):
                continue
            if msg and msg[0] == "tev_batch":
                out.extend(msg[2])
        return out

    def shutdown(self):
        self._shutdown = True
        with self._dirq_cv:
            self._dirq_cv.notify_all()
        with self.lock:
            links = list(self.links.values())
            self.links.clear()
        for link in links:
            try:
                link.send(("shard_shutdown",))
            except OSError:
                pass
            link.close()
            if link.proc is not None:
                try:
                    link.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    link.proc.kill()


if __name__ == "__main__":
    main()
