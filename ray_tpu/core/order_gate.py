"""Per-(caller, actor) submission-order gate for actor-call executors.

Parity: the sequence-number enforcement of the reference's direct actor
transport (`src/ray/core_worker/transport/actor_task_submitter.h:78`,
ordered delivery with out-of-order buffering, and the post-resolution
ordering of `dependency_resolver.h` — a dep-gated call's slot is
skip-released so later calls don't stall behind it).

Used by TWO executors that each receive actor execs over racing
transports and must restore the caller's submission order:

- the node agent (direct agent<->agent channel racing the head relay),
- head-node pooled workers (the worker<->worker peer plane racing the
  head's exec dispatch).

A sequence gap that never fills — a call that failed before reaching
this executor — resyncs after GAP_TIMEOUT so one lost call can't wedge
the actor. A brand-new key (actor just placed/restarted here) adopts the
lowest arriving seq after the much shorter FRESH_TIMEOUT, since the
caller's counter survives actor migrations. Release order is protected
by a per-key single drainer: a concurrent arrival can never overtake a
released-but-not-yet-delivered earlier frame.
"""

from __future__ import annotations

import collections
import threading
import time
import traceback


class OrderGate:
    GAP_TIMEOUT = 5.0    # s to wait for a missing mid-stream seq
    # A brand-new key can't tell "actor migrated here mid-stream" (lowest
    # in-flight seq is the caller's live counter, adopt it) from "the
    # caller's first-ever calls raced and the head relay is behind" (seq
    # 0 is coming, wait for it). 2s covers any realistic head-relay lag.
    FRESH_TIMEOUT = 2.0
    KEY_TTL = 600.0      # s of inactivity before a key is swept

    def __init__(self):
        # key -> [next_seq, buf {seq: (deliver, on_drop, target,
        #         deadline)}, out deque, draining flag, last_used,
        #         delivered_any, skip-released slots]
        self._order: dict[tuple, list] = {}
        self._lock = threading.Lock()
        self.buffered = 0  # frames parked waiting for a gap (for pacing)

    def submit(self, spec, deliver, on_drop=None, target=None):
        """Deliver an actor exec in per-(caller, actor) submission order.

        `deliver()` performs the actual dispatch; `on_drop()` fails the
        call back to its origin if `target` dies while the frame is
        buffered (None = the sender replays it itself). Specs without a
        caller_seq/owner bypass the gate entirely (single-transport
        callers need no reordering).
        """
        seq = getattr(spec, "caller_seq", None)
        if seq is None or spec.owner is None or spec.actor_id is None:
            deliver()
            return
        key = (spec.owner, spec.actor_id)
        now = time.monotonic()
        with self._lock:
            st = self._key_locked(key, now)
            if seq > st[0]:
                timeout = (self.GAP_TIMEOUT if st[5]
                           else self.FRESH_TIMEOUT)
                if seq not in st[1]:  # dup = retry of a buffered frame;
                    self.buffered += 1  # keep one count
                st[1][seq] = (deliver, on_drop, target, now + timeout)
                self._advance_locked(st)  # skips may gate the way
            else:
                st[2].append(deliver)
                st[5] = True
                if seq == st[0]:
                    st[0] += 1
                    self._advance_locked(st)
                # seq < st[0]: a slot consumed earlier — a head-path
                # retry after a fallback, or a dep-gated call the head
                # skip-released (it orders at dep-resolution time) —
                # deliver in queue order.
        self._drain(st)

    def skip(self, owner: bytes, actor_id: bytes, seq: int):
        """Sender notice: slot `seq` parked on pending deps and will
        arrive later (delivered at dep-resolution time, reference
        semantics); release its successors now."""
        with self._lock:
            st = self._key_locked((owner, actor_id), time.monotonic())
            if seq < st[0]:
                return
            st[6].add(seq)
            if len(st[6]) > 4096:  # lost-call hygiene: skips are tiny
                st[6] = {s for s in st[6] if s >= st[0]}
            self._advance_locked(st)
        self._drain(st)

    def _key_locked(self, key, now):
        st = self._order.get(key)
        if st is None:
            st = self._order[key] = [0, {}, collections.deque(),
                                    False, now, False, set()]
        st[4] = now
        return st

    def _advance_locked(self, st):
        """Release every consecutive buffered or skip-released slot from
        st[0]; on progress, extend the remaining buffered deadlines — a
        slow-but-advancing relay is not a gap."""
        progressed = False
        while True:
            if st[0] in st[1]:
                d, _f, _t, _dl = st[1].pop(st[0])
                self.buffered -= 1
                st[2].append(d)
                st[0] += 1
                progressed = True
            elif st[0] in st[6]:
                st[6].discard(st[0])
                st[0] += 1
                progressed = True
            else:
                break
        if progressed:
            st[5] = True
            if st[1]:
                ddl = time.monotonic() + self.GAP_TIMEOUT
                for s, e in list(st[1].items()):
                    st[1][s] = (e[0], e[1], e[2], ddl)

    def _drain(self, st):
        """Single-drainer: deliver the key's released frames in order."""
        with self._lock:
            if st[3] or not st[2]:
                return
            st[3] = True
        while True:
            with self._lock:
                if not st[2]:
                    st[3] = False
                    return
                d = st[2].popleft()
            try:
                d()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def flush_expired(self):
        """A buffered seq waited past its deadline: the missing call died
        en route (e.g. failed at the head) or predates this key (actor
        migrated here mid-stream). Resync to the lowest buffered seq."""
        now = time.monotonic()
        drain = []
        with self._lock:
            for st in self._order.values():
                buf = st[1]
                if not buf or min(e[3] for e in buf.values()) > now:
                    continue
                st[0] = min(buf)
                st[6] = {s for s in st[6] if s > st[0]}
                self._advance_locked(st)
                drain.append(st)
        for st in drain:
            self._drain(st)

    def drop_for_target(self, target):
        """`target` died: flush its buffered execs to their drop handlers
        (direct calls fall back through the head; head-path calls are
        simply dropped — the head replays them on worker death). Keys
        survive the death: a restart continues the caller's counter
        seamlessly; elsewhere, a fresh key adopts the live counter after
        FRESH_TIMEOUT."""
        dropped = []
        with self._lock:
            for key, st in list(self._order.items()):
                for seq, entry in list(st[1].items()):
                    if entry[2] == target:
                        del st[1][seq]
                        self.buffered -= 1
                        dropped.append(entry[1])
        for on_drop in dropped:
            if on_drop is not None:
                try:
                    on_drop()
                except Exception:  # noqa: BLE001
                    traceback.print_exc()

    def sweep(self):
        """TTL sweep of idle keys (callers and actors come and go; the
        gate must not grow without bound)."""
        cutoff = time.monotonic() - self.KEY_TTL
        with self._lock:
            for key, st in list(self._order.items()):
                if st[4] < cutoff and not st[1] and not st[2]:
                    del self._order[key]
