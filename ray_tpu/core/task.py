"""Task specification shipped from submitter to executor.

Parity: reference `src/ray/common/task/task_spec.h` / `common.proto` TaskSpec.
Kept as a __slots__ class pickled whole — the single-node transport is pickle
frames, so a protobuf round trip would only add overhead.
"""

from __future__ import annotations


class TaskSpec:
    __slots__ = (
        "task_id",        # bytes
        "fn_id",          # bytes (sha of cloudpickled fn / class)
        "name",           # human-readable
        "payload",        # pickled (args, kwargs)
        "buffers",        # out-of-band buffers
        "inline_deps",    # {oid_bytes: (payload, buffers)} values only the owner had
        "return_ids",     # [bytes]
        "num_cpus",
        "num_tpus",
        "resources",      # {name: amount}
        "max_retries",
        "retries_left",
        "actor_id",       # bytes | None — actor task if set
        "method_name",    # str | None
        "seq_no",         # per-actor submission order
        "owner",          # worker_id bytes of submitter (None = driver)
        "scheduling_strategy",
        "dependencies",   # [oid_bytes] that must be ready before dispatch
        "runtime_env",    # {"env_vars": {...}, "working_dir": str,
                          #  "py_modules": [str]} | None
        "trace_ctx",      # W3C traceparent carrier dict | None (tracing)
        "streaming",      # True = generator task (num_returns="streaming")
        "caller_seq",     # per-(caller, actor) submission index; stamped by
                          # workers that may mix the direct agent<->agent
                          # path with the head relay, enforced at the
                          # executing node's agent (parity: the sequence
                          # numbers of actor_task_submitter.h:78)
        "idempotent",     # user-declared: safe to re-execute without a
                          # failure; opts into the one-phase steal fast path
        "payload_format",  # None/"pickle" | "proto" (language-neutral
                           # TaskArgs payload — proto_wire.decode_task_args)
        "args_ref",        # oid bytes | None — large pickle-5 arg buffers
                           # shipped through the shm arena as one ArgPack
                           # object instead of riding the socket frame
                           # (serialization.maybe_offload_args); always
                           # also listed in `dependencies` so the head
                           # gates dispatch on it and frees it after the
                           # final completion
        "spill_hops",      # int | None — agent->agent lease-spillback hops
                           # taken so far; capped by lease_spill_max_hops
                           # so a lease cannot ping-pong between loaded
                           # agents (parity: the spillback hop guard of
                           # cluster_task_manager.cc:187)
        "lease_seq",       # int | None — head-side lease grant generation,
                           # bumped on every (re)grant. Spill/return notices
                           # echo it so the head can ignore stale frames
                           # that name a PREVIOUS grant of the same task —
                           # acting on one would re-point or re-enqueue a
                           # live lease (duplicate execution / lost replay)
        "language",        # None/"python" | "cpp" — which worker runtime
                           # executes this task. cpp tasks address a native
                           # symbol by `name`, carry a language-neutral
                           # TaskArgs payload (payload_format="proto"), and
                           # are dispatched agent-side onto a C++ worker
                           # over the protobuf worker plane (no pickle on
                           # any frame the executing worker reads/writes)
        "exec_ts",         # worker-local scratch: [exec_start, args_ready,
                           # exec_done] wall stamps collected during
                           # execution, packed into ONE task event at
                           # output seal (core/task_events.py EXEC_SPANS —
                           # per-point emits churned enough allocations to
                           # move the task storm). Never meaningful on the
                           # wire: the executing worker is the last
                           # process to hold the spec.
        "job_id",          # str | None — owning tenant (core/jobs.py
                           # ledger key; None reads as the default driver
                           # job). Quota admission and weighted-DRF
                           # fair-share order key on it at the head's
                           # grant loop. Appended LAST on purpose:
                           # _from_tuple backfills missing trailing slots
                           # with None, so old journals and old peers
                           # stay readable (raytpu.proto field 22).
    )

    # __init__ is generated below with one STORE_ATTR per slot: the
    # setattr-per-slot loop was ~75% of TaskSpec construction cost, and a
    # spec is built on every submit (the head's hottest per-task work
    # after the lease plane went native).

    def __reduce__(self):
        return (TaskSpec._from_tuple, (tuple(getattr(self, s) for s in self.__slots__),))

    @staticmethod
    def _from_tuple(t):
        obj = TaskSpec.__new__(TaskSpec)
        for s, v in zip(TaskSpec.__slots__, t):
            object.__setattr__(obj, s, v)
        # Slots appended after `t` was pickled (old journal/peer): leave
        # them None rather than unset — __reduce__ reads every slot.
        for s in TaskSpec.__slots__[len(t):]:
            object.__setattr__(obj, s, None)
        return obj

    def describe(self) -> str:
        if self.actor_id is not None:
            return f"{self.name}.{self.method_name}"
        return self.name or "task"


def _gen_taskspec_init():
    args = ", ".join(f"{s}=None" for s in TaskSpec.__slots__)
    body = "\n".join(f"    self.{s} = {s}" for s in TaskSpec.__slots__)
    src = (f"def __init__(self, {args}):\n{body}\n"
           "    if resources is None:\n"
           "        self.resources = {}\n"
           "    if inline_deps is None:\n"
           "        self.inline_deps = {}\n")
    ns: dict = {}
    exec(src, ns)  # noqa: S102 — static template over __slots__
    return ns["__init__"]


TaskSpec.__init__ = _gen_taskspec_init()


class ActorCreationSpec:
    """Constructor spec kept by the control plane for restarts.

    Parity: `gcs_actor_manager.h:328` (GCS owns the actor lifecycle FSM and
    replays creation on restart).
    """

    __slots__ = ("actor_id", "cls_id", "cls_blob", "name", "payload", "buffers",
                 "max_restarts", "restarts_used", "max_concurrency", "is_async",
                 "num_cpus", "num_tpus", "resources", "max_task_retries",
                 "placement_group_id", "bundle_index", "runtime_env",
                 "dependencies", "methods_meta", "scheduling_strategy",
                 "job_id")

    def __init__(self, **kw):
        for s in self.__slots__:
            setattr(self, s, kw.get(s))
        if self.resources is None:
            self.resources = {}
        self.restarts_used = self.restarts_used or 0
