"""TPU chip detection and topology helpers.

Parity: reference `python/ray/_private/accelerators/tpu.py:109`
(TPUAcceleratorManager; /dev/accel* & /dev/vfio detection at :135,
TPU_VISIBLE_CHIPS, pod-slice `TPU-{type}-head` resource at :422). TPUs are
first-class schedulable resources here: the head counts chips at boot and the
mesh layer (ray_tpu.parallel) maps logical TPU resource slots to jax devices.
"""

from __future__ import annotations

import glob
import os

_GKE_TPU_ENV = "TPU_WORKER_ID"


def detect_tpus() -> int:
    """Number of TPU chips attached to this host (0 if none)."""
    env = os.environ.get("RAY_TPU_NUM_TPUS")
    if env:
        return int(env)
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def tpu_pod_name() -> str | None:
    """Pod-slice identity for gang scheduling (parity: tpu.py:422 and
    `ray.util.accelerators.tpu.get_current_pod_name`)."""
    name = os.environ.get("TPU_NAME") or os.environ.get("TPU_POD_NAME")
    return name or None


def tpu_accelerator_type() -> str | None:
    return os.environ.get("TPU_ACCELERATOR_TYPE") or None


def tpu_worker_count() -> int:
    return int(os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") + 1) \
        if os.environ.get("TPU_WORKER_HOSTNAMES") else 1
