"""Deterministic, seeded fault injection at named hot-path seams.

Parity: reference `src/ray/rpc/rpc_chaos.h` (RpcFailure injection keyed by
method name) — generalized from "drop this RPC" to a registry of NAMED
INJECTION SITES threaded through every hot seam of the runtime: transport
send/recv, objxfer range pulls, the shm store's write-reservation plane,
the agent's lease/spill control frames, the worker's direct-call plane,
and the head's lease grants. Each site encodes one concrete fault the
surrounding code must survive (a torn frame, a dead stream, a SIGKILL
between reserve and publish); the schedule only decides WHEN it fires.

Schedule grammar (`chaos_schedule` config knob, comma-separated):

    site:N      fire exactly once, on the N-th hit of that site (1-based)
    site:P      P in (0, 1): fire each hit with probability P
    glob:spec   `site` may be an fnmatch glob over REGISTERED_SITES
                ("transport.*:0.01" arms every transport seam at 1%)

Determinism: every site derives its own RNG from (`chaos_seed`, site
name), so a given seed replays the identical per-site fire sequence
regardless of how calls to DIFFERENT sites interleave across threads —
the property that makes a chaos storm a regression test instead of a
flake generator. The fire log (`fire_log()`) records (site, hit#) pairs
for reproducibility assertions.

Zero overhead when disabled: `site()` reads one module global and
returns. Armed processes pay a dict lookup + lock per hit.

The schedule rides the resolved config (env / `_system_config`), so every
process in the cluster — head, agents, workers — arms the same table;
role targeting falls out of the site namespace (`agent.*` sites only ever
execute inside agents, `worker.*` inside workers).
"""

from __future__ import annotations

import fnmatch
import os
import signal
import threading
import time
import zlib

# Every legal site name -> the fault the seam injects when it fires.
# tools/staticcheck's chaos_sites pass cross-checks this table against the
# `chaos.site("...")` literals in the source tree, both directions.
REGISTERED_SITES: dict[str, str] = {
    "transport.send.drop": "frame silently dropped on send",
    "transport.send.trunc": "half the frame sent, then connection reset "
                            "(torn frame at the receiver)",
    "transport.send.delay": "send delayed by a seeded jitter",
    "transport.recv.delay": "recv delayed by a seeded jitter",
    "transport.recv.reset": "recv reports connection reset (peer EOF)",
    "transport.dial.fail": "ctrl-plane dial raises OSError",
    "objxfer.pull.reset": "pull connection dies before the request",
    "objxfer.range.reset": "one range stream of a striped pull dies "
                           "mid-transfer",
    "objxfer.fetch.delay": "cross-node fetch delayed by a seeded jitter",
    "store.reserve.exhaust": "write-reservation carve reports arena "
                             "exhaustion (falls back to evicting create)",
    "store.reserve.abandon": "reservation tail leaked instead of released "
                             "(the crash window the liveness sweep repairs)",
    "store.publish.kill": "self-SIGKILL between reserve and publish",
    "head.lease_grant.lose": "a node_exec lease batch dropped on send",
    "head.kill": "the head self-SIGKILLs right after WAL-committing a "
                 "lease batch and before sending it (restart must replay "
                 "every committed task and re-admit every journaled "
                 "stream from the journal alone)",
    "shard.kill": "a head shard self-SIGKILLs on a dir/tev ingest frame, "
                  "before the WAL append (the manager's heal pass must "
                  "re-slice, respawn and WAL-replay it; committed "
                  "entries survive, the un-acked frame is re-driven by "
                  "the mirror flusher)",
    "agent.spill_notice.lose": "the lease_spilled notice to the head "
                               "dropped",
    "agent.peer_dial.fail": "agent->agent ctrl dial reports unreachable",
    "agent.sigkill": "the node agent SIGKILLs itself (heartbeat tick)",
    "worker.exec.kill": "worker self-SIGKILLs right before executing a "
                        "task",
    "worker.direct_call.reset": "the direct worker<->worker UDS channel "
                                "resets under an outgoing call",
    "train.worker_kill": "a train worker self-SIGKILLs mid-step (on a "
                         "session.report — no ack, no shard durability)",
    "train.ckpt_shard_abandon": "a rank writes its checkpoint shard but "
                                "dies before acking durability, so the "
                                "step's manifest can never commit",
    "train.manifest_loss": "the controller's manifest commit for a "
                           "fully-acked step is dropped (resume must come "
                           "from the previous committed step)",
    "train.poll_hang": "a train worker's poll() wedges without dying "
                       "(the hung-not-dead worker the watchdog converts "
                       "into a FailurePolicy restart)",
    "serve.router.drop": "the serving coordinator's routed decode "
                         "dispatch is dropped before it reaches the "
                         "pool (redriven through the shared backoff)",
    "serve.kv_handoff.lose": "the sealed prefill->decode KV handoff "
                             "object is lost in flight — the decode "
                             "replica must fall back to re-prefilling",
    "serve.decode.kill": "a decode replica self-SIGKILLs mid-stream "
                         "(one hit per emitted stream chunk; in-flight "
                         "streams must re-resolve exactly-once on a "
                         "surviving replica)",
    "serve.prefill.stall": "the prefill worker stalls by a seeded "
                           "jitter before returning its KV handoff",
    "job.hostile": "the hostile tenant strikes: a seeded task-storm "
                   "burst plus a giant put attributed to one job "
                   "(core/jobs.py hostile_tick — the multi_tenant "
                   "bench's replayable noisy neighbor)",
}


class _SiteState:
    __slots__ = ("mode", "arg", "rng", "hits", "fires")

    def __init__(self, mode: str, arg, rng):
        self.mode = mode  # "nth" | "prob"
        self.arg = arg
        self.rng = rng
        self.hits = 0
        self.fires = 0


_armed: dict[str, _SiteState] | None = None
_fire_log: list = []
_FIRE_LOG_CAP = 8192
_lock = threading.Lock()

# racecheck seam: the interleaving explorer (tools/racecheck) registers a
# schedule hook so every chaos site doubles as a yield point — the same
# zero-overhead contract as a disarmed schedule (one global read).
_sched_hook = None


def set_schedule_hook(hook):
    """Install (or clear, with None) the explorer's schedule hook;
    returns the previous hook so nested explorers can restore it."""
    global _sched_hook
    old = _sched_hook
    _sched_hook = hook
    return old


def _site_rng(name: str, seed: int):
    import random
    # Stable per-site stream: crc32 (not hash(): salted per process) mixed
    # with the shared seed, so every process derives identical streams.
    return random.Random(((zlib.crc32(name.encode()) + 1) << 32)
                         ^ (seed * 0x9E3779B97F4A7C15 + 0x1234567))


def _parse_spec(spec: str):
    try:
        if "." in spec or "e" in spec.lower():
            p = float(spec)
            if not 0.0 < p < 1.0:
                raise ValueError
            return "prob", p
        n = int(spec)
        if n < 1:
            raise ValueError
        return "nth", n
    except ValueError:
        raise ValueError(
            f"chaos_schedule spec {spec!r}: expected a 1-based hit count "
            "(integer) or a probability in (0, 1)") from None


def configure(schedule: str, seed: int = 0) -> None:
    """(Re)arm from a schedule string; empty schedule disarms. Raises
    ValueError on an unknown site or malformed spec — a typo'd schedule
    must fail the boot, not silently inject nothing."""
    global _armed, _fire_log
    if not schedule:
        _armed = None
        _fire_log = []
        return
    armed: dict[str, _SiteState] = {}
    for part in schedule.split(","):
        part = part.strip()
        if not part:
            continue
        pat, sep, spec = part.rpartition(":")
        if not sep or not pat:
            raise ValueError(f"chaos_schedule entry {part!r}: want "
                             "'site:spec'")
        names = (fnmatch.filter(REGISTERED_SITES, pat)
                 if any(c in pat for c in "*?[") else
                 ([pat] if pat in REGISTERED_SITES else []))
        if not names:
            raise ValueError(
                f"chaos_schedule: no registered site matches {pat!r} "
                f"(have: {', '.join(sorted(REGISTERED_SITES))})")
        mode, arg = _parse_spec(spec)
        for name in names:
            armed[name] = _SiteState(mode, arg, _site_rng(name, seed))
    _fire_log = []
    _armed = armed


def configure_from(cfg) -> None:
    configure(getattr(cfg, "chaos_schedule", ""),
              getattr(cfg, "chaos_seed", 0))


def armed() -> bool:
    return _armed is not None


def site(name: str) -> bool:
    """One hit of the named seam; returns True when the fault should
    fire. The caller implements the fault — the site's semantics live at
    the seam, the schedule only picks the hits."""
    h = _sched_hook
    if h is not None:
        h(name)
    a = _armed
    if a is None:
        return False
    st = a.get(name)
    if st is None:
        if name not in REGISTERED_SITES:
            raise ValueError(f"chaos site {name!r} is not registered "
                             "(add it to chaos.REGISTERED_SITES)")
        return False
    with _lock:
        st.hits += 1
        if st.mode == "nth":
            fire = st.hits == st.arg
        else:
            fire = st.rng.random() < st.arg
        if fire:
            st.fires += 1
            if len(_fire_log) < _FIRE_LOG_CAP:
                _fire_log.append((name, st.hits))
    return fire


def delay(name: str, max_s: float = 0.05) -> None:
    """Sleep a seeded fraction of `max_s` when the site fires (the
    duration draw rides the same per-site RNG, so it replays too)."""
    a = _armed
    if a is None:
        if _sched_hook is not None:
            site(name)  # schedule point only: disarmed sites never fire
        return
    if site(name):
        st = a[name]
        with _lock:
            frac = st.rng.random()
        time.sleep(max_s * frac)


def kill(name: str) -> None:
    """SIGKILL this process when the site fires — the crash-consistency
    probe: no atexit, no flush, no release runs."""
    if _armed is None and _sched_hook is None:
        return
    if site(name):
        os.kill(os.getpid(), signal.SIGKILL)


def snapshot() -> dict:
    """site -> (hits, fires) for every armed site (diagnostics/tests)."""
    a = _armed
    if a is None:
        return {}
    with _lock:
        return {name: (st.hits, st.fires) for name, st in a.items()}


def fire_log() -> list:
    """[(site, hit#)] in fire order — the reproducibility witness: same
    seed + same per-site call sequence => identical log."""
    with _lock:
        return list(_fire_log)
