"""Protobuf client plane: the port non-Python frontends connect to.

Parity: the reference's Ray Client server (`python/ray/util/client/server/`
speaking `src/ray/protobuf/ray_client.proto`) and the role of the C++/Java
frontends' connection to the cluster. Framing: 4-byte little-endian length
+ `raytpu.ClientRequest`; replies mirror with `raytpu.ClientReply`. One
thread per connection; requests on a connection run sequentially (a client
wanting parallelism opens more connections).

Cross-language tasks: `SubmitRequest` addresses a PYTHON function by
importable name ("pkg.module.fn", parity: the reference's cross-language
function descriptors); args arrive as tagged Values, decoded head-side, and
the task runs through the normal scheduler as `_xlang_call(fn_name, *args)`
on any Python worker. Results flow back as tagged Values (scalars/str/bytes
stay language-neutral; anything else is pickled and opaque to non-Python
readers).
"""

from __future__ import annotations

import importlib
import os
import socket
import struct
import threading

from ray_tpu.core import jobs, proto_wire, serialization
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.protocol import raytpu_pb2 as pb

_LEN = struct.Struct("<I")


def _xlang_call(fn_name: str, *args):
    """Executed on a worker: resolve `pkg.module.fn` and call it."""
    module, _, attr = fn_name.rpartition(".")
    if not module:
        raise ValueError(
            f"cross-language function name {fn_name!r} must be "
            f"'module.function'")
    fn = getattr(importlib.import_module(module), attr)
    return fn(*args)


class ClientProtoServer:
    """Accepts protobuf frontends on its own port (like the reference's
    dedicated Ray Client port)."""

    def __init__(self, runtime, host: str):
        self.rt = runtime
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind((host, 0))
        self.srv.listen(16)
        self.addr = (host, self.srv.getsockname()[1])
        self._stop = False
        self._xlang_fn_id = None
        # actor_id -> ActorHandle created through this plane (keeps the
        # handle alive; cross-language clients address actors by id)
        self._actors: dict[bytes, object] = {}
        self._pgs: dict[bytes, object] = {}  # pg_id -> PlacementGroup
        self._actors_lock = threading.Lock()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="rtpu-proto-clients").start()

    def close(self):
        self._stop = True
        try:
            self.srv.close()
        except OSError:
            pass

    # ---------------- plumbing ----------------

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        # Per-connection result-ref retention (the reference's Ray Client
        # server keeps the same map): an actor call's result ObjectRef is
        # refcounted, and dropping it head-side frees the object BEFORE
        # the remote client gets to wait()/get() it — results vanished
        # intermittently under exactly that race. Refs die with the
        # connection.
        refs: dict[bytes, object] = {}
        try:
            while True:
                hdr = self._recv(conn, _LEN.size)
                if hdr is None:
                    return
                (n,) = _LEN.unpack(hdr)
                body = self._recv(conn, n)
                if body is None:
                    return
                req = pb.ClientRequest()
                req.ParseFromString(body)
                reply = pb.ClientReply(req_id=req.req_id)
                try:
                    self._handle(req, reply, refs)
                except Exception as e:  # noqa: BLE001 — ship to client
                    reply.error = f"{type(e).__name__}: {e}"
                out = reply.SerializeToString()
                conn.sendall(_LEN.pack(len(out)) + out)
        finally:
            refs.clear()
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv(conn, n):
        chunks = []
        while n:
            try:
                c = conn.recv(n)
            except OSError:
                return None
            if not c:
                return None
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    # ---------------- handlers ----------------

    def _handle(self, req: pb.ClientRequest, reply: pb.ClientReply,
                refs: dict):
        which = req.WhichOneof("req")
        rt = self.rt
        if which == "init":
            reply.init.session_id = os.urandom(8)
            reply.init.version = "ray_tpu-0.3"
            for k, v in rt.cluster_resources().items():
                reply.init.cluster_resources[k] = float(v)
        elif which == "put":
            v = req.put.value
            if v.format == "pickle":
                raise ValueError(
                    "received a pickle-format Value on a plane that "
                    "asserts no-pickle")
            # Sealed VERBATIM in the tagged arena layout (TAGGED_META):
            # the client's bytes never detour through a Python object or
            # a pickle, and a cpp worker can read the object zero-copy.
            oid = ObjectID.from_random()
            rt.put_tagged_store(oid, v.format, v.data)
            rt.directory.put(oid.binary(), ("shm", {rt.head_node_id}))
            reply.put.object_id = oid.binary()
        elif which == "get":
            timeout = req.get.timeout_s or None
            ref = ObjectRef(ObjectID(req.get.object_id), _add_ref=False)
            value = rt._get_one(ref, timeout=timeout)
            reply.get.value.CopyFrom(
                proto_wire.encode_value(value, allow_pickle=False))
            reply.get.found = True
        elif which == "submit":
            self._submit(req.submit, reply)
        elif which == "wait":
            oids = list(req.wait.object_ids)
            nret = req.wait.num_returns or 1
            if nret > len(oids):
                # mirror the Python API's ValueError instead of blocking
                # this connection's serial request loop forever
                raise ValueError(
                    f"num_returns {nret} > len(object_ids) {len(oids)}")
            # timeout semantics: < 0 waits forever, 0 is a non-blocking
            # probe, > 0 is a deadline (proto3 default 0 must not mean
            # "block forever" — a poll would wedge the connection).
            timeout = req.wait.timeout_s
            timeout = None if timeout < 0 else timeout
            ready = rt._wait_oids(oids, nret, timeout)[:nret]
            rset = set(ready)
            reply.wait.ready.extend(ready)
            reply.wait.not_ready.extend(o for o in oids if o not in rset)
        elif which == "create_actor":
            self._create_actor(req.create_actor, reply)
        elif which == "actor_call":
            self._actor_call(req.actor_call, reply, refs)
        elif which == "kill_actor":
            self._kill_actor(req.kill_actor, reply)
        elif which == "create_placement_group":
            self._create_pg(req.create_placement_group, reply)
        elif which == "remove_placement_group":
            self._remove_pg(req.remove_placement_group, reply)
        elif which == "kv_put":
            with rt.lock:
                rt.kv[req.kv_put.key] = req.kv_put.value
            reply.kv_put.ok = True
        elif which == "kv_get":
            with rt.lock:
                v = rt.kv.get(req.kv_get.key)
            reply.kv_get.found = v is not None
            reply.kv_get.value = v or b""
        else:
            raise ValueError(f"unknown client request {which!r}")

    def _submit(self, sub: pb.SubmitRequest, reply: pb.ClientReply):
        from ray_tpu.core.task import TaskSpec
        rt = self.rt
        # Validate arg Values eagerly (no-pickle plane assertion) without
        # materializing Python copies — the payload below carries the
        # client's tagged Args VERBATIM (language-neutral exec payload;
        # VERDICT r4 #7 exec-plane neutrality where representable).
        deps = []
        fn_arg = pb.Arg()
        fn_arg.value.CopyFrom(pb.Value(data=sub.fn_name.encode(),
                                       format="utf8"))
        for a in sub.args:
            if a.WhichOneof("arg") == "object_id":
                deps.append(a.object_id)
            elif a.value.format == "pickle":
                raise ValueError(
                    "received a pickle-format Value on a plane that "
                    "asserts no-pickle")
        if self._xlang_fn_id is None:
            fn_id, blob = serialization.serialize_function(_xlang_call)
            rt.export_function(fn_id, blob)
            self._xlang_fn_id = fn_id
        payload = proto_wire.encode_task_args([fn_arg, *sub.args])
        num_returns = sub.num_returns or 1
        rnd = os.urandom(16 + 16 * num_returns)
        spec = TaskSpec(
            task_id=rnd[:16],
            fn_id=self._xlang_fn_id,
            name=f"xlang:{sub.fn_name}",
            payload=payload,
            payload_format="proto",
            buffers=[],
            return_ids=[rnd[16 + 16 * i: 32 + 16 * i]
                        for i in range(num_returns)],
            num_cpus=sub.num_cpus or 1,
            num_tpus=0,
            resources=dict(sub.resources),
            max_retries=0,
            retries_left=0,
            dependencies=deps,
            # Cross-language clients have no job env; attribute to the
            # head process's resolved job (usually the default driver).
            job_id=jobs.current_job_id(rt=rt),
        )
        rt.submit_task(spec)
        reply.submit.return_ids.extend(spec.return_ids)

    # ---------------- cross-language actors ----------------
    # Parity: the reference's cross-language actor creation/calls
    # (core_worker.proto:457 CreateActor/PushTask with function
    # descriptors; cpp/include/ray/api.h:130). The class is an importable
    # Python name; the lifecycle (placement, restarts, ordering) is the
    # normal actor machinery.

    def _decode_args(self, proto_args):
        args = []
        for a in proto_args:
            if a.WhichOneof("arg") == "object_id":
                args.append(ObjectRef(ObjectID(a.object_id),
                                      _add_ref=False))
            else:
                args.append(proto_wire.decode_value(a.value,
                                                    allow_pickle=False))
        return args

    def _sweep_dead_actors(self):
        """Evict handles whose actors died on their own (process exit,
        restarts exhausted, killed Python-side) — without this a
        long-lived head leaks one handle per short-lived actor."""
        with self._actors_lock:
            for aid in list(self._actors):
                st = self.rt.actors.get(aid)
                if st is None or getattr(st, "state", "") == "dead":
                    del self._actors[aid]

    def _create_pg(self, m: pb.CreatePlacementGroupRequest, reply):
        """Placement groups driven from a non-Python frontend (parity:
        the PG RPCs of gcs_service.proto; VERDICT r4 #7)."""
        from ray_tpu.util.placement_group import placement_group
        bundles = [dict(b.resources) for b in m.bundles]
        pg = placement_group(bundles, strategy=m.strategy or "PACK",
                             name=m.name)
        with self._actors_lock:
            self._pgs[pg.id.binary()] = pg
        ready = True
        if m.ready_timeout_s > 0:
            ready = pg.wait(timeout_seconds=m.ready_timeout_s)
        reply.create_placement_group.placement_group_id = pg.id.binary()
        reply.create_placement_group.ready = ready

    def _remove_pg(self, m: pb.RemovePlacementGroupRequest, reply):
        from ray_tpu.util.placement_group import remove_placement_group
        with self._actors_lock:
            pg = self._pgs.pop(m.placement_group_id, None)
        if pg is not None:
            remove_placement_group(pg)
        reply.remove_placement_group.ok = pg is not None

    def _create_actor(self, m: pb.CreateActorRequest, reply):
        from ray_tpu.core.actor import ActorClass
        self._sweep_dead_actors()
        module, _, attr = m.class_name.rpartition(".")
        if not module:
            raise ValueError(
                f"cross-language actor class {m.class_name!r} must be "
                f"'module.Class'")
        cls = getattr(importlib.import_module(module), attr)
        opts = {"num_cpus": m.num_cpus or 1,
                "max_restarts": m.max_restarts,
                "resources": dict(m.resources) or None}
        if m.name:
            opts["name"] = m.name
        if m.placement_group_id:
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy)
            with self._actors_lock:
                pg = self._pgs.get(m.placement_group_id)
            if pg is None:
                raise KeyError(
                    f"unknown placement group "
                    f"{m.placement_group_id.hex()} (created through this "
                    f"client plane?)")
            idx = m.bundle_index if m.bundle_index >= 0 else None
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=pg,
                placement_group_bundle_index=idx)
        handle = ActorClass(cls, **opts).remote(*self._decode_args(m.args))
        with self._actors_lock:
            self._actors[handle._actor_id] = handle
        reply.create_actor.actor_id = handle._actor_id

    # Per-connection retained-result cap: a long-lived frontend looping
    # CallActor without disconnecting must not pin unbounded results in
    # the store. FIFO eviction — results are overwhelmingly fetched soon
    # after their call; a client coming back for a result more than
    # MAX_CONN_REFS calls later sees it as released (the reference's
    # client server bounds its reference map with client-side releases).
    MAX_CONN_REFS = 4096

    def _actor_call(self, m: pb.ActorCallRequest, reply, refs: dict):
        with self._actors_lock:
            handle = self._actors.get(m.actor_id)
        if handle is None:
            raise KeyError(f"unknown actor {m.actor_id.hex()} (created "
                           f"through this client plane?)")
        ref = getattr(handle, m.method).remote(*self._decode_args(m.args))
        while len(refs) >= self.MAX_CONN_REFS:
            refs.pop(next(iter(refs)))
        refs[ref.id.binary()] = ref  # retained: see _serve
        reply.actor_call.return_id = ref.id.binary()

    def _kill_actor(self, m: pb.KillActorRequest, reply):
        with self._actors_lock:
            handle = self._actors.pop(m.actor_id, None)
        if handle is not None:
            self.rt.kill_actor_by_id(m.actor_id,
                                     no_restart=bool(m.no_restart))
        reply.kill_actor.ok = handle is not None
