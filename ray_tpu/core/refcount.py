"""Owner-side reference counting.

Parity: reference `src/ray/core_worker/reference_count.h:72`. v1 scope: the
owner (driver/head) counts local ObjectRef handles and frees the object from
the directory + shm store when the count hits zero. Borrower counting across
workers is conservative: objects referenced by in-flight tasks are pinned
until the task completes (the dependency manager holds a ref for the task's
lifetime), and shm reads are protected by the store's own per-get refcount,
so a freed-while-reading race cannot corrupt a reader.
"""

from __future__ import annotations

import threading


class ReferenceCounter:
    def __init__(self, free_callback=None):
        self._counts: dict[bytes, int] = {}
        self._pins: dict[bytes, int] = {}   # task-lifetime pins
        self._deferred: set[bytes] = set()  # count hit 0 while pinned
        self._lock = threading.Lock()
        self._free_callback = free_callback

    def add_local_ref(self, object_id):
        key = object_id.binary()
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1

    def remove_local_ref(self, object_id):
        key = object_id.binary()
        free = False
        with self._lock:
            n = self._counts.get(key, 0) - 1
            if n <= 0:
                self._counts.pop(key, None)
                if key in self._pins:
                    # Free is deferred until the last pin drops; objects the
                    # owner never counted (worker-owned) are NOT freed by
                    # unpinning alone.
                    self._deferred.add(key)
                else:
                    free = True
            else:
                self._counts[key] = n
        if free and self._free_callback:
            self._free_callback(key)

    def is_pinned(self, key: bytes) -> bool:
        with self._lock:
            return key in self._pins

    def pin(self, key: bytes):
        with self._lock:
            self._pins[key] = self._pins.get(key, 0) + 1

    def unpin(self, key: bytes):
        free = False
        with self._lock:
            n = self._pins.get(key, 0) - 1
            if n <= 0:
                self._pins.pop(key, None)
                if key in self._deferred:
                    self._deferred.discard(key)
                    free = True
            else:
                self._pins[key] = n
        if free and self._free_callback:
            self._free_callback(key)

    def has_refs(self, key: bytes) -> bool:
        with self._lock:
            return key in self._counts or key in self._pins
