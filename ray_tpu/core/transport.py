"""Framed message transport over unix sockets.

Parity: reference `src/ray/rpc/` (GrpcServer/GrpcClient) — but single-node IPC
here is a length-prefixed pickle frame over a socketpair, which is the latency
floor for Python peers; the multi-node path (ray_tpu.core.cluster) layers the
same frames over TCP. Fault-injection hooks (`testing_rpc_failure`,
`testing_delay_us` config, parity `src/ray/rpc/rpc_chaos.h:23`) live here so
every message path is chaos-testable.
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time

_HDR = struct.Struct("<Q")


class ChaosInjector:
    """Drops or delays messages by op name, per config flags."""

    def __init__(self, failure_spec: str = "", delay_spec: str = ""):
        self._fail: dict[str, int] = {}
        self._delay: dict[str, tuple[float, float]] = {}
        for part in filter(None, failure_spec.split(",")):
            meth, n = part.split("=")
            self._fail[meth] = int(n)
        for part in filter(None, delay_spec.split(",")):
            meth, rng = part.split("=")
            lo, hi = rng.split(":")
            self._delay[meth] = (float(lo) / 1e6, float(hi) / 1e6)

    def maybe_drop(self, op: str) -> bool:
        left = self._fail.get(op)
        if left:
            self._fail[op] = left - 1
            return True
        return False

    def maybe_delay(self, op: str):
        rng = self._delay.get(op)
        if rng:
            time.sleep(random.uniform(*rng))


_chaos: ChaosInjector | None = None


def get_chaos() -> ChaosInjector:
    global _chaos
    if _chaos is None:
        from ray_tpu.core.config import get_config
        cfg = get_config()
        _chaos = ChaosInjector(cfg.testing_rpc_failure, cfg.testing_delay_us)
    return _chaos


def send_msg(sock: socket.socket, msg, lock: threading.Lock | None = None):
    op = msg[0] if isinstance(msg, tuple) and msg else ""
    chaos = get_chaos()
    chaos.maybe_delay(op)
    if chaos.maybe_drop(op):
        return
    payload = pickle.dumps(msg, protocol=5)
    data = _HDR.pack(len(payload)) + payload
    if lock:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def recv_msg(sock: socket.socket):
    """Blocking receive of one frame; returns None on clean EOF."""
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return pickle.loads(payload)


def _recv_exact(sock: socket.socket, n: int):
    chunks = []
    while n:
        try:
            chunk = sock.recv(n)
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class FrameBuffer:
    """Incremental frame decoder for the driver's selector loop."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes):
        self._buf.extend(data)

    def frames(self):
        out = []
        while True:
            if len(self._buf) < _HDR.size:
                break
            (n,) = _HDR.unpack_from(self._buf, 0)
            if len(self._buf) < _HDR.size + n:
                break
            payload = bytes(self._buf[_HDR.size : _HDR.size + n])
            del self._buf[: _HDR.size + n]
            out.append(pickle.loads(payload))
        return out


def make_socketpair():
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    return a, b


def socket_from_fd(fd: int) -> socket.socket:
    return socket.socket(socket.AF_UNIX, socket.SOCK_STREAM, fileno=fd)


def free_tcp_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
